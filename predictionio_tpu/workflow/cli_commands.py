"""CLI subcommands backed by the workflow and tools layers: train, eval,
deploy, undeploy, dashboard, adminserver, export, import, build, run,
upgrade, template.

Parity: tools/.../console/Console.scala build:147/train:177/eval:227/
deploy:255/undeploy:313/dashboard:326/adminserver:354/run:367/upgrade:396/
template:546/export:561/import:578 and commands/Engine.scala:37-318. The
reference spawned `spark-submit` of CreateWorkflow/CreateServer
(Runner.scala:185-307); here training and serving run in-process on the
JAX runtime — there is no assembly jar or process boundary to cross, so
`pio build` reduces to the checks the reference's compile step enforced
(factory resolves, engine.json params bind).
"""

from __future__ import annotations

import argparse
import json
import os

from predictionio_tpu.cli.pio import find_channel, register_command
from predictionio_tpu.workflow.context import WorkflowParams


def _load_variant(path: str) -> dict | None:
    """Parse an engine variant file. {} when the file is absent; None
    (with a printed error) when it exists but is not valid JSON — every
    subcommand gets the same clean diagnostic instead of a traceback."""
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        try:
            return json.load(f)
        except json.JSONDecodeError as exc:
            print(f"[ERROR] {path} is not valid JSON: {exc}")
            return None


def _check_template_min_version(template_json: str = "template.json") -> bool:
    """template.json {"pio": {"version": {"min": "X.Y.Z"}}} gate on
    train/deploy. Parity: Template.verifyTemplateMinVersion
    (tools/.../commands/Template.scala:31-69). Returns False (with an
    error printed) when this framework is older than the template needs."""
    if not os.path.exists(template_json):
        return True
    try:
        with open(template_json) as f:
            spec = json.load(f)
        min_version = spec.get("pio", {}).get("version", {}).get("min")
    except (json.JSONDecodeError, AttributeError):
        print(f"[WARN] {template_json} is malformed; skipping version check.")
        return True
    if not min_version:
        return True
    from predictionio_tpu import __version__

    def vtuple(v):
        return tuple(int(p) for p in str(v).split(".") if p.isdigit())

    if not vtuple(min_version):
        print(f"[WARN] {template_json} min version {min_version!r} is not "
              "a version string; skipping version check.")
        return True
    if vtuple(__version__) < vtuple(min_version):
        print(f"[ERROR] This template requires predictionio_tpu >= {min_version} "
              f"(current: {__version__}).")
        return False
    return True


def _serve(server, label: str, ip: str) -> int:
    """Print the bound address and block until interrupt — shared by every
    server-launching subcommand."""
    print(f"[INFO] {label} listening on {ip}:{server.port}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.stop()
    return 0


# ---------------------------------------------------------------------------
# pio train
# ---------------------------------------------------------------------------

def _configure_train(sub) -> None:
    p = sub.add_parser("train", help="train an engine variant")
    p.add_argument("--engine-json", default="engine.json",
                   help="engine variant file (default: ./engine.json)")
    p.add_argument("--engine-factory", default="",
                   help="override engineFactory from engine.json")
    p.add_argument("--batch", default="", help="batch label")
    p.add_argument("--skip-sanity-check", action="store_true")
    p.add_argument("--stop-after-read", action="store_true")
    p.add_argument("--stop-after-prepare", action="store_true")
    p.add_argument("--no-save-model", action="store_true", dest="no_save_model")
    p.add_argument("--profile", action="store_true",
                   help="profile the run: per-stage wall/compile/execute "
                        "split, MFU, HBM peaks and the recompile table, "
                        "written to TRAIN_REPORT.json (docs/observability.md "
                        "'Device and compiler observability')")
    p.add_argument("--profile-dir", default="",
                   help="with --profile: also dump a jax.profiler trace "
                        "into this directory for deep dives (TensorBoard/"
                        "Perfetto); implies --profile")
    p.add_argument("--profile-out", default="TRAIN_REPORT.json",
                   help="where --profile writes the report "
                        "(default: ./TRAIN_REPORT.json)")


def _cmd_train(args, storage) -> int:
    from predictionio_tpu.workflow.train import run_train

    if not _check_template_min_version():
        return 1
    variant = _load_variant(args.engine_json)
    if variant is None:
        return 1
    if not variant and not args.engine_factory:
        print(f"[ERROR] {args.engine_json} not found and no --engine-factory given.")
        return 1
    wp = WorkflowParams(
        batch=args.batch,
        save_model=not args.no_save_model,
        skip_sanity_check=args.skip_sanity_check,
        stop_after_read=args.stop_after_read,
        stop_after_prepare=args.stop_after_prepare,
    )
    profiler = None
    if args.profile or args.profile_dir:
        from predictionio_tpu.obs.device import TrainProfiler

        profiler = TrainProfiler(profile_dir=args.profile_dir or None)
    outcome = run_train(
        engine_factory=args.engine_factory,
        variant=variant,
        workflow_params=wp,
        storage=storage,
        profiler=profiler,
    )
    print(f"[INFO] Training finished: engine instance {outcome.instance_id} "
          f"({outcome.status})")
    if outcome.stage_seconds:
        from predictionio_tpu.workflow.train import format_stage_times

        # per-DASE-stage walltimes (docs/observability.md): where a
        # slow train actually spent its time
        print(f"[INFO] Stage times: {format_stage_times(outcome.stage_seconds)}")
    if outcome.report is not None:
        import json as _json

        from predictionio_tpu.obs.device import summarize_train_report

        print(f"[INFO] Train profile: {summarize_train_report(outcome.report)}")
        try:
            with open(args.profile_out, "w") as f:
                _json.dump(outcome.report, f, indent=2)
        except OSError as e:
            # the train itself succeeded and the summary already
            # printed — an unwritable report path must not turn a
            # completed (and persisted) run into a failing exit code
            print(f"[WARN] could not write {args.profile_out}: {e}")
        else:
            print(f"[INFO] Train report written to {args.profile_out}")
        if args.profile_dir:
            print(f"[INFO] jax.profiler trace in {args.profile_dir}")
    return 0 if outcome.status in ("COMPLETED", "INTERRUPTED") else 1


# ---------------------------------------------------------------------------
# pio eval
# ---------------------------------------------------------------------------

def _configure_eval(sub) -> None:
    p = sub.add_parser("eval", help="evaluate an engine over a params grid")
    p.add_argument("evaluation", help="Evaluation class spec, e.g. pkg.mod.MyEval")
    p.add_argument("params_generator", nargs="?", default="",
                   help="EngineParamsGenerator class spec (defaults to the "
                        "evaluation module's own generator if omitted)")
    p.add_argument("--batch", default="")
    p.add_argument("--parallel", type=int, default=None, metavar="N",
                   help="fan grid points over N eval worker processes "
                        "(default: PIO_EVAL_PARALLEL or 1 = sequential)")


def _cmd_eval(args, storage) -> int:
    from predictionio_tpu.workflow.evaluation import run_evaluation

    generator = args.params_generator or _default_generator(args.evaluation)
    try:
        outcome = run_evaluation(
            args.evaluation,
            generator,
            workflow_params=WorkflowParams(batch=args.batch),
            storage=storage,
            parallel=args.parallel,
        )
    except Exception as exc:
        # the instance row already says FAILED (workflow/evaluation.py)
        print(f"[ERROR] Evaluation failed: {exc}")
        return 1
    print(f"[INFO] Evaluation finished: instance {outcome.instance_id}")
    print(f"[INFO] {outcome.result.to_one_liner()}")
    return 0


def _default_generator(evaluation_spec: str):
    """When no generator spec is given, look for an EngineParamsGenerator
    subclass/instance in the evaluation's module (the reference required
    both classes; this is a convenience on top)."""
    import importlib

    from predictionio_tpu.controller.evaluation import EngineParamsGenerator
    from predictionio_tpu.utils.reflection import resolve_attr

    evaluation = resolve_attr(evaluation_spec)
    module = importlib.import_module(type(evaluation).__module__
                                     if not isinstance(evaluation, type)
                                     else evaluation.__module__)
    for name in dir(module):
        obj = getattr(module, name)
        if isinstance(obj, EngineParamsGenerator):
            return obj
        if (isinstance(obj, type) and issubclass(obj, EngineParamsGenerator)
                and obj is not EngineParamsGenerator):
            return obj()
    raise ValueError(
        f"no EngineParamsGenerator found in {module.__name__}; "
        "pass one explicitly: pio eval <evaluation> <generator>"
    )


# ---------------------------------------------------------------------------
# pio deploy / undeploy
# ---------------------------------------------------------------------------

def _configure_deploy(sub) -> None:
    p = sub.add_parser("deploy", help="deploy the latest trained engine instance")
    p.add_argument("--ip", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    # prefork worker pool (docs/serving-performance.md "Multi-process
    # serving"): N engine-server processes share one SO_REUSEPORT
    # listen port — the serving plane's escape from the single-process
    # GIL floor. None defers to PIO_SERVING_WORKERS.
    p.add_argument("--workers", type=int, default=None,
                   help="engine-server worker processes sharing the "
                        "listen port via SO_REUSEPORT; /metrics, "
                        "/stats.json and /traces.json report the whole "
                        "pool from any worker, and /reload//drain/"
                        "/retrieval reach every sibling")
    p.add_argument("--supervise", action="store_true",
                   help="own the worker siblings: respawn on death "
                        "with damped backoff, latch crash loops, stop "
                        "the whole pool on SIGTERM (fleet/supervisor)")
    p.add_argument("--model-mmap", action="store_true", dest="model_mmap",
                   help="load npz model checkpoints with mmap so the "
                        "worker processes share one physical copy of "
                        "the factor tables (sets PIO_CHECKPOINT_MMAP=r; "
                        "utils/checkpoint has the verification "
                        "trade-off)")
    p.add_argument("--engine-instance-id", default=None)
    p.add_argument("--engine-json", default="engine.json")
    p.add_argument("--feedback", action="store_true")
    p.add_argument("--event-server-ip", default="0.0.0.0")
    p.add_argument("--event-server-port", type=int, default=7070)
    p.add_argument("--accesskey", default="", help="access key for feedback events")
    p.add_argument("--server-key", default=None,
                   help="when set, /stop and /reload require this key")
    # serving knobs default to None so an absent flag falls through to
    # ServerConfig's PIO_SERVING_* env-aware defaults instead of
    # re-hard-coding them here; the boolean pairs (--batching /
    # --no-batching) exist so the CLI can force either state over a
    # fleet-wide env setting (docs/serving-performance.md)
    p.add_argument("--batching", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="coalesce concurrent queries into one device "
                        "dispatch (micro-batching; the adaptive policy "
                        "waits near-zero when idle)")
    p.add_argument("--batch-policy", choices=("adaptive", "fixed"),
                   default=None)
    p.add_argument("--batch-max", type=int, default=None)
    p.add_argument("--batch-wait-ms", type=float, default=None,
                   help="adaptive: wait cap; fixed: the constant window")
    p.add_argument("--cache", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="LRU+TTL result cache over canonical query "
                        "JSON, invalidated on /reload")
    p.add_argument("--cache-max-entries", type=int, default=None)
    p.add_argument("--cache-ttl-s", type=float, default=None)
    p.add_argument("--shm-cache", action=argparse.BooleanOptionalAction,
                   default=None, dest="shm_cache",
                   help="back the result cache with ONE shared-memory "
                        "segment all --workers siblings attach (a key "
                        "warmed by any worker is hot pool-wide; "
                        "serving/shm_cache). Implies --cache; falls "
                        "back to the private LRU where the platform "
                        "lacks shm")
    p.add_argument("--shm-slots", type=int, default=None,
                   dest="shm_slots",
                   help="slot count of the shared cache table "
                        "(PIO_SERVING_SHM_SLOTS)")
    p.add_argument("--shm-slot-bytes", type=int, default=None,
                   dest="shm_slot_bytes",
                   help="bytes per shared-cache slot "
                        "(PIO_SERVING_SHM_SLOT_BYTES)")
    # sublinear retrieval (ops/ann; docs/serving-performance.md):
    # None defers to the PIO_SERVING_ANN_* env-aware ServerConfig
    # defaults, matching the other serving knobs
    p.add_argument("--retrieval", choices=("brute", "ann"), default=None,
                   help="'ann' probes the IVF-flat MIPS index persisted "
                        "beside the model (built at deploy when missing) "
                        "and exact-rescores the shortlist; 'brute' "
                        "scores the full item table per query")
    p.add_argument("--ann-nlist", type=int, default=None, dest="ann_nlist",
                   help="IVF cell count for a deploy-time index build "
                        "(0 = auto ~4*sqrt(catalog))")
    p.add_argument("--ann-nprobe", type=int, default=None,
                   dest="ann_nprobe",
                   help="cells probed per query (0 = auto nlist/64, "
                        "floored at 16); higher = better recall, more "
                        "rescore work")
    p.add_argument("--ann-rescore", type=int, default=None,
                   dest="ann_rescore",
                   help="cap on shortlist candidates exact-rescored per "
                        "query (0 = all probed candidates)")
    # real-time freshness plane (online/; docs/freshness.md): None
    # defers to the PIO_ONLINE_* env-aware ServerConfig defaults
    p.add_argument("--online", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="fold new events into the deployed ALS model "
                        "between retrains: tail the event store and "
                        "recompute touched users' vectors closed-form "
                        "— event→recommendation freshness in seconds, "
                        "no retrain, no restart")
    p.add_argument("--online-interval-s", type=float, default=None,
                   dest="online_interval_s",
                   help="tail polling interval (the freshness lag "
                        "floor; default 1.0)")
    p.add_argument("--online-overlay-max", type=int, default=None,
                   dest="online_overlay_max",
                   help="max folded users held in the serving overlay "
                        "(LRU; evicted users fall back to their base "
                        "vector until the next retrain)")
    p.add_argument("--online-state-dir", default=None,
                   dest="online_state_dir",
                   help="directory for the durable tail cursor "
                        "(restart resumes exactly-once; default: "
                        "in-memory, re-tails from deploy time)")
    # observability (docs/observability.md): None defers to the
    # PIO_TRACE / PIO_ACCESS_LOG env vars; the boolean pairs let the
    # CLI force either state over a fleet-wide env setting
    p.add_argument("--tracing", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="per-request span collection for /queries.json "
                        "(served on GET /traces.json)")
    p.add_argument("--access-log", action=argparse.BooleanOptionalAction,
                   default=None, dest="access_log",
                   help="structured JSON access logs (method, path, "
                        "status, latency_ms, request_id)")


def _deploy_worker(config) -> None:
    """One extra `pio deploy --workers N` sibling process: a full
    engine server on the shared SO_REUSEPORT port, with its OWN storage
    connection and model replica (mmap-share the factor tables via
    --model-mmap / PIO_CHECKPOINT_MMAP=r)."""
    from predictionio_tpu.api.engine_server import create_engine_server
    from predictionio_tpu.serving.placement import apply_worker_affinity
    from predictionio_tpu.storage.registry import Storage

    # before the model loads, so its pages fault in on the pinned
    # cores; a respawn re-applies (the index rides the config, and the
    # stripe is carved from the CLI's pre-pin CPU snapshot — a respawn
    # inherits the PINNED parent's mask, which must not narrow ours)
    apply_worker_affinity(config.worker_index, max(1, config.workers),
                          cpus=config.cpu_allowlist)
    server = create_engine_server(storage=Storage.default(), config=config)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.stop()


def _cmd_deploy(args, storage) -> int:
    import dataclasses

    from predictionio_tpu.api.engine_server import create_engine_server
    from predictionio_tpu.workflow.deploy import ServerConfig

    if not _check_template_min_version():
        return 1
    variant = _load_variant(args.engine_json)
    if variant is None:
        return 1
    if args.model_mmap:
        # before any model load, and inherited by every worker spawn:
        # N processes map the same checkpoint pages instead of holding
        # N heap copies (utils/checkpoint module docstring)
        os.environ["PIO_CHECKPOINT_MMAP"] = "r"
    config = ServerConfig(
        ip=args.ip,
        port=args.port,
        engine_instance_id=args.engine_instance_id,
        engine_id=variant.get("id"),
        engine_version=variant.get("version"),
        engine_variant=variant.get("variantId"),
        feedback=args.feedback,
        event_server_ip=args.event_server_ip,
        event_server_port=args.event_server_port,
        access_key=args.accesskey,
        server_key=args.server_key,
        **{k: v for k, v in {
            "batching": args.batching,
            "batch_policy": args.batch_policy,
            "batch_max": args.batch_max,
            "batch_wait_ms": args.batch_wait_ms,
            # --shm-cache without --cache means "cache, shared": the
            # shm flag implies the cache it backs
            "cache_enabled": (True if (args.cache is None
                                       and args.shm_cache)
                              else args.cache),
            "cache_max_entries": args.cache_max_entries,
            "cache_ttl_s": args.cache_ttl_s,
            "shm_cache": args.shm_cache,
            "shm_slots": args.shm_slots,
            "shm_slot_bytes": args.shm_slot_bytes,
            "retrieval": args.retrieval,
            "ann_nlist": args.ann_nlist,
            "ann_nprobe": args.ann_nprobe,
            "ann_rescore": args.ann_rescore,
            "tracing": args.tracing,
            "access_log": args.access_log,
            "workers": args.workers,
            "online": args.online,
            "online_interval_s": args.online_interval_s,
            "online_overlay_max": args.online_overlay_max,
            "online_state_dir": args.online_state_dir,
        }.items() if v is not None},
    )
    workers = max(1, config.workers)
    if workers == 1:
        if args.supervise:
            # nothing to supervise: the supervisor owns worker
            # SIBLINGS, and a 1-worker deploy is just this process —
            # say so instead of silently dropping the flag
            print("[WARN] --supervise has no effect with --workers 1 "
                  "(it respawns worker siblings); use an external "
                  "supervisor for a single process.")
        server = create_engine_server(storage=storage, config=config)
        return _serve(
            server,
            f"Engine instance {server.service.deployed.instance.id}",
            args.ip,
        )

    # prefork pool: N-1 sibling processes + this one share the
    # SO_REUSEPORT port; the spool carries peering + shared admin state
    # (docs/serving-performance.md "Multi-process serving")
    import multiprocessing
    import shutil
    import signal
    import tempfile

    from predictionio_tpu.cli.pio import resolve_concrete_port

    config = dataclasses.replace(
        config,
        port=resolve_concrete_port(config.ip, config.port),
        reuse_port=True,
        worker_spool_dir=tempfile.mkdtemp(prefix="pio-deploy-workers-"))

    # ONE shared-memory cache segment for the whole pool: the parent
    # creates and owns it (unlinked in the teardown below), workers
    # attach by name. Creation failure degrades the pool to private
    # per-worker LRUs — same serving semantics, worker-local warmth.
    shm_owner = None
    if config.shm_cache and config.cache_enabled and not config.shm_segment:
        from predictionio_tpu.serving.shm_cache import ShmResultCache

        segment = f"pio-shm-{os.getpid()}"
        try:
            shm_owner = ShmResultCache(
                segment, nslots=config.shm_slots,
                slot_bytes=config.shm_slot_bytes,
                ttl_s=config.cache_ttl_s, create="create")
            config = dataclasses.replace(config, shm_segment=segment)
        except Exception as exc:
            print(f"[WARN] shared-memory cache unavailable "
                  f"({type(exc).__name__}: {exc}); workers fall back "
                  f"to private result caches")
            config = dataclasses.replace(config, shm_cache=False)

    # capture the pool's allowed-CPU set BEFORE the parent pins itself
    # to stripe 0: a supervisor respawn happens after that pin, and the
    # child would inherit (and carve from) the parent's one-stripe
    # mask — every respawn piling onto worker 0's cores is the exact
    # opposite of the placement intent
    from predictionio_tpu.serving.placement import apply_worker_affinity

    getaffinity = getattr(os, "sched_getaffinity", None)
    try:
        allowed_cpus = (tuple(sorted(getaffinity(0)))
                        if getaffinity is not None else None)
    except OSError:
        allowed_cpus = None
    config = dataclasses.replace(config, cpu_allowlist=allowed_cpus)

    def sibling(index: int):
        return multiprocessing.Process(
            target=_deploy_worker,
            args=(dataclasses.replace(config, worker_index=index),),
            daemon=True)

    # SIGTERM's default action would kill this parent without running
    # any finally, orphaning the SO_REUSEPORT siblings on the shared
    # port; route it through KeyboardInterrupt (the `pio router`
    # discipline) BEFORE the first sibling spawns — a stop landing
    # mid-model-load must tear the pool down too, so everything from
    # the spawns on runs inside the cleanup try
    def _on_sigterm(signum, frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _on_sigterm)
    supervisor = None
    worker_procs: list = []
    server = None
    try:
        if args.supervise:
            from predictionio_tpu.fleet.supervisor import (
                WORKER,
                FleetSupervisor,
                ProcessHandle,
                SpawnSpec,
            )

            supervisor = FleetSupervisor([
                SpawnSpec(id=f"worker:{i}",
                          spawn=lambda i=i: ProcessHandle(sibling(i)),
                          role=WORKER)
                for i in range(1, workers)
            ])
            supervisor.start()
        else:
            for i in range(1, workers):
                proc = sibling(i)
                proc.start()
                worker_procs.append(proc)
        # the parent is worker 0 of the pool: pin it to its own stripe
        # (carved from the same pre-pin snapshot the workers use)
        apply_worker_affinity(0, workers, cpus=config.cpu_allowlist)
        server = create_engine_server(storage=storage, config=config)
        print(f"[INFO] Engine instance "
              f"{server.service.deployed.instance.id} listening on "
              f"{args.ip}:{server.port} ({workers} worker(s)"
              + (", supervised" if supervisor is not None else "") + ")")
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if supervisor is not None:
            supervisor.shutdown()
        if server is not None:
            server.stop()
        for proc in worker_procs:
            proc.terminate()
        for proc in worker_procs:
            proc.join(timeout=5)
        # terminate() is SIGTERM: siblings die without running
        # WorkerHub.close, leaving spool entries behind — the parent
        # mkdtemp'd the dir, the parent removes it
        shutil.rmtree(config.worker_spool_dir, ignore_errors=True)
        if shm_owner is not None:
            # same ownership story as the spool: the parent created
            # the segment, the parent unlinks it
            shm_owner.close(unlink=True)
    return 0


def _configure_undeploy(sub) -> None:
    p = sub.add_parser("undeploy", help="stop a deployed engine server")
    p.add_argument("--ip", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--server-key", default=None)


def _cmd_undeploy(args, storage) -> int:
    from predictionio_tpu.api.engine_server import undeploy

    if undeploy(args.ip, args.port, args.server_key):
        print(f"[INFO] Undeployed engine server at {args.ip}:{args.port}")
        return 0
    print(f"[ERROR] No engine server running at {args.ip}:{args.port}")
    return 1


# ---------------------------------------------------------------------------
# pio dashboard / adminserver
# ---------------------------------------------------------------------------

def _configure_dashboard(sub) -> None:
    p = sub.add_parser("dashboard", help="launch the evaluation dashboard")
    p.add_argument("--ip", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9000)
    p.add_argument("--access-log", action=argparse.BooleanOptionalAction,
                   default=None, dest="access_log",
                   help="structured JSON access logs "
                        "(docs/observability.md)")


def _cmd_dashboard(args, storage) -> int:
    from predictionio_tpu.tools.dashboard import Dashboard

    return _serve(Dashboard(storage, ip=args.ip, port=args.port,
                            access_log=args.access_log),
                  "Dashboard", args.ip)


def _configure_adminserver(sub) -> None:
    p = sub.add_parser("adminserver", help="launch the admin REST API")
    p.add_argument("--ip", default="0.0.0.0")
    p.add_argument("--port", type=int, default=7071)


def _cmd_adminserver(args, storage) -> int:
    from predictionio_tpu.tools.admin import AdminServer

    return _serve(AdminServer(storage, ip=args.ip, port=args.port),
                  "Admin API", args.ip)


# ---------------------------------------------------------------------------
# pio export / import
# ---------------------------------------------------------------------------

def _configure_export(sub) -> None:
    p = sub.add_parser(
        "export", help="export an app's events to a JSON-lines or Parquet file"
    )
    p.add_argument("--appid", type=int, required=True)
    p.add_argument("--output", required=True)
    p.add_argument("--channel", default=None)
    # EventsToFile.scala:97-105 format option
    p.add_argument("--format", choices=("json", "parquet"), default="json")


def _resolve_app_channel(storage, app_id: int, channel_name: str | None):
    """Validate --appid refers to a real app (unlike raw DAO access, which
    would silently auto-init an orphan event table) and resolve --channel.
    Returns (ok, channel_id)."""
    if storage.get_meta_data_apps().get(app_id) is None:
        print(f"[ERROR] App id {app_id} does not exist.")
        return False, None
    if channel_name is None:
        return True, None
    chan = find_channel(storage, app_id, channel_name)
    if chan is None:
        print(f"[ERROR] Channel {channel_name} does not exist.")
        return False, None
    return True, chan.id


def _cmd_export(args, storage) -> int:
    from predictionio_tpu.tools.export_import import (
        export_events,
        export_events_parquet,
    )

    ok, channel_id = _resolve_app_channel(storage, args.appid, args.channel)
    if not ok:
        return 1
    if getattr(args, "format", "json") == "parquet":
        try:
            n = export_events_parquet(storage, args.appid, args.output, channel_id)
        except ImportError:
            print("[ERROR] Parquet support requires pyarrow "
                  "(pip install 'predictionio-tpu[parquet]').")
            return 1
    else:
        with open(args.output, "w") as f:
            n = export_events(storage, args.appid, f, channel_id)
    print(f"[INFO] Exported {n} events to {args.output}")
    return 0


def _configure_import(sub) -> None:
    p = sub.add_parser(
        "import", help="import events from a JSON-lines or Parquet file"
    )
    p.add_argument("--appid", type=int, required=True)
    p.add_argument("--input", required=True)
    p.add_argument("--channel", default=None)
    p.add_argument("--format", choices=("json", "parquet"), default=None,
                   help="default: parquet for .parquet files, else json")


def _cmd_import(args, storage) -> int:
    from predictionio_tpu.tools.export_import import (
        ImportFormatError,
        import_events,
        import_events_parquet,
    )

    ok, channel_id = _resolve_app_channel(storage, args.appid, args.channel)
    if not ok:
        return 1
    if not os.path.exists(args.input):
        print(f"[ERROR] {args.input} not found.")
        return 1
    fmt = getattr(args, "format", None) or (
        "parquet" if args.input.endswith(".parquet") else "json"
    )
    try:
        if fmt == "parquet":
            n = import_events_parquet(storage, args.appid, args.input, channel_id)
        else:
            with open(args.input) as f:
                n = import_events(storage, args.appid, f, channel_id)
    except ImportFormatError as e:
        print(f"[ERROR] {args.input}: {e}")
        return 1
    except ImportError:
        print("[ERROR] Parquet support requires pyarrow "
              "(pip install 'predictionio-tpu[parquet]').")
        return 1
    print(f"[INFO] Imported {n} events from {args.input}")
    return 0


# ---------------------------------------------------------------------------
# pio build / run / upgrade / template
# ---------------------------------------------------------------------------

def _configure_build(sub) -> None:
    p = sub.add_parser("build", help="verify an engine variant is runnable")
    p.add_argument("--engine-json", default="engine.json",
                   help="engine variant file (default: ./engine.json)")
    p.add_argument("--engine-factory", default="",
                   help="override engineFactory from engine.json")


def _cmd_build(args, storage) -> int:
    """Verify the engine variant: template version gate + engineFactory
    import + instantiation. Parity: commands/Engine.scala build:65-163 —
    the reference generated pio.sbt and ran sbt package/assembly; Python
    engines import directly, so "build" reduces to the same checks the
    reference's compile step enforced (factory resolves, params bind)."""
    from predictionio_tpu.controller.engine import resolve_engine_factory

    if not _check_template_min_version():
        return 1
    variant = _load_variant(args.engine_json)
    if variant is None:
        return 1
    factory_path = args.engine_factory or variant.get("engineFactory", "")
    if not factory_path:
        if os.path.exists(args.engine_json):
            print(f"[ERROR] {args.engine_json} has no engineFactory and "
                  "no --engine-factory given.")
        else:
            print(f"[ERROR] {args.engine_json} not found and no "
                  "--engine-factory given.")
        return 1
    try:
        factory = resolve_engine_factory(factory_path)
        engine = factory()
    except Exception as exc:
        print(f"[ERROR] engineFactory {factory_path!r} failed: {exc}")
        return 1
    try:
        engine.params_from_variant_json(variant)
    except Exception as exc:
        print(f"[ERROR] engine.json params do not bind: {exc}")
        return 1
    print(f"[INFO] Build successful: {factory_path} "
          f"({type(engine).__name__}) binds {args.engine_json}.")
    return 0


def _configure_run(sub) -> None:
    p = sub.add_parser(
        "run", help="run an arbitrary main function with storage wired up")
    p.add_argument("main", help="dotted path module[:function] (default function: main)")
    import argparse

    p.add_argument("args", nargs=argparse.REMAINDER,
                   help="arguments passed through verbatim")


def _cmd_run(args, storage) -> int:
    """Launch an arbitrary user main with the PIO environment prepared.
    Parity: commands/Engine.scala run:278 (spark-submit of a user class);
    here the user names ``pkg.module[:function]`` and it runs in-process
    with storage initialised."""
    import importlib

    target = args.main
    mod_name, _, fn_name = target.partition(":")
    fn_name = fn_name or "main"
    try:
        module = importlib.import_module(mod_name)
        fn = getattr(module, fn_name)
    except (ImportError, AttributeError) as exc:
        print(f"[ERROR] cannot resolve {target!r}: {exc}")
        return 1
    result = fn(*args.args)
    # bool subclasses int; a main returning True means success, not rc=1
    if isinstance(result, bool):
        return 0 if result else 1
    return int(result) if isinstance(result, int) else 0


def _configure_upgrade(sub) -> None:
    sub.add_parser("upgrade", help="(no longer supported)")


def _cmd_upgrade(args, storage) -> int:
    # Parity: Console.scala:664-666 — upgrade is a hard error upstream too.
    print("[ERROR] Upgrade is no longer supported")
    return 1


def _configure_template(sub) -> None:
    p = sub.add_parser("template", help="(no longer supported; use git)")
    p.add_argument("subcommand", nargs="*")


def _cmd_template(args, storage) -> int:
    # Parity: Console.scala:691-694 — template gallery was retired upstream;
    # engine templates ship in predictionio_tpu.templates instead.
    print("[ERROR] template commands are no longer supported.")
    print("[ERROR] Built-in engine templates live in predictionio_tpu.templates "
          "(recommendation, similarproduct, ecommerce, classification).")
    return 1


register_command("train", _configure_train, _cmd_train)
register_command("eval", _configure_eval, _cmd_eval)
register_command("deploy", _configure_deploy, _cmd_deploy)
register_command("undeploy", _configure_undeploy, _cmd_undeploy)
register_command("dashboard", _configure_dashboard, _cmd_dashboard)
register_command("adminserver", _configure_adminserver, _cmd_adminserver)
register_command("export", _configure_export, _cmd_export)
register_command("import", _configure_import, _cmd_import)
register_command("build", _configure_build, _cmd_build)
register_command("run", _configure_run, _cmd_run)
register_command("upgrade", _configure_upgrade, _cmd_upgrade)
register_command("template", _configure_template, _cmd_template)

# `pio experiment` registers itself on import, same extension point
import predictionio_tpu.experiment.cli  # noqa: E402,F401
