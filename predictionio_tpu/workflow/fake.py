"""FakeWorkflow — run arbitrary code through the evaluation plumbing.

Parity: core/src/main/scala/.../workflow/FakeWorkflow.scala:30-109
(`pio eval HelloWorld` style): wrap a ``ctx -> None`` function in a fake
engine/evaluator pair so it executes with the full workflow context
(storage wired, mesh available, EvaluationInstance recorded) without
defining a real DASE engine.

Usage::

    class MyRun(FakeRun):
        def __init__(self):
            super().__init__(lambda ctx: print(ctx.mesh))

    # pio eval my_module.MyRun my_module.FakeEngineParamsGenerator
"""

from __future__ import annotations

from typing import Callable, Sequence, TYPE_CHECKING

from predictionio_tpu.controller.engine import Engine
from predictionio_tpu.controller.evaluation import (
    BaseEvaluator,
    BaseEvaluatorResult,
    EngineParamsGenerator,
    Evaluation,
)
from predictionio_tpu.controller.params import EngineParams

if TYPE_CHECKING:
    from predictionio_tpu.workflow.context import EngineContext


class FakeEvalResult(BaseEvaluatorResult):
    """Parity: FakeEvalResult (FakeWorkflow.scala:71-77) — noSave, so the
    workflow records nothing beyond the run itself."""

    no_save = True

    def to_one_liner(self) -> str:
        return "FakeRun completed"


class _FakeEngine(Engine):
    """Skips the DASE pipeline entirely; batch_eval invokes the function
    (FakeWorkflow.scala FakeEngine:33-55 + FakeRunner:57-69)."""

    def __init__(self, fn: Callable[["EngineContext"], None]):
        super().__init__({}, {}, {}, {})
        self._fn = fn

    def batch_eval(self, ctx, engine_params_list: Sequence[EngineParams]):
        self._fn(ctx)
        return [(ep, []) for ep in engine_params_list]


class _FakeEvaluator(BaseEvaluator):
    def evaluate(self, ctx, evaluation, engine_eval_data_set):
        return FakeEvalResult()


class FakeRun(Evaluation):
    """Parity: FakeRun (FakeWorkflow.scala:96-109)."""

    def __init__(self, fn: Callable[["EngineContext"], None]):
        super().__init__()
        self.engine_evaluator = (_FakeEngine(fn), _FakeEvaluator())


class FakeEngineParamsGenerator(EngineParamsGenerator):
    """A single empty grid point — all a FakeRun needs."""

    def __init__(self):
        super().__init__([EngineParams()])
