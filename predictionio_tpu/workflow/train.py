"""The training workflow driver.

Parity: core/src/main/scala/.../workflow/{CreateWorkflow.scala:136-277,
CoreWorkflow.scala:39-101}: resolve the engine factory, bind engine.json
variant params, record an INIT EngineInstance, run the train pipeline,
persist models, mark COMPLETED (or leave non-COMPLETED on failure —
SURVEY.md §5 failure-detection note).

No spark-submit process boundary exists: training runs in-process on the
JAX mesh. The CLI still offers subprocess isolation (`pio train` spawns a
worker when --isolated) without changing this driver.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import traceback
from datetime import datetime, timezone
from typing import Any, Mapping

from predictionio_tpu.controller.engine import (
    Engine,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
    resolve_engine_factory,
)
from predictionio_tpu.controller.params import EngineParams, params_to_json
from predictionio_tpu.obs.trace import Trace, span, use_trace
from predictionio_tpu.storage.base import EngineInstance
from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.workflow.context import EngineContext, WorkflowParams
from predictionio_tpu.workflow.persistence import save_models

logger = logging.getLogger(__name__)


def format_stage_times(stage_seconds: Mapping[str, float]) -> str:
    """One-line stage breakdown for logs and the `pio train` output,
    e.g. ``read 0.52s | prepare 0.11s | train 8.43s | persist 0.04s``."""
    return " | ".join(f"{name} {secs:.2f}s"
                      for name, secs in stage_seconds.items())


def _now() -> datetime:
    return datetime.now(timezone.utc)


def _params_json(name_params: tuple[str, Any]) -> str:
    name, params = name_params
    return json.dumps({"name": name, "params": params_to_json(params)})


def _algo_params_json(algorithm_params_list) -> str:
    return json.dumps(
        [{"name": n, "params": params_to_json(p)} for n, p in algorithm_params_list]
    )


@dataclasses.dataclass
class TrainOutcome:
    instance_id: str
    status: str
    models: list[Any]
    #: per-DASE-stage walltimes (read/prepare/train/persist seconds),
    #: collected by the training trace (docs/observability.md)
    stage_seconds: dict[str, float] = dataclasses.field(default_factory=dict)
    #: the TRAIN_REPORT document when the run was profiled
    #: (``pio train --profile``; obs/device.TrainProfiler) — per-stage
    #: wall/compile/execute split, MFU, HBM peaks, recompile table
    report: dict[str, Any] | None = None


def run_train(
    engine: Engine | None = None,
    engine_factory: str = "",
    variant: Mapping[str, Any] | None = None,
    engine_params: EngineParams | None = None,
    workflow_params: WorkflowParams = WorkflowParams(),
    storage: Storage | None = None,
    ctx: EngineContext | None = None,
    profiler: Any | None = None,
) -> TrainOutcome:
    """Train one engine variant and persist the results.

    Either pass a constructed ``engine`` (tests, programmatic use) or an
    ``engine_factory`` spec string (CLI path). ``variant`` is the parsed
    engine.json; ``engine_params`` overrides it when given.

    ``profiler`` (an :class:`~predictionio_tpu.obs.device.TrainProfiler`,
    `pio train --profile`) binds to the training trace before the run
    and its report lands on ``TrainOutcome.report``; it is always
    closed, so an interrupted or failed run cannot leak a running
    ``jax.profiler`` capture.
    """
    storage = storage or Storage.default()
    variant = dict(variant or {})
    if engine is None:
        if not engine_factory:
            engine_factory = variant.get("engineFactory", "")
        if not engine_factory:
            raise ValueError("run_train needs an engine or an engineFactory spec")
        engine = resolve_engine_factory(engine_factory)()
    if engine_params is None:
        engine_params = engine.params_from_variant_json(variant)
    ctx = ctx or EngineContext(workflow_params=workflow_params, storage=storage)

    instances = storage.get_meta_data_engine_instances()
    instance = EngineInstance(
        id="",
        status="INIT",
        start_time=_now(),
        completion_time=_now(),
        engine_id=variant.get("id", "default"),
        engine_version=variant.get("version", "1"),
        engine_variant=variant.get("variantId", variant.get("id", "default")),
        engine_factory=engine_factory or f"{type(engine).__module__}.{type(engine).__qualname__}",
        batch=workflow_params.batch,
        env={},
        mesh_conf=dict(workflow_params.mesh_conf),
        data_source_params=_params_json(engine_params.data_source_params),
        preparator_params=_params_json(engine_params.preparator_params),
        algorithms_params=_algo_params_json(engine_params.algorithm_params_list),
        serving_params=_params_json(engine_params.serving_params),
    )
    instance_id = instances.insert(instance)
    logger.info("engine instance %s: INIT", instance_id)
    ctx = ctx.with_workflow_params(engine_instance_id=instance_id)

    # the training trace is ALWAYS collected (a handful of spans per
    # run — noise next to any real train): Engine.train records the
    # read/prepare/train stages against the ambient binding, persist is
    # timed here, and `pio train` prints the breakdown
    trace = Trace("train", request_id=instance_id)
    if profiler is not None:
        profiler.begin(trace)
    try:
        try:
            with use_trace(trace):
                result = engine.train(ctx, engine_params)
        except (StopAfterReadInterruption, StopAfterPrepareInterruption) as stop:
            # deliberate debug early-exit, not a failure
            # (reference: CreateWorkflow catches these cleanly)
            interrupted = dataclasses.replace(
                instances.get(instance_id), status="INTERRUPTED", completion_time=_now()
            )
            instances.update(interrupted)
            logger.info("engine instance %s: INTERRUPTED (%s)", instance_id, stop)
            report = (profiler.finish(trace, instance_id, "INTERRUPTED")
                      if profiler is not None else None)
            return TrainOutcome(instance_id, "INTERRUPTED", [],
                                trace.stage_seconds(), report=report)
        with use_trace(trace), span("persist"):
            save_models(storage, instance_id, result.persisted)
        completed = dataclasses.replace(
            instances.get(instance_id),
            status="COMPLETED",
            completion_time=_now(),
        )
        instances.update(completed)
        stage_seconds = trace.stage_seconds()
        logger.info("engine instance %s: COMPLETED (%s)", instance_id,
                    format_stage_times(stage_seconds))
        report = (profiler.finish(trace, instance_id, "COMPLETED")
                  if profiler is not None else None)
        return TrainOutcome(instance_id, "COMPLETED", result.models,
                            stage_seconds, report=report)
    except Exception:
        # training failures leave the instance non-COMPLETED
        # (CoreWorkflow.scala:68-73 only updates on success)
        failed = dataclasses.replace(
            instances.get(instance_id), status="FAILED", completion_time=_now()
        )
        instances.update(failed)
        logger.error("engine instance %s: FAILED\n%s", instance_id, traceback.format_exc())
        raise
    finally:
        if profiler is not None:
            # idempotent: stops a still-running jax.profiler capture on
            # the failure path (finish already ran on success)
            profiler.finish(trace, instance_id, "FAILED")
