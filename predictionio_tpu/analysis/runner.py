"""Run the registered rules over a file tree and collect findings.

The runner owns everything rule-independent: file discovery, parsing,
path scoping, suppression filtering, and report formatting. Rules see
one :class:`ModuleInfo` at a time and never touch the filesystem.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Iterable

from predictionio_tpu.analysis.config import LintConfig, default_config, path_matches
from predictionio_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    suppression_findings,
)


def _iter_py_files(path: str) -> Iterable[str]:
    if not os.path.exists(path):
        # a typo'd CI hook must fail loudly, not lint zero files "clean"
        raise FileNotFoundError(f"lint path does not exist: {path}")
    if os.path.isfile(path):
        yield path
        return
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames.sort()
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                yield os.path.join(dirpath, fname)


def lint_paths(
    paths: Iterable[str],
    config: LintConfig | None = None,
    rel_root: str | None = None,
    rule_ids: Iterable[str] | None = None,
) -> list[Finding]:
    """Lint ``paths`` (files or trees), scoping rules by path relative
    to ``rel_root`` (default: each argument itself). ``rule_ids``
    restricts the run to a subset of enabled rules."""
    config = config or default_config()
    rules = config.enabled_rules()
    if rule_ids is not None:
        wanted = set(rule_ids)
        unknown = wanted - set(rules)
        if unknown:
            raise KeyError(f"unknown/disabled rule(s): {sorted(unknown)}")
        rules = {rid: r for rid, r in rules.items() if rid in wanted}

    findings: list[Finding] = []
    seen_files: set[str] = set()
    for top in paths:
        base = rel_root or (top if os.path.isdir(top) else os.path.dirname(top))
        for fpath in _iter_py_files(top):
            real = os.path.realpath(fpath)
            if real in seen_files:
                continue  # overlapping path args must not double-report
            seen_files.add(real)
            relpath = os.path.relpath(fpath, base).replace(os.sep, "/")
            if path_matches(relpath, config.exclude):
                continue
            try:
                with open(fpath, encoding="utf-8") as f:
                    source = f.read()
                tree = ast.parse(source, filename=fpath)
            except (SyntaxError, UnicodeDecodeError) as exc:
                findings.append(Finding(
                    "parse-error", relpath,
                    getattr(exc, "lineno", 0) or 0,
                    f"could not parse: {exc}",
                ))
                continue
            module = ModuleInfo(fpath, source, tree, relpath=relpath)
            findings.extend(suppression_findings(module, relpath))
            for rule in rules.values():
                if not path_matches(relpath, config.rule_paths(rule)):
                    continue
                raw = rule.check(module, config.rule_options(rule))
                waived = module.suppressed_lines(rule.rule_id)
                findings.extend(
                    Finding(rule.rule_id, relpath, f.line, f.message, f.col)
                    for f in raw
                    if f.line not in waived
                )
    findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return findings


def lint_package(
    package_dir: str | None = None,
    config: LintConfig | None = None,
    rule_ids: Iterable[str] | None = None,
) -> list[Finding]:
    """Lint the installed ``predictionio_tpu`` package with the repo
    policy — what `pio lint` and the tier-1 gate run."""
    if package_dir is None:
        import predictionio_tpu

        package_dir = os.path.dirname(predictionio_tpu.__file__)
    return lint_paths([package_dir], config=config, rel_root=package_dir,
                      rule_ids=rule_ids)


def format_findings(findings: list[Finding], fmt: str = "text") -> str:
    if fmt == "json":
        return json.dumps(
            [
                {
                    "rule": f.rule_id,
                    "path": f.path,
                    "line": f.line,
                    "col": f.col,
                    "message": f.message,
                }
                for f in findings
            ],
            indent=2,
        )
    out = [f.format() for f in findings]
    n = len(findings)
    out.append(f"{n} finding{'s' if n != 1 else ''}" if n else "clean: no findings")
    return "\n".join(out)
