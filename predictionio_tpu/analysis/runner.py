"""Run the registered rules over a file tree and collect findings.

The runner owns everything rule-independent: file discovery, parsing,
per-file result caching, path scoping, suppression filtering, the
two-phase schedule (per-module rules, then project rules over one
shared :class:`ProjectModel`), and report formatting. Rules see one
:class:`ModuleInfo` — or the whole ProjectModel — and never touch the
filesystem.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import time
from typing import Iterable

from predictionio_tpu.analysis.cache import LintCache
from predictionio_tpu.analysis.config import LintConfig, default_config, path_matches
from predictionio_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    ProjectRule,
    suppression_findings,
)


def _iter_py_files(path: str) -> Iterable[str]:
    if not os.path.exists(path):
        # a typo'd CI hook must fail loudly, not lint zero files "clean"
        raise FileNotFoundError(f"lint path does not exist: {path}")
    if os.path.isfile(path):
        yield path
        return
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames.sort()
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                yield os.path.join(dirpath, fname)


@dataclasses.dataclass
class LintStats:
    """Machine-readable run report (`--format json` carries it)."""

    files: int = 0
    cache_hits: int = 0
    cache_misses: int = 0
    parse_s: float = 0.0
    module_rules_s: float = 0.0
    project_rules_s: float = 0.0
    total_s: float = 0.0
    #: project-phase rules that actually ran
    project_rules: list[str] = dataclasses.field(default_factory=list)
    module_rules: list[str] = dataclasses.field(default_factory=list)
    changed_scope: list[str] | None = None

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        for k in ("parse_s", "module_rules_s", "project_rules_s", "total_s"):
            d[k] = round(d[k], 4)
        return d


def lint_paths(
    paths: Iterable[str],
    config: LintConfig | None = None,
    rel_root: str | None = None,
    rule_ids: Iterable[str] | None = None,
    cache: LintCache | None = None,
    project: bool = True,
    changed: set[str] | None = None,
) -> list[Finding]:
    """Lint ``paths`` (files or trees), scoping rules by path relative
    to ``rel_root`` (default: each argument itself). ``rule_ids``
    restricts the run to a subset of enabled rules; ``changed``
    restricts *reported* findings to those package-relative paths (the
    whole tree is still parsed so project passes see every module)."""
    findings, _ = lint_paths_report(
        paths, config=config, rel_root=rel_root, rule_ids=rule_ids,
        cache=cache, project=project, changed=changed)
    return findings


def lint_paths_report(
    paths: Iterable[str],
    config: LintConfig | None = None,
    rel_root: str | None = None,
    rule_ids: Iterable[str] | None = None,
    cache: LintCache | None = None,
    project: bool = True,
    changed: set[str] | None = None,
) -> tuple[list[Finding], LintStats]:
    """:func:`lint_paths` plus a :class:`LintStats` run report."""
    t_start = time.monotonic()
    config = config or default_config()
    rules = config.enabled_rules()
    if rule_ids is not None:
        wanted = set(rule_ids)
        unknown = wanted - set(rules)
        if unknown:
            raise KeyError(f"unknown/disabled rule(s): {sorted(unknown)}")
        rules = {rid: r for rid, r in rules.items() if rid in wanted}
    module_rules = {rid: r for rid, r in rules.items()
                    if not isinstance(r, ProjectRule)}
    project_rules = {rid: r for rid, r in rules.items()
                     if isinstance(r, ProjectRule)}

    stats = LintStats(
        module_rules=sorted(module_rules),
        project_rules=sorted(project_rules) if project else [],
        changed_scope=sorted(changed) if changed is not None else None,
    )
    findings: list[Finding] = []
    modules: dict[str, ModuleInfo] = {}
    seen_files: set[str] = set()
    for top in paths:
        base = rel_root or (top if os.path.isdir(top) else os.path.dirname(top))
        for fpath in _iter_py_files(top):
            real = os.path.realpath(fpath)
            if real in seen_files:
                continue  # overlapping path args must not double-report
            seen_files.add(real)
            relpath = os.path.relpath(fpath, base).replace(os.sep, "/")
            if path_matches(relpath, config.exclude):
                continue
            t0 = time.monotonic()
            try:
                st = os.stat(fpath)
                with open(fpath, encoding="utf-8") as f:
                    source = f.read()
                tree = ast.parse(source, filename=fpath)
            except (SyntaxError, UnicodeDecodeError) as exc:
                findings.append(Finding(
                    "parse-error", relpath,
                    getattr(exc, "lineno", 0) or 0,
                    f"could not parse: {exc}",
                ))
                continue
            module = ModuleInfo(fpath, source, tree, relpath=relpath)
            modules[relpath] = module
            stats.files += 1
            stats.parse_s += time.monotonic() - t0

            t0 = time.monotonic()
            cached = (cache.get(relpath, st.st_mtime_ns, st.st_size)
                      if cache is not None else None)
            if cached is not None:
                findings.extend(cached)
            else:
                per_file = list(suppression_findings(module, relpath))
                for rule in module_rules.values():
                    if not path_matches(relpath, config.rule_paths(rule)):
                        continue
                    raw = rule.check(module, config.rule_options(rule))
                    waived = module.suppressed_lines(rule.rule_id)
                    per_file.extend(
                        Finding(rule.rule_id, relpath, f.line, f.message, f.col)
                        for f in raw
                        if f.line not in waived
                    )
                if cache is not None:
                    cache.put(relpath, st.st_mtime_ns, st.st_size, per_file)
                findings.extend(per_file)
            stats.module_rules_s += time.monotonic() - t0
    if cache is not None:
        stats.cache_hits, stats.cache_misses = cache.hits, cache.misses
        cache.save()

    if project and project_rules and modules:
        from predictionio_tpu.analysis.project import ProjectModel

        t0 = time.monotonic()
        model = ProjectModel(modules)
        for rule in project_rules.values():
            raw = rule.check_project(model, config.rule_options(rule))
            for f in raw:
                if not path_matches(f.path, config.rule_paths(rule)):
                    continue
                mod = modules.get(f.path)
                if mod is not None and f.line in mod.suppressed_lines(rule.rule_id):
                    continue
                findings.append(Finding(rule.rule_id, f.path, f.line,
                                        f.message, f.col))
        stats.project_rules_s += time.monotonic() - t0

    if changed is not None:
        findings = [f for f in findings if f.path in changed]
    findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    stats.total_s = time.monotonic() - t_start
    return findings, stats


def lint_package(
    package_dir: str | None = None,
    config: LintConfig | None = None,
    rule_ids: Iterable[str] | None = None,
    cache: LintCache | None = None,
    project: bool = True,
    changed: set[str] | None = None,
) -> list[Finding]:
    """Lint the installed ``predictionio_tpu`` package with the repo
    policy — what `pio lint` and the tier-1 gate run."""
    findings, _ = lint_package_report(
        package_dir, config=config, rule_ids=rule_ids, cache=cache,
        project=project, changed=changed)
    return findings


def lint_package_report(
    package_dir: str | None = None,
    config: LintConfig | None = None,
    rule_ids: Iterable[str] | None = None,
    cache: LintCache | None = None,
    project: bool = True,
    changed: set[str] | None = None,
) -> tuple[list[Finding], LintStats]:
    if package_dir is None:
        import predictionio_tpu

        package_dir = os.path.dirname(predictionio_tpu.__file__)
    return lint_paths_report(
        [package_dir], config=config, rel_root=package_dir,
        rule_ids=rule_ids, cache=cache, project=project, changed=changed)


def format_findings(findings: list[Finding], fmt: str = "text",
                    stats: LintStats | None = None) -> str:
    if fmt == "json":
        items = [
            {
                "rule": f.rule_id,
                "path": f.path,
                "line": f.line,
                "col": f.col,
                "message": f.message,
            }
            for f in findings
        ]
        if stats is None:
            return json.dumps(items, indent=2)
        return json.dumps({"findings": items, "stats": stats.as_dict()},
                          indent=2)
    if fmt == "sarif":
        from predictionio_tpu.analysis.core import all_rules
        from predictionio_tpu.analysis.report import to_sarif

        descriptions = {rid: r.description for rid, r in all_rules().items()}
        return to_sarif(findings, descriptions)
    out = [f.format() for f in findings]
    n = len(findings)
    out.append(f"{n} finding{'s' if n != 1 else ''}" if n else "clean: no findings")
    return "\n".join(out)
