"""shared-state-race: cross-module escape analysis for unsynchronized
attribute sharing between thread contexts.

The per-file ``lock-discipline`` rule only sees ``Thread(target=self.m)``
inside one class; this pass walks the whole-program :class:`ProjectModel`
instead. It resolves every Thread/Timer/submit target (including bound
methods on objects defined in *other* modules and objects escaping
through ``args=``), propagates the thread context through the resolved
call graph, then checks each class attribute that is touched from both
a thread context and the main context:

- a thread-context WRITE with no lock held (and no caller-inherited
  lock) while the attribute is also accessed outside that context is a
  finding at the write;
- thread-context writes all under lock L, but some main-context access
  holds no lock in common with every write site, is a finding at the
  write too (one per attribute — the message lists the unlocked reader)
  so a single justified suppression can document a deliberate
  publication discipline (e.g. the gateway's lock-free table swap).

Pre-publication state is excluded: ``self.x = ...`` inside
``__init__``/``__post_init__`` and accesses through a local name bound
to a constructor call in the same function (the object has not escaped
yet). Wildcard (unresolvable) locks on either side conservatively
count as protection.
"""

from __future__ import annotations

from typing import Any

from predictionio_tpu.analysis.core import Finding, ProjectRule, register_rule
from predictionio_tpu.analysis.project import (
    READ,
    WRITE,
    WILDCARD_LOCK,
    AttrAccess,
    ProjectModel,
    lock_label,
)


def _locks_at(project: ProjectModel, acc: AttrAccess) -> frozenset:
    unit = project.functions[acc.func]
    return project.locks_held_at(unit, acc.node)


@register_rule
class SharedStateRaceRule(ProjectRule):
    rule_id = "shared-state-race"
    description = (
        "attribute written in one thread context and read in another "
        "without a common lock (whole-program escape analysis)"
    )
    default_paths = ("",)

    def check_project(self, project: ProjectModel,
                      options: dict[str, Any]) -> list[Finding]:
        findings: list[Finding] = []
        reach = project.thread_reachable()
        by_class: dict[str, list[AttrAccess]] = {}
        for unit in project.functions.values():
            for acc in unit.accesses:
                if not acc.fresh:
                    by_class.setdefault(acc.cls_key, []).append(acc)

        for cls_key in sorted(by_class):
            per_attr: dict[str, list[AttrAccess]] = {}
            for acc in by_class[cls_key]:
                per_attr.setdefault(acc.attr, []).append(acc)
            for attr in sorted(per_attr):
                f = self._check_attr(project, reach, cls_key, attr,
                                     per_attr[attr])
                if f is not None:
                    findings.append(f)
        return findings

    def _check_attr(self, project: ProjectModel, reach, cls_key: str,
                    attr: str, accesses: list[AttrAccess]) -> Finding | None:
        thread_acc = [a for a in accesses if a.func in reach]
        main_acc = [a for a in accesses if a.func not in reach]
        twrites = sorted((a for a in thread_acc if a.kind == WRITE),
                         key=lambda a: (a.module, a.line))
        if not twrites or not main_acc:
            return None
        # program order inside the spawning function happens-before the
        # thread starts: a main-context access earlier in the very
        # function that performs EVERY spawn reaching these writes is
        # pre-publication setup, not a race
        spawns = {id(reach[w.func]): reach[w.func] for w in twrites}
        main_acc = [
            m for m in main_acc
            if not all(s.func == m.func and m.line < s.line
                       for s in spawns.values())
        ]
        if not main_acc:
            return None
        cls_name = cls_key.split(":")[-1]
        lock_sets = {id(a): _locks_at(project, a) for a in twrites + main_acc}

        def provenance(acc: AttrAccess) -> str:
            spawn = reach[acc.func]
            return f"{spawn.kind} spawned at {spawn.module}:{spawn.line}"

        # case 1: an unlocked thread-context write
        for w in twrites:
            if not lock_sets[id(w)]:
                other = min(main_acc, key=lambda a: (a.module, a.line))
                return Finding(
                    self.rule_id, w.module, w.line,
                    f"{cls_name}.{attr} is written here on a thread "
                    f"context ({provenance(w)}) with no lock held, but "
                    f"is also {'written' if other.kind == WRITE else 'read'}"
                    f" from the main context at {other.module}:{other.line}"
                    " — take a common lock on both sides or document the"
                    " publication discipline with a suppression",
                    w.col)
        # wildcard anywhere on the write side -> assume protected
        if any(WILDCARD_LOCK in lock_sets[id(w)] for w in twrites):
            return None
        # case 2: locked writes, but a main-context access shares no
        # lock with some write site
        for m in sorted(main_acc, key=lambda a: (a.module, a.line)):
            held = lock_sets[id(m)]
            if WILDCARD_LOCK in held:
                continue
            for w in twrites:
                if held & lock_sets[id(w)]:
                    continue
                locks = ", ".join(sorted(lock_label(l) for l in lock_sets[id(w)]))
                return Finding(
                    self.rule_id, w.module, w.line,
                    f"{cls_name}.{attr} is written here under {locks} on a "
                    f"thread context ({provenance(w)}), but "
                    f"{m.module}:{m.line} accesses it from the main context"
                    " without that lock — lock the reader or document the"
                    " lock-free publication discipline with a suppression",
                    w.col)
        return None
