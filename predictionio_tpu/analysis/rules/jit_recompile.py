"""jit-recompile-risk: static counterpart to the ``pio_jit_recompiles``
runtime sentinel (obs/compile.py).

The ProjectModel registers every ``@jax.jit`` / ``pjit`` /
``instrumented_jit`` entry point with its static-argument menu
(``static_argnames``/``static_argnums``). At each *resolved* call site
this rule flags:

- a static argument fed a provably per-call-varying Python scalar —
  ``len(...)``, ``.shape[...]``, arithmetic over non-constants,
  ``int()`` of a non-constant — every distinct value compiles a fresh
  program. Values snapped through a width-menu helper (options
  ``snap_calls``, default ``serving_k``/``serving_batch`` — the
  ``ops/topk.BATCH_WIDTHS`` discipline), literals, and UPPERCASE
  constants are accepted; a bare name we cannot trace is NOT flagged
  (documented give-up: better silent than noisy).
- a traced argument built inline from a list/generator comprehension
  via ``asarray``/``array``/``stack`` — its shape varies with the
  comprehension length, recompiling per batch size; pad through the
  width menus instead.

Some jit programs hide behind a cached FACTORY instead of a decorator —
``ops/topk._sharded_topk_fn`` builds its shard_map program keyed on
``(mesh, k, shard_rows)``, so every distinct ``k`` reaching the plain
wrapper ``recommend_topk_sharded`` mints a compile exactly like a
static arg would, invisibly to the decorator scan. The
``extra_entries`` option (function name → list of jit-static parameter
names) extends the same call-site classification over those wrappers.
"""

from __future__ import annotations

import ast
from typing import Any

from predictionio_tpu.analysis.core import Finding, ProjectRule, Rule, register_rule
from predictionio_tpu.analysis.project import FunctionUnit, ProjectModel

_DEFAULT_SNAP_CALLS = ("serving_k", "serving_batch")
_ARRAY_CTORS = ("asarray", "array", "stack")

_OK, _RISKY, _UNKNOWN = "ok", "risky", "unknown"


@register_rule
class JitRecompileRiskRule(ProjectRule):
    rule_id = "jit-recompile-risk"
    description = (
        "per-call-varying static args / shape-varying inline arrays at "
        "jit entry call sites (recompile on every distinct value)"
    )
    default_paths = ("",)

    def check_project(self, project: ProjectModel,
                      options: dict[str, Any]) -> list[Finding]:
        snaps = tuple(options.get("snap_calls", _DEFAULT_SNAP_CALLS))
        findings: list[Finding] = []
        for site in project.jit_call_sites:
            entry = project.jit_entries[site.entry]
            unit = project.functions[site.func]
            bound = self._bind(entry.params, site.node)
            for param, arg in bound:
                if param in entry.static_params:
                    verdict = self._classify(project, unit, arg, snaps, 0)
                    if verdict == _RISKY:
                        findings.append(Finding(
                            self.rule_id, site.module, arg.lineno,
                            f"static parameter '{param}' of jit entry "
                            f"{entry.name}() ({entry.module}) receives a "
                            "per-call-varying value — every distinct value "
                            "compiles a fresh program; snap it to a width "
                            "menu (e.g. ops/topk serving_k/serving_batch) "
                            "or hoist it to a constant",
                            arg.col_offset))
                elif self._shape_varying(arg):
                    findings.append(Finding(
                        self.rule_id, site.module, arg.lineno,
                        f"traced argument of jit entry {entry.name}() "
                        f"({entry.module}) is built inline from a "
                        "comprehension — its shape varies per call, "
                        "recompiling per batch size; pad to a width menu "
                        "(ops/topk BATCH_WIDTHS discipline) first",
                        arg.col_offset))
        extra = {str(name): tuple(statics) for name, statics in
                 (options.get("extra_entries") or {}).items()}
        if extra:
            findings.extend(self._check_extra_entries(project, extra, snaps))
        return findings

    def _check_extra_entries(self, project: ProjectModel,
                             extra: dict[str, tuple[str, ...]],
                             snaps: tuple[str, ...]) -> list[Finding]:
        """Call-site classification for factory-backed jit wrappers
        (module docstring): the wrapper is a plain function, so its
        call edges are in ``unit.calls`` rather than
        ``jit_call_sites``; the configured params compile-key the
        cached program exactly like static args."""
        findings: list[Finding] = []
        for unit in project.functions.values():
            for edge in unit.calls:
                if not isinstance(edge.node, ast.Call):
                    continue                # property-read edge
                if edge.callee in project.jit_entries:
                    continue                # already covered above
                target = project.functions.get(edge.callee)
                if target is None or target.name not in extra:
                    continue
                statics = extra[target.name]
                params = tuple(a.arg for a in
                               (list(target.node.args.posonlyargs)
                                + list(target.node.args.args)))
                for param, arg in self._bind(params, edge.node):
                    if param not in statics:
                        continue
                    if self._classify(project, unit, arg, snaps,
                                      0) == _RISKY:
                        findings.append(Finding(
                            self.rule_id, unit.module, arg.lineno,
                            f"compile-keyed parameter '{param}' of "
                            f"{target.name}() ({target.module}) receives "
                            "a per-call-varying value — the cached jit "
                            "factory behind it compiles a fresh program "
                            "per distinct value; snap it to a width menu "
                            "(e.g. ops/topk serving_k/serving_batch) or "
                            "hoist it to a constant",
                            arg.col_offset))
        return findings

    @staticmethod
    def _bind(params: tuple[str, ...],
              call: ast.Call) -> list[tuple[str, ast.expr]]:
        bound: list[tuple[str, ast.expr]] = []
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break
            if i < len(params):
                bound.append((params[i], arg))
        for kw in call.keywords:
            if kw.arg is not None:
                bound.append((kw.arg, kw.value))
        return bound

    def _classify(self, project: ProjectModel, unit: FunctionUnit,
                  expr: ast.expr, snaps: tuple[str, ...],
                  depth: int) -> str:
        if depth > 4:
            return _UNKNOWN
        if isinstance(expr, ast.Constant):
            return _OK
        if isinstance(expr, ast.Name):
            if expr.id.isupper():
                return _OK
            if expr.id in project.module_constants.get(unit.mkey, set()):
                return _OK
            src = unit.assigns.get(expr.id)
            if src is not None:
                return self._classify(project, unit, src, snaps, depth + 1)
            return _UNKNOWN
        if isinstance(expr, ast.Attribute):
            if expr.attr.isupper():
                return _OK                      # module.CONSTANT
            if "shape" in (Rule.dotted_name(expr) or "").split("."):
                # a static arg equal to f(input.shape) adds no variation
                # beyond the shape-driven recompiles the array causes anyway
                return _OK
            return _UNKNOWN
        if isinstance(expr, ast.Subscript):
            base = Rule.dotted_name(expr.value) or ""
            if base.endswith("shape") or "shape" in base.split("."):
                return _OK                      # x.shape[0]: see above
            if isinstance(expr.value, ast.Name) and expr.value.id.isupper():
                return _OK                      # WIDTHS[i] menu pick
            return _UNKNOWN
        if isinstance(expr, ast.Call):
            last = (Rule.dotted_name(expr.func) or "").split(".")[-1]
            if self._is_snap(project, unit, expr, last, snaps):
                return _OK
            if last == "len":
                return _RISKY
            if last in ("int", "float", "round"):
                inner = expr.args[0] if expr.args else None
                if inner is None:
                    return _UNKNOWN
                v = self._classify(project, unit, inner, snaps, depth + 1)
                return _OK if v == _OK else v
            if last in ("min", "max"):
                verdicts = [self._classify(project, unit, a, snaps, depth + 1)
                            for a in expr.args]
                if _RISKY in verdicts:
                    return _RISKY
                return _OK if verdicts and all(v == _OK for v in verdicts) \
                    else _UNKNOWN
            return _UNKNOWN                     # might be another snapper
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add) \
                and isinstance(expr.right, ast.BinOp) \
                and isinstance(expr.right.op, ast.Mod):
            # the pad-to-multiple idiom ``x + (-x) % m`` — a width menu
            # of multiples of m, not per-call drift
            return _OK
        if isinstance(expr, (ast.BinOp, ast.UnaryOp)):
            parts = ([expr.operand] if isinstance(expr, ast.UnaryOp)
                     else [expr.left, expr.right])
            verdicts = [self._classify(project, unit, p, snaps, depth + 1)
                        for p in parts]
            if all(v == _OK for v in verdicts):
                return _OK
            # arithmetic over anything non-constant drifts per call
            return _RISKY
        if isinstance(expr, ast.IfExp):
            verdicts = [self._classify(project, unit, p, snaps, depth + 1)
                        for p in (expr.body, expr.orelse)]
            if _RISKY in verdicts:
                return _RISKY
            return _OK if all(v == _OK for v in verdicts) else _UNKNOWN
        return _UNKNOWN

    @staticmethod
    def _is_snap(project: ProjectModel, unit: FunctionUnit, call: ast.Call,
                 last: str, snaps: tuple[str, ...]) -> bool:
        """A snap-helper call pins its result to a width menu. Matched
        by trailing name (leading underscores stripped, so a private
        alias like ``_serving_k`` counts) and, when the callee
        resolves, by the resolved function's own name."""
        if last in snaps or last.lstrip("_") in snaps:
            return True
        sym = project._resolve_symbol(
            unit.mkey, Rule.dotted_name(call.func) or "")
        if sym and sym[0] == "func":
            name = sym[1].split(":")[-1].split(".")[-1]
            return name in snaps or name.lstrip("_") in snaps
        return False

    @staticmethod
    def _shape_varying(expr: ast.expr) -> bool:
        if not isinstance(expr, ast.Call):
            return False
        last = (Rule.dotted_name(expr.func) or "").split(".")[-1]
        if last not in _ARRAY_CTORS or not expr.args:
            return False
        return isinstance(expr.args[0], (ast.ListComp, ast.GeneratorExp,
                                         ast.SetComp))
