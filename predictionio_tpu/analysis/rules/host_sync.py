"""host-sync-in-hot-path: no device→host synchronization while a
request handler holds the socket.

``.item()``, ``float(jnp_value)``, ``np.asarray(jax_value)``,
``jax.device_get`` and ``.block_until_ready()`` all block the calling
thread until the device (possibly a remote-attached TPU, ~100ms RTT)
finishes and the value lands on host. On the serving path that turns
one stray scalar read into a full device round-trip per request —
the latency regression PR 1's load tests kept rediscovering. Models
must return device arrays; the serving layer converts ONCE at the
wire boundary (core/wire.to_wire), outside the scope of this rule.

Heuristics, tuned to zero false positives on the current tree:
``float()``/``int()``/``np.asarray()`` are flagged only when their
argument expression textually references ``jnp.``/``jax.`` — a plain
``float(header_value)`` stays legal.
"""

from __future__ import annotations

import ast
from typing import Any

from predictionio_tpu.analysis.core import Finding, ModuleInfo, Rule, register_rule

#: zero-arg methods that force a device sync wherever they appear
SYNC_METHODS = ("item", "block_until_ready")

#: converters that sync only when fed a device value
CONVERTERS = ("float", "int", "bool", "np.asarray", "numpy.asarray",
              "np.array", "numpy.array")

JAX_MARKERS = ("jnp.", "jax.")


@register_rule
class HostSyncRule(Rule):
    rule_id = "host-sync-in-hot-path"
    description = "no host-device synchronization on the request-serving path"
    default_paths = ("api/", "workflow/deploy.py")

    def check(self, module: ModuleInfo, options: dict[str, Any]) -> list[Finding]:
        sync_methods = set(options.get("sync_methods", SYNC_METHODS))
        converters = set(options.get("converters", CONVERTERS))

        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = self.dotted_name(node.func)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in sync_methods
                    and not node.args and not node.keywords):
                findings.append(Finding(
                    self.rule_id, "", node.lineno,
                    f".{node.func.attr}() on the serving path blocks the "
                    f"handler thread on a device round-trip — keep values "
                    f"on device until the wire boundary", node.col_offset))
                continue
            if dotted == "jax.device_get":
                findings.append(Finding(
                    self.rule_id, "", node.lineno,
                    "jax.device_get() on the serving path forces a "
                    "device→host transfer per request", node.col_offset))
                continue
            if dotted in converters and node.args:
                arg_src = ast.unparse(node.args[0])
                if any(m in arg_src for m in JAX_MARKERS):
                    findings.append(Finding(
                        self.rule_id, "", node.lineno,
                        f"{dotted}({arg_src}) converts a device value on "
                        f"the serving path — a hidden blocking sync",
                        node.col_offset))
        return findings
