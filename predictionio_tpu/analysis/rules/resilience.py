"""resilience-bypass: no raw network call may bypass the resilience
layer (utils/resilience.resilient).

The generalization of PR 1's one-off AST test
(tests/test_resilience_static.py, now a thin wrapper over this rule):

- raw network callables (``urlopen``/``create_connection`` by default)
  may appear ONLY inside a module's designated guarded functions;
- guarded functions may be referenced (outside their own ``def``) only
  as arguments of a ``resilient(...)`` call — no direct invocation, no
  aliasing them out;
- constructor guards: a class carrying an unguarded raw call (pgwire's
  ``PGConnection``) may be constructed only inside a named function
  that the reference check above proves is resilient()-routed;
- guard tables must not go stale: every declared guarded site and
  resilient-only function must still exist;
- every module with guarded sites must import the resilience layer.

A module in scope but absent from the guard tables gets the strictest
policy: any raw network call is a violation. New storage backends must
therefore either route through ``resilient()`` or declare their guarded
site in the lint config — exactly the review rule PR 1 encoded by hand.
"""

from __future__ import annotations

import ast
import os
from typing import Any

from predictionio_tpu.analysis.core import Finding, ModuleInfo, Rule, register_rule

DEFAULT_NET_CALLS = ("urlopen", "create_connection")


@register_rule
class ResilienceBypassRule(Rule):
    rule_id = "resilience-bypass"
    description = (
        "raw network calls must sit in guarded functions invoked only "
        "through resilient(...)"
    )
    default_paths = ("storage/",)

    def check(self, module: ModuleInfo, options: dict[str, Any]) -> list[Finding]:
        basename = os.path.basename(module.path)
        net_calls = set(options.get("net_calls", DEFAULT_NET_CALLS))
        guarded_sites: dict = options.get("guarded_sites", {})
        resilient_only: dict = options.get("resilient_only", {})
        ctor_guard: dict = options.get("ctor_guard", {})
        require_import: str = options.get(
            "require_import", "predictionio_tpu.utils.resilience")
        no_import_ok = set(options.get("no_import_ok", ()))

        findings: list[Finding] = []
        allowed = set(guarded_sites.get(basename, ()))

        # 1. raw net calls only inside the guarded functions
        seen_quals: set[str] = set()
        for node, stack in self.walk_with_stack(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self.call_name(node)
            if name not in net_calls:
                continue
            qual = ".".join(stack) or "<module>"
            seen_quals.add(qual)
            if qual not in allowed:
                findings.append(Finding(
                    self.rule_id, "", node.lineno,
                    f"raw network call {name}() in {qual} — route it "
                    f"through resilient() or declare the guarded site "
                    f"in the lint config", node.col_offset,
                ))
        # stale guard table: every declared site must still exist
        for qual in sorted(allowed - seen_quals):
            findings.append(Finding(
                self.rule_id, "", 1,
                f"stale guard: declared net-call site {qual} makes no "
                f"raw network call — update the lint config",
            ))

        # 2. guarded functions referenced only via resilient(...)
        for name in resilient_only.get(basename, ()):
            refs = [
                node for node in ast.walk(module.tree)
                if (isinstance(node, ast.Attribute) and node.attr == name)
                or (isinstance(node, ast.Name) and node.id == name)
            ]
            if not refs:
                findings.append(Finding(
                    self.rule_id, "", 1,
                    f"stale guard: resilient-only function {name} is "
                    f"never referenced — update the lint config",
                ))
                continue
            for ref in refs:
                if self._is_own_def(module, ref, name):
                    continue
                if not self._inside_resilient(module, ref):
                    findings.append(Finding(
                        self.rule_id, "", ref.lineno,
                        f"{name} referenced outside resilient(...) — "
                        f"direct calls/aliases bypass retry+breaker",
                        ref.col_offset,
                    ))

        # 3. call guards: references to a raw function allowed only from
        # inside named enclosing functions (pgwire's _open_socket may be
        # touched only by PGConnection.__init__, whose construction the
        # ctor guard below routes through the pool's resilient connect)
        call_guard: dict = options.get("call_guard", {})
        for name, allowed_quals in call_guard.get(basename, {}).items():
            allowed_set = set(allowed_quals)
            refs = [
                (node, stack)
                for node, stack in self.walk_with_stack(module.tree)
                if (isinstance(node, ast.Attribute) and node.attr == name)
                or (isinstance(node, ast.Name) and node.id == name)
            ]
            # drop the function's own def subtree (incl. recursion)
            refs = [(n, s) for n, s in refs
                    if not self._is_own_def(module, n, name)]
            if not refs:
                findings.append(Finding(
                    self.rule_id, "", 1,
                    f"stale guard: call-guarded function {name} is never "
                    f"referenced — update the lint config",
                ))
            for node, stack in refs:
                qual = ".".join(stack) or "<module>"
                if qual not in allowed_set:
                    findings.append(Finding(
                        self.rule_id, "", node.lineno,
                        f"{name} referenced from {qual} — only "
                        f"{sorted(allowed_set)} may touch it",
                        node.col_offset,
                    ))

        # 4. constructor guards
        for cls_name, fn_name in ctor_guard.get(basename, {}).items():
            spans = [
                (node.lineno, getattr(node, "end_lineno", node.lineno))
                for node in ast.walk(module.tree)
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == fn_name
            ]
            ctors = [
                node for node in ast.walk(module.tree)
                if isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == cls_name
            ]
            if not spans:
                findings.append(Finding(
                    self.rule_id, "", 1,
                    f"stale guard: constructor-guard function {fn_name} "
                    f"not found — update the lint config",
                ))
            if not ctors:
                findings.append(Finding(
                    self.rule_id, "", 1,
                    f"stale guard: {cls_name} is never constructed — "
                    f"update the lint config",
                ))
            for node in ctors:
                if not any(lo <= node.lineno <= hi for lo, hi in spans):
                    findings.append(Finding(
                        self.rule_id, "", node.lineno,
                        f"{cls_name} constructed outside {fn_name} — "
                        f"bypasses the connect resilience",
                        node.col_offset,
                    ))

        # 5. the resilience layer must be imported where guards apply
        if (basename in guarded_sites and basename not in no_import_ok
                and require_import not in module.source):
            findings.append(Finding(
                self.rule_id, "", 1,
                f"module does not import the resilience layer "
                f"({require_import})",
            ))
        return findings

    @staticmethod
    def _is_own_def(module: ModuleInfo, ref: ast.AST, name: str) -> bool:
        """The reference IS (or sits inside) the function's own def."""
        for anc in [ref, *module.ancestors(ref)]:
            if (isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and anc.name == name):
                return True
        return False

    @staticmethod
    def _inside_resilient(module: ModuleInfo, ref: ast.AST) -> bool:
        for anc in module.ancestors(ref):
            if (isinstance(anc, ast.Call)
                    and isinstance(anc.func, ast.Name)
                    and anc.func.id == "resilient"):
                return True
        return False
