"""lock-order: global lock-ordering graph with cycle detection.

Built from the ProjectModel's lock inventory: every ``with <lock>:``
span contributes edges ``held -> acquired`` — both for lexically nested
acquisitions and for acquisitions reached through resolved calls (the
callee's transitive lock closure), including the caller-holds-the-lock
idiom via inherited locks. A cycle in that digraph means two code paths
can take the same pair of locks in opposite orders: a potential
deadlock between, e.g., the membership lock and a controller tick.

Also flagged: a direct self-deadlock — calling, via ``self``, a method
that re-acquires a non-reentrant lock already held at the call site
(``with self._lock: self.snapshot()`` where ``snapshot`` takes
``self._lock``).

Instance identity is the documented give-up: lock identity is
``(class, attr)``, so call edges through a non-``self`` receiver of
the holder's own class are skipped rather than fabricate a
same-instance ordering that may never occur.
"""

from __future__ import annotations

from typing import Any

from predictionio_tpu.analysis.core import Finding, ProjectRule, register_rule
from predictionio_tpu.analysis.project import (
    WILDCARD_LOCK,
    FunctionUnit,
    ProjectModel,
    lock_label,
)


@register_rule
class LockOrderRule(ProjectRule):
    rule_id = "lock-order"
    description = (
        "lock-ordering cycles and self-deadlocks across the global "
        "lock-acquisition graph"
    )
    default_paths = ("",)

    def check_project(self, project: ProjectModel,
                      options: dict[str, Any]) -> list[Finding]:
        findings: list[Finding] = []
        # edges[(L1, L2)] = first (module, line, detail) that creates it
        edges: dict[tuple, tuple[str, int, str]] = {}

        def add_edge(l1, l2, module, line, detail):
            if l1 == l2 or WILDCARD_LOCK in (l1, l2):
                return
            edges.setdefault((l1, l2), (module, line, detail))

        for key in sorted(project.functions):
            unit = project.functions[key]
            inherited = project.inherited_locks(key)
            for acq in unit.acquires:
                if acq.lock == WILDCARD_LOCK:
                    continue
                held = project.ancestor_locks(unit, acq.node) | inherited
                for h in held:
                    add_edge(h, acq.lock, unit.module, acq.node.lineno,
                             f"acquires {lock_label(acq.lock)} while "
                             f"holding {lock_label(h)}")
            for edge in unit.calls:
                held = project.locks_held_at(unit, edge.node)
                held = {h for h in held if h != WILDCARD_LOCK}
                if not held:
                    continue
                callee_cls = self._callee_class(edge.callee)
                if (not edge.same_instance and unit.cls is not None
                        and callee_cls == unit.cls.key):
                    # same class, possibly different instance: skip
                    # rather than fabricate a same-instance ordering
                    continue
                direct = project.direct_acquires(edge.callee)
                for lock in direct & held:
                    if edge.same_instance and not project.lock_reentrant(lock):
                        findings.append(Finding(
                            self.rule_id, unit.module, edge.node.lineno,
                            f"self-deadlock: this call re-enters "
                            f"{edge.callee.split(':')[-1]}(), which acquires "
                            f"non-reentrant {lock_label(lock)} already held "
                            "here — split out an unlocked helper or use an "
                            "RLock",
                            edge.node.col_offset))
                for lock in project.lock_closure(edge.callee):
                    add_edge_held = held - {lock}
                    for h in add_edge_held:
                        add_edge(h, lock, unit.module, edge.node.lineno,
                                 f"call into {edge.callee.split(':')[-1]}() "
                                 f"acquires {lock_label(lock)} while holding "
                                 f"{lock_label(h)}")

        findings.extend(self._cycles(edges))
        return findings

    @staticmethod
    def _callee_class(callee_key: str) -> str | None:
        mod, _, qual = callee_key.partition(":")
        cls, _, _ = qual.rpartition(".")
        return f"{mod}:{cls}" if cls else None

    def _cycles(self, edges: dict) -> list[Finding]:
        graph: dict[tuple, set] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        findings: list[Finding] = []
        reported: set[frozenset] = set()
        for start in sorted(graph):
            cycle = self._find_cycle(graph, start)
            if not cycle:
                continue
            key = frozenset(cycle)
            if key in reported:
                continue
            reported.add(key)
            legs = []
            for i, a in enumerate(cycle):
                b = cycle[(i + 1) % len(cycle)]
                mod, line, detail = edges[(a, b)]
                legs.append(f"{detail} ({mod}:{line})")
            mod0, line0, _ = edges[(cycle[0], cycle[1 % len(cycle)])]
            findings.append(Finding(
                self.rule_id, mod0, line0,
                "potential deadlock: lock ordering cycle "
                + " -> ".join(lock_label(l) for l in cycle + [cycle[0]])
                + "; " + "; ".join(legs)
                + " — pick one global order for these locks",
            ))
        return findings

    @staticmethod
    def _find_cycle(graph: dict, start) -> list | None:
        """Shortest cycle through ``start`` (BFS back to start)."""
        frontier = [[start]]
        seen = {start}
        while frontier:
            path = frontier.pop(0)
            for nxt in sorted(graph.get(path[-1], ())):
                if nxt == start:
                    return path
                if nxt not in seen and len(path) < 6:
                    seen.add(nxt)
                    frontier.append(path + [nxt])
        return None
