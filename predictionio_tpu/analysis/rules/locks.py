"""lock-discipline: heuristic race detector for handler/worker threads.

The reference serialized shared state through Akka actors; here worker
threads (query batcher dispatcher, plugin sniffer drains, feedback
posts) share plain Python objects with handler threads. The rule finds
instance attributes WRITTEN from a ``threading.Thread`` target (or any
same-class method the target transitively calls via ``self.m()``) and
demands one of:

- the write sits under a ``with <...lock...>:`` block (any context
  manager whose expression mentions "lock"), AND every same-class read
  outside the thread's call tree is likewise protected; or
- the attribute is documented atomic via a suppression with
  justification (single-writer counters read for stats can say so).

Private attributes (leading underscore) written by the thread are only
flagged when some other method of the class actually reads them
unprotected; PUBLIC attributes are part of the object's API, presumed
read externally, and must be protected or documented at the write
site. This is deliberately a heuristic — it catches the shape of race
that actually bit this codebase (unsynchronized stats counters,
state flags flipped across threads), not every aliasing pattern.
"""

from __future__ import annotations

import ast
from typing import Any

from predictionio_tpu.analysis.core import Finding, ModuleInfo, Rule, register_rule


def _is_thread_ctor(node: ast.Call) -> bool:
    dotted = Rule.dotted_name(node.func) or ""
    return dotted.split(".")[-1] == "Thread"


def _with_protects(module: ModuleInfo, node: ast.AST) -> bool:
    """Any ancestor `with` whose context expression mentions a lock."""
    for anc in module.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                if "lock" in ast.unparse(item.context_expr).lower():
                    return True
    return False


@register_rule
class LockDisciplineRule(Rule):
    rule_id = "lock-discipline"
    description = (
        "attributes written from worker threads must be lock-protected "
        "at writer and readers, or documented atomic"
    )
    default_paths = ("",)

    def check(self, module: ModuleInfo, options: dict[str, Any]) -> list[Finding]:
        findings: list[Finding] = []
        for cls in ast.walk(module.tree):
            if isinstance(cls, ast.ClassDef):
                findings.extend(self._check_class(module, cls))
        return findings

    # -- per-class analysis --------------------------------------------------
    def _check_class(self, module: ModuleInfo, cls: ast.ClassDef) -> list[Finding]:
        methods = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        targets = self._thread_target_methods(cls, methods)
        if not targets:
            return []
        # expand through self.m() calls: everything the thread reaches
        reachable = set(targets)
        work = list(targets)
        while work:
            fn = methods[work.pop()]
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and node.func.attr in methods
                        and node.func.attr not in reachable):
                    reachable.add(node.func.attr)
                    work.append(node.func.attr)

        # attribute writes inside the thread's call tree
        writes: dict[str, list[ast.AST]] = {}
        for name in reachable:
            for node in ast.walk(methods[name]):
                attr = self._self_attr_store(node)
                if attr is not None:
                    writes.setdefault(attr, []).append(node)

        findings: list[Finding] = []
        for attr, sites in sorted(writes.items()):
            unprotected_writes = [
                n for n in sites if not _with_protects(module, n)]
            # reads of self.<attr> from methods OUTSIDE the thread tree
            outside_reads = []
            for name, fn in methods.items():
                if name in reachable:
                    continue
                for node in ast.walk(fn):
                    if (isinstance(node, ast.Attribute)
                            and isinstance(node.ctx, ast.Load)
                            and node.attr == attr
                            and isinstance(node.value, ast.Name)
                            and node.value.id == "self"):
                        outside_reads.append(node)
            shared = bool(outside_reads) or not attr.startswith("_")
            if not shared:
                continue
            for node in unprotected_writes:
                why = (f"read by {len(outside_reads)} same-class site(s)"
                       if outside_reads else "public attribute")
                findings.append(Finding(
                    self.rule_id, "", node.lineno,
                    f"{cls.name}.{attr} written from a thread target "
                    f"without holding a lock ({why}) — guard both sides "
                    f"with one lock, or suppress documenting why the "
                    f"access is atomic", getattr(node, "col_offset", 0)))
            if not unprotected_writes:
                # writer is disciplined; readers must be too
                for node in outside_reads:
                    if not _with_protects(module, node):
                        findings.append(Finding(
                            self.rule_id, "", node.lineno,
                            f"{cls.name}.{attr} is lock-protected at its "
                            f"thread-side writer but read here without "
                            f"the lock — torn/stale reads",
                            node.col_offset))
        return findings

    @staticmethod
    def _thread_target_methods(
        cls: ast.ClassDef, methods: dict[str, ast.AST],
    ) -> set[str]:
        """Methods of ``cls`` used as Thread(target=self.<m>)."""
        out: set[str] = set()
        for node in ast.walk(cls):
            if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
                continue
            for kw in node.keywords:
                if (kw.arg == "target"
                        and isinstance(kw.value, ast.Attribute)
                        and isinstance(kw.value.value, ast.Name)
                        and kw.value.value.id == "self"
                        and kw.value.attr in methods):
                    out.add(kw.value.attr)
        return out

    @staticmethod
    def _self_attr_store(node: ast.AST) -> str | None:
        """'attr' when node stores to self.attr (assign/augassign)."""
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                return t.attr
        return None
