"""dtype-discipline: compute modules stay f32/bf16.

TPU MXU/VPU throughput and HBM budget both assume 32-bit (or narrower)
floats; a ``float64`` array silently falls back to slow emulated f64 on
TPU (or forces ``jax_enable_x64`` games) and doubles memory traffic.
Any ``float64`` in ops/, models/ or e2/ is therefore a finding unless
the site carries a numerical-stability justification — exact linear
solves in a parity oracle earn a suppression; "it was numpy's default"
does not.

Flagged forms: ``<mod>.float64`` attributes (np/jnp/numpy/...),
``dtype="float64"`` string constants, and ``.astype("float64")``.
"""

from __future__ import annotations

import ast
from typing import Any

from predictionio_tpu.analysis.core import Finding, ModuleInfo, Rule, register_rule

WIDE_DTYPES = ("float64", "complex128", "int64")
#: int64 indices are routinely legitimate (vocab > 2^31 never is here,
#: but jnp defaults int32 anyway) — only the float widths are policed
#: by default; options can extend.
DEFAULT_POLICED = ("float64", "complex128")


@register_rule
class DtypeDisciplineRule(Rule):
    rule_id = "dtype-discipline"
    description = "no float64/complex128 on the TPU compute path"
    default_paths = ("ops/", "models/", "e2/")

    def check(self, module: ModuleInfo, options: dict[str, Any]) -> list[Finding]:
        policed = set(options.get("policed_dtypes", DEFAULT_POLICED))
        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            # np.float64 / jnp.float64 / numpy.float64 attribute use
            if isinstance(node, ast.Attribute) and node.attr in policed:
                findings.append(self._finding(node, node.attr))
            # dtype="float64" and .astype("float64")
            elif (isinstance(node, ast.keyword) and node.arg == "dtype"
                    and isinstance(node.value, ast.Constant)
                    and node.value.value in policed):
                findings.append(self._finding(node.value, node.value.value))
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value in policed):
                findings.append(self._finding(node.args[0], node.args[0].value))
        return findings

    def _finding(self, node: ast.AST, dtype: str) -> Finding:
        return Finding(
            self.rule_id, "", node.lineno,
            f"{dtype} on the compute path — TPUs emulate f64 at a "
            f"fraction of f32 speed and double HBM traffic; use "
            f"float32/bfloat16, or suppress with a numerical-stability "
            f"justification", getattr(node, "col_offset", 0))
