"""The built-in rule suite — importing this package registers every
rule with the framework registry (analysis.core)."""

from __future__ import annotations

import predictionio_tpu.analysis.rules.resilience  # noqa: F401
import predictionio_tpu.analysis.rules.jit_purity  # noqa: F401
import predictionio_tpu.analysis.rules.host_sync  # noqa: F401
import predictionio_tpu.analysis.rules.dtype  # noqa: F401
import predictionio_tpu.analysis.rules.blocking_io  # noqa: F401
import predictionio_tpu.analysis.rules.locks  # noqa: F401
import predictionio_tpu.analysis.rules.shared_state_race  # noqa: F401
import predictionio_tpu.analysis.rules.lock_order  # noqa: F401
import predictionio_tpu.analysis.rules.jit_recompile  # noqa: F401
