"""untimed-blocking-io: every socket/HTTP call in the serving plane
carries a timeout.

A handler thread blocked on an un-timed ``urlopen`` (the fire-and-
forget feedback POST, an undeploy probe, a webhook fan-out) holds its
socket — and under ThreadingHTTPServer, a thread — for as long as the
peer cares to stall. The resilience layer bounds retries, but only a
socket-level timeout bounds a single attempt. Default policed calls:
``urlopen`` and ``socket.create_connection``; config may extend (e.g.
``requests``-style ``get``/``post`` if that dependency ever lands).

The timeout may be any expression (config field, constant, deadline
remainder) — it just has to be PASSED. ``timeout=None`` is flagged:
that is the spelled-out version of the bug.

The ``banned_sleep_paths`` option extends the rule to supervision
loops (PR 9): within the listed paths a bare ``time.sleep`` is a
finding — waits there must ride the injectable
``utils.resilience.Clock`` (``clock.sleep``) or an ``Event.wait``
timeout, or the supervisor/controller backoff and drain schedules
cannot be driven deterministically under ``ManualClock`` and their
child-process ``wait()``/``poll()`` loops become untestable wall-time
spins.
"""

from __future__ import annotations

import ast
from typing import Any

from predictionio_tpu.analysis.core import Finding, ModuleInfo, Rule, register_rule

#: policed call -> 0-based POSITIONAL index of its timeout parameter:
#: urlopen(url, data=None, timeout=...), create_connection(addr, timeout)
DEFAULT_POLICED_CALLS = {"urlopen": 2, "create_connection": 1}


@register_rule
class UntimedBlockingIORule(Rule):
    rule_id = "untimed-blocking-io"
    description = "blocking network calls in the serving plane must set a timeout"
    default_paths = ("api/",)

    def check(self, module: ModuleInfo, options: dict[str, Any]) -> list[Finding]:
        policed = dict(options.get("policed_calls", DEFAULT_POLICED_CALLS))
        # per-call path scoping: a generic method name ("request") may
        # be policed only where it means the fleet transport's exchange
        # — an unrelated wrapper with the same name elsewhere (the ES
        # client's resilient request(), which binds its timeout
        # internally) must not produce findings
        call_paths: dict[str, list[str]] = options.get("call_paths", {})
        from predictionio_tpu.analysis.config import path_matches

        # bare time.sleep ban (module docstring): applies when the
        # module falls under banned_sleep_paths; `from time import
        # sleep` aliases are tracked so renaming cannot dodge the rule
        banned_sleep = tuple(options.get("banned_sleep_paths", ()))
        sleep_banned_here = bool(banned_sleep) and (
            not module.relpath
            or path_matches(module.relpath, banned_sleep))
        sleep_aliases: set[str] = set()
        if sleep_banned_here:
            for node in ast.walk(module.tree):
                if isinstance(node, ast.ImportFrom) \
                        and node.module == "time":
                    for alias in node.names:
                        if alias.name == "sleep":
                            sleep_aliases.add(alias.asname or "sleep")

        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if sleep_banned_here and self._is_bare_sleep(node,
                                                         sleep_aliases):
                findings.append(Finding(
                    self.rule_id, "", node.lineno,
                    "bare time.sleep in a supervision path — waits "
                    "here must use the injectable Clock "
                    "(clock.sleep) or Event.wait so backoff/drain "
                    "schedules stay deterministic under ManualClock",
                    node.col_offset))
                continue
            name = self.call_name(node)
            if name not in policed:
                continue
            scoped = call_paths.get(name)
            if scoped is not None and module.relpath \
                    and not path_matches(module.relpath, tuple(scoped)):
                continue
            timeout = next(
                (kw.value for kw in node.keywords if kw.arg == "timeout"),
                None)
            if timeout is None and len(node.args) > policed[name]:
                timeout = node.args[policed[name]]
            if timeout is None:
                findings.append(Finding(
                    self.rule_id, "", node.lineno,
                    f"{name}() without a timeout — a stalled peer parks "
                    f"this thread forever; pass timeout=<bounded>",
                    node.col_offset))
            elif isinstance(timeout, ast.Constant) and timeout.value is None:
                findings.append(Finding(
                    self.rule_id, "", node.lineno,
                    f"{name}(timeout=None) — explicitly unbounded; pass "
                    f"a finite timeout", node.col_offset))
        return findings

    def _is_bare_sleep(self, node: ast.Call,
                       sleep_aliases: set[str]) -> bool:
        if self.dotted_name(node.func) == "time.sleep":
            return True
        return (isinstance(node.func, ast.Name)
                and node.func.id in sleep_aliases)
