"""jit-purity: no side effects inside jit-compiled functions.

``jax.jit`` traces a function ONCE per input signature; Python side
effects inside the body run at trace time, then silently never again —
a ``print`` that "works" in testing, a ``time.time()`` that freezes at
its trace-time value, a ``random.random()`` constant-folded into the
compiled graph, global/nonlocal mutation that happens once. All are
latent serving bugs, so they are banned outright in the compute
modules. (Use ``jax.debug.print`` / ``jax.debug.callback`` for traced
effects and ``jax.random`` for randomness — both are allowed.)

Detected jit entry points: ``@jax.jit`` / ``@jit`` / ``@pjit`` /
``@instrumented_jit`` (the recompile sentinel's wrapper,
obs/compile.py — it IS jax.jit plus counters, so its bodies are traced
exactly the same) decorators, ``@partial(jax.jit, ...)`` (any alias of
partial), and local functions passed by name to a ``jax.jit(fn)``
call. The whole body including nested defs is policed — everything
inside is traced.
"""

from __future__ import annotations

import ast
from typing import Any

from predictionio_tpu.analysis.core import Finding, ModuleInfo, Rule, register_rule

#: bare-name calls that are always impure host I/O
FORBIDDEN_NAMES = ("print", "open", "input", "breakpoint", "exec", "eval")

#: dotted-prefix call roots that reach host state. ``random.`` is the
#: stdlib module (jax.random/np.random root at jax/np and are checked
#: separately); np.random is host randomness that constant-folds.
FORBIDDEN_PREFIXES = (
    "time.", "random.", "np.random.", "numpy.random.", "os.", "sys.",
    "logging.", "logger.", "builtins.print",
)


#: decorator/callable last-components that mean "this body is traced"
_JIT_NAMES = ("jit", "pjit", "instrumented_jit")


def _decorator_is_jit(dec: ast.expr) -> bool:
    name = Rule.dotted_name(dec)
    if name is not None:
        return name.split(".")[-1] in _JIT_NAMES
    if isinstance(dec, ast.Call):
        fn_name = Rule.dotted_name(dec.func) or ""
        if fn_name.split(".")[-1] in _JIT_NAMES:
            return True
        # partial(jax.jit, ...) under any partial alias
        if fn_name.split(".")[-1].lstrip("_") == "partial" and dec.args:
            inner = Rule.dotted_name(dec.args[0]) or ""
            return inner.split(".")[-1] in _JIT_NAMES
    return False


@register_rule
class JitPurityRule(Rule):
    rule_id = "jit-purity"
    description = "no host side effects inside jit/pjit-compiled functions"
    default_paths = ("ops/", "models/", "e2/")

    def check(self, module: ModuleInfo, options: dict[str, Any]) -> list[Finding]:
        forbidden_names = set(options.get("forbidden_names", FORBIDDEN_NAMES))
        forbidden_prefixes = tuple(
            options.get("forbidden_prefixes", FORBIDDEN_PREFIXES))

        # names wrapped functionally: fn in jax.jit(fn) / jit(fn, ...)
        wrapped_names: set[str] = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn_name = self.dotted_name(node.func) or ""
            if fn_name.split(".")[-1] in _JIT_NAMES:
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Name):
                        wrapped_names.add(arg.id)

        findings: list[Finding] = []
        seen: set[ast.AST] = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            jitted = (
                any(_decorator_is_jit(d) for d in node.decorator_list)
                or node.name in wrapped_names
            )
            if not jitted or node in seen:
                continue
            # the whole subtree is traced, nested defs included
            for sub in ast.walk(node):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    seen.add(sub)
                findings.extend(self._check_stmt(node.name, sub, forbidden_names,
                                                 forbidden_prefixes))
        return findings

    def _check_stmt(
        self,
        fn_name: str,
        node: ast.AST,
        forbidden_names: set[str],
        forbidden_prefixes: tuple[str, ...],
    ) -> list[Finding]:
        where = f"inside jit-compiled {fn_name}()"
        if isinstance(node, ast.Global):
            return [Finding(self.rule_id, "", node.lineno,
                            f"global statement {where} — trace-time-only "
                            f"mutation; hoist the state out of the jit")]
        if isinstance(node, ast.Nonlocal):
            return [Finding(self.rule_id, "", node.lineno,
                            f"nonlocal statement {where} — trace-time-only "
                            f"mutation; return the value instead")]
        if not isinstance(node, ast.Call):
            return []
        dotted = self.dotted_name(node.func)
        if dotted in forbidden_names:
            return [Finding(
                self.rule_id, "", node.lineno,
                f"{dotted}() {where} — runs at trace time only; use "
                f"jax.debug.* for traced effects", node.col_offset)]
        if dotted and any(dotted.startswith(p) for p in forbidden_prefixes):
            return [Finding(
                self.rule_id, "", node.lineno,
                f"{dotted}() {where} — host state constant-folds at "
                f"trace time (use jax.random for randomness)",
                node.col_offset)]
        return []
