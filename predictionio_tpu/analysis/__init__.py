"""`pio lint` — AST-based invariant checking for the serving and compute
paths.

The reference PredictionIO leaned on JVM typing and Spark's execution
model to keep framework invariants honest; this Python/JAX port has
neither, so the invariants are machine-checked here instead:

- every remote network call routes through the resilience layer
  (``resilience-bypass``)
- jit-compiled functions are pure (``jit-purity``)
- no host-device sync on the request-serving hot path
  (``host-sync-in-hot-path``)
- compute modules stay f32/bf16 (``dtype-discipline``)
- every blocking socket/HTTP call in the serving plane carries a
  timeout (``untimed-blocking-io``)
- state shared with worker threads is lock-protected or documented
  atomic (``lock-discipline`` per file; ``shared-state-race`` across
  modules via the whole-program ProjectModel)
- no two code paths take the same pair of locks in opposite orders
  (``lock-order``, global lock-ordering graph with cycle detection)
- jit entry call sites keep their static/padded-width contracts so the
  ``pio_jit_recompiles`` runtime sentinel stays silent
  (``jit-recompile-risk``)

Public surface: :func:`lint_paths` runs the registered rules over a file
tree and returns :class:`Finding`s (:func:`lint_paths_report` adds a
:class:`LintStats` run report, and project-phase rules see one shared
:class:`ProjectModel`); the ``pio lint`` CLI subcommand and the tier-1
gate (``tests/test_lint_gate.py``) are thin callers. See
docs/static-analysis.md for the rule catalog, the whole-program model,
and suppression syntax
(``# pio: lint-ignore[rule-id]: justification``).
"""

from __future__ import annotations

from predictionio_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    ProjectRule,
    Rule,
    all_rules,
    get_rule,
    register_rule,
)
from predictionio_tpu.analysis.config import LintConfig, default_config
from predictionio_tpu.analysis.runner import (
    LintStats,
    format_findings,
    lint_package,
    lint_package_report,
    lint_paths,
    lint_paths_report,
)
from predictionio_tpu.analysis.project import ProjectModel

# importing the rules package registers the built-in rule suite
import predictionio_tpu.analysis.rules  # noqa: E402,F401  (registration side effect)

__all__ = [
    "Finding",
    "LintConfig",
    "LintStats",
    "ModuleInfo",
    "ProjectModel",
    "ProjectRule",
    "Rule",
    "all_rules",
    "default_config",
    "format_findings",
    "get_rule",
    "lint_package",
    "lint_package_report",
    "lint_paths",
    "lint_paths_report",
    "register_rule",
]
