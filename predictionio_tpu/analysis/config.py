"""Per-rule, per-package lint configuration.

``default_config()`` encodes the repo policy: which package subtrees
each rule patrols and the rule-specific tables (the resilience guard
lists PR 1 proved out in ``tests/test_resilience_static.py``, the
hot-path module set, the dtype whitelist). Tests and downstream
embedders build their own ``LintConfig`` to lint fixture trees or to
tighten/loosen scope without editing the rules.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from predictionio_tpu.analysis.core import Rule, all_rules


@dataclasses.dataclass
class RuleConfig:
    """How one rule applies in a run."""

    enabled: bool = True
    #: package-relative path prefixes (e.g. ``"api/"``) or exact files
    #: (``"workflow/deploy.py"``); None -> the rule's ``default_paths``
    paths: tuple[str, ...] | None = None
    options: dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class LintConfig:
    """A full lint run: rule set + file exclusions."""

    rules: dict[str, RuleConfig] = dataclasses.field(default_factory=dict)
    #: package-relative prefixes skipped entirely
    exclude: tuple[str, ...] = ()

    def rule_paths(self, rule: Rule) -> tuple[str, ...]:
        rc = self.rules.get(rule.rule_id)
        if rc is not None and rc.paths is not None:
            return rc.paths
        return rule.default_paths

    def rule_options(self, rule: Rule) -> dict[str, Any]:
        rc = self.rules.get(rule.rule_id)
        return rc.options if rc is not None else {}

    def unscoped(self) -> "LintConfig":
        """A copy with every rule's path scope widened to the whole
        tree AND module-keyed policy options dropped — for linting
        ad-hoc files (fixtures, snippets) outside the package. The
        package guard tables are keyed by basename, so an unrelated
        file that happens to be called ``postgres.py`` must get the
        generic strict policy, not the repo's per-module expectations
        (which would report spurious stale-guard findings)."""
        return LintConfig(
            rules={
                rid: RuleConfig(
                    enabled=self.rules.get(rid, RuleConfig()).enabled,
                    paths=("",))
                for rid in {*all_rules(), *self.rules}
            },
            exclude=self.exclude,
        )

    def enabled_rules(self) -> dict[str, Rule]:
        return {
            rid: rule
            for rid, rule in all_rules().items()
            if self.rules.get(rid, RuleConfig()).enabled
        }


def path_matches(relpath: str, prefixes: tuple[str, ...]) -> bool:
    """True when ``relpath`` (forward slashes) falls under any prefix.
    ``""`` matches everything; ``"api/"`` matches the subtree;
    ``"workflow/deploy.py"`` matches exactly that file."""
    for p in prefixes:
        if p == "" or relpath == p:
            return True
        q = p if p.endswith("/") else p + "/"
        if relpath.startswith(q):
            return True
    return False


#: the compute subtrees that must stay TPU-friendly (f32/bf16, pure jit)
COMPUTE_PATHS = ("ops/", "models/", "e2/")

#: request-serving hot path: handler threads, the deployed query path,
#: the batching/cache subsystem (serving/ — PR 3), the columnar
#: data plane's scan/view consumers (data/ — PR 4): a host sync inside
#: the train-read loop would serialize every batch, the
#: observability plane (obs/ — PR 5), which runs INSIDE every request
#: and must never block on the device, the fleet router
#: (fleet/ — PR 6), which sits on EVERY fleet query, and the ANN
#: retrieval kernels (ops/ann.py — PR 8), whose probe/rescore path
#: answers every sublinear query (build/quality helpers are host-side
#: by design and carry justified suppressions)
#: online/ rides along (PR 14): the overlay reads sit INSIDE every
#: recommendation query once --online is live, and the fold loop's
#: deliberate host syncs (per-generation constants, per-user gathers on
#: the background tail thread) carry justified suppressions
#: fleet/gateway.py (PR 15) is covered by the fleet/ prefix here and in
#: every fleet-scoped rule below (resilience-bypass,
#: untimed-blocking-io incl. banned_sleep_paths): the engine-table
#: resolution runs on EVERY gateway query, the gateway itself does no
#: I/O (routing + token buckets only), and its table-mutation paths
#: must never grow a bare sleep or an untimed fetch
#: the per-tenant elasticity plane (PR 16: CapacityArbiter,
#: EngineScaleSet, burst credits) rides the same fleet/ prefix — its
#: sweep loop must stay on Event.wait, its one fleet scrape flows
#: through the already-policed fleet_metrics fan-out, and the
#: credit-spend check sits on the gateway's admit path
#: the shared-memory serving plane (PR 18: serving/shm_cache.py,
#: serving/placement.py) is covered by the serving/ prefix here and in
#: every serving-scoped rule below (resilience-bypass,
#: untimed-blocking-io): the seqlock cache sits INSIDE every cached
#: query, must never grow network I/O or a host sync, and its bounded
#: read-retry loop must stay sleep-free (readers never wait on the
#: writer — serving/shm_cache.py is in banned_sleep_paths to keep it
#: that way)
#: the experimentation plane (PR 20: experiment/) rides here because
#: the variant-assignment + attribution-stamp path sits on EVERY bare
#: /queries.json through the router, the controller's tick runs inside
#: record() on the request path, and the grid scheduler's join loop
#: must stay on bounded waits — experiment/ is in banned_sleep_paths
#: so neither ever grows a bare sleep
HOT_PATHS = ("api/", "workflow/deploy.py", "serving/", "data/", "obs/",
             "fleet/", "ops/ann.py", "online/", "experiment/")


def default_config() -> LintConfig:
    """The repo policy `pio lint` and the tier-1 gate run with."""
    return LintConfig(
        rules={
            "resilience-bypass": RuleConfig(
                # serving/, data/, obs/ and the event server's ingest
                # path carry the strictest policy (no guard-table
                # entries): any raw network call there is a violation —
                # the columnar scan and batch-ingest paths must reach
                # remote backends only through the DAO layer's
                # resilient() wrappers, and the observability plane
                # must never do network I/O of its own (scrapers pull;
                # the plane never pushes)
                # fleet/ and the router's HTTP surface ride along
                # (PR 6): the router's ONE raw-socket site is the
                # transport's connect, declared below; everything else
                # in the fleet tier must reach replicas only through
                # resilient()-routed exchanges
                # online/ (PR 14): the freshness plane reaches storage
                # only through the DAO layer's resilient() wrappers
                # (the tail reads and per-user history fetches) and
                # does no network I/O of its own — the spool plane is
                # files, the overlay is memory
                paths=("storage/", "serving/", "data/", "obs/", "fleet/",
                       "online/",
                       "api/event_server.py", "api/router_server.py"),
                options={
                    # raw-network callables we police
                    "net_calls": ["urlopen", "create_connection"],
                    # module basename -> qualnames allowed to hold raw
                    # network calls; everything else must be network-free
                    "guarded_sites": {
                        "elasticsearch.py": ["ESClient._raw_request"],
                        "s3.py": ["S3Models._raw_request"],
                        "pgwire.py": ["_open_socket"],
                        "postgres.py": [],
                        "hdfs.py": [],
                        "transport.py": ["BackendTransport._connect"],
                    },
                    # module basename -> functions referable (outside
                    # their own def) only inside a resilient(...) call
                    "resilient_only": {
                        "elasticsearch.py": ["_raw_request"],
                        "s3.py": ["_raw_request"],
                        "postgres.py": ["_open_connection"],
                        "hdfs.py": ["_write", "_read", "_remove"],
                    },
                    # module basename -> {func: [allowed enclosing
                    # qualnames]}: the raw function may be referenced
                    # only from those functions (the pgwire socket
                    # opener is reachable solely from PGConnection
                    # construction, which the ctor guard below pins to
                    # the pool's resilient-wrapped connect)
                    "call_guard": {
                        "pgwire.py": {
                            "_open_socket": ["PGConnection.__init__"],
                        },
                        # the fleet transport's socket opener is
                        # reachable only from the request exchange,
                        # whose callers route through
                        # resilient(backend.resilience, ...) at the
                        # router layer (fleet/router._exchange)
                        "transport.py": {
                            "_connect": ["BackendTransport.request"],
                        },
                    },
                    # module basename -> {ClassName: enclosing function}:
                    # the class may only be constructed inside that
                    # function (pgwire's socket guard routes through the
                    # pool's resilient-wrapped connect)
                    "ctor_guard": {
                        "postgres.py": {"PGConnection": "_open_connection"},
                    },
                    # modules with guarded sites must import the layer
                    "require_import": "predictionio_tpu.utils.resilience",
                    # pgwire is guarded one level up, in postgres.py
                    "no_import_ok": ["pgwire.py"],
                },
            ),
            # the device/compiler observability layer rides along
            # (PR 12): obs/compile.py wraps the jit entry points and
            # obs/device.py prices their programs — any jitted helper
            # growing there must obey the same purity contract as the
            # compute modules it instruments
            "jit-purity": RuleConfig(
                paths=COMPUTE_PATHS + ("obs/compile.py", "obs/device.py")),
            "host-sync-in-hot-path": RuleConfig(paths=HOT_PATHS),
            "dtype-discipline": RuleConfig(paths=COMPUTE_PATHS),
            # storage/ included: the deleted PR 1 test pinned pgwire's
            # exact connect line partly to keep its timeout — a blocked
            # connect is not interruptible by the retry layer.
            # fleet/ + obs/ + cli/ cover the fleet-observability
            # fan-out paths (worker-peer fetches, /fleet/metrics
            # replica scrapes, /traces.json stitching, `pio trace`):
            # every cross-process fetch must carry a timeout, so the
            # transport's kw-only `timeout` is policed too (`request`
            # with a large positional index: it can only be passed by
            # keyword, and its absence is the finding)
            # serving/ added with the prefork worker pool: the engine
            # side of the worker-coherence machinery
            # (serving/workers.py) must never grow an untimed fetch or
            # a bare sleep in its sync loop
            # data/wal.py added with durable ingest (PR 13): the WAL
            # drainer's retry loop must ride clock.sleep/Event.wait —
            # a bare time.sleep there is unstoppable during shutdown
            # and untestable on a ManualClock
            # online/ (PR 14): the fold loop must ride Event.wait (a
            # bare time.sleep is unstoppable during shutdown and
            # untestable on a ManualClock), and any cross-process
            # fetch growing there must carry a timeout
            "untimed-blocking-io": RuleConfig(
                paths=("api/", "storage/", "fleet/", "obs/", "cli/",
                       "serving/", "data/wal.py", "online/",
                       "experiment/"),
                options={
                    "policed_calls": {
                        "urlopen": 2, "create_connection": 1,
                        "request": 99,
                    },
                    # "request" means the fleet transport's exchange
                    # only on the fan-out paths; the ES client's own
                    # request() binds its timeout internally
                    "call_paths": {
                        "request": ["fleet/", "obs/",
                                    "api/router_server.py"],
                    },
                    # supervision loops (fleet/supervisor.py,
                    # fleet/controller.py and everything else in the
                    # fleet tier): child-process wait()/poll() loops
                    # must be clock-injectable, so a bare time.sleep
                    # there is a finding — use clock.sleep or
                    # Event.wait (PR 9; docs/static-analysis.md)
                    # serving/shm_cache.py (PR 18): the seqlock
                    # reader's bounded retry must SPIN-then-miss, never
                    # sleep — a sleeping reader inside /queries.json is
                    # exactly the reader-blocks-on-writer coupling the
                    # seqlock exists to remove
                    # experiment/ (PR 20): the controller ticks inside
                    # the request path and the grid's join loop must
                    # stay on ProcessHandle.wait(timeout) — a bare
                    # sleep in either stalls every routed query or
                    # makes the scheduler untestable
                    "banned_sleep_paths": ["fleet/",
                                           "serving/workers.py",
                                           "serving/shm_cache.py",
                                           "data/wal.py",
                                           "online/",
                                           "experiment/"],
                },
            ),
            "lock-discipline": RuleConfig(paths=("",)),
            # -- project-phase rules (PR 17): whole-tree scope; the
            # ProjectModel is built from every module in the run, so
            # narrowing `paths` only narrows where findings ANCHOR,
            # not what the analysis sees
            "shared-state-race": RuleConfig(paths=("",)),
            "lock-order": RuleConfig(paths=("",)),
            "jit-recompile-risk": RuleConfig(
                paths=("",),
                options={
                    # width-menu snappers (ops/topk.py): a static arg
                    # routed through one of these is pinned to the
                    # BATCH_WIDTHS/_K_WIDTHS menus and cannot drift
                    "snap_calls": ["serving_k", "serving_batch"],
                    # factory-backed jit wrappers: plain functions whose
                    # named params compile-key a cached jit program
                    # (ops/topk._sharded_topk_fn behind the sharded
                    # serving dispatch) — same per-call-drift check as
                    # decorator-declared static args
                    "extra_entries": {"recommend_topk_sharded": ["k"]},
                },
            ),
        },
        exclude=("__pycache__/",),
    )
