"""Framework primitives: findings, rules, registry, suppressions.

A rule is a class with a stable ``rule_id``, registered via
:func:`register_rule`; the runner hands each rule a parsed
:class:`ModuleInfo` plus that rule's configuration and collects
:class:`Finding`s. Inline suppressions follow the syntax

    # pio: lint-ignore[rule-id]: justification text

either trailing the offending line or on a comment line directly above
it. The justification is REQUIRED — a bare ``lint-ignore`` is itself
reported (rule id ``bad-suppression``), as is one naming a rule that
does not exist. This keeps every waived invariant carrying its reason
in the diff, the way the reference's reviewers carried them in their
heads.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import Any, Callable, Iterable, Iterator

#: framework pseudo-rule for malformed/unknown suppression comments —
#: not in the registry (it cannot be suppressed or disabled)
BAD_SUPPRESSION = "bad-suppression"

_SUPPRESS_RE = re.compile(
    r"#\s*pio:\s*lint-ignore\[(?P<rules>[a-z0-9_,\s-]+)\]"
    r"(?::\s*(?P<why>\S.*))?"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One invariant violation, pinned to file:line."""

    rule_id: str
    path: str          #: path as given to the runner (repo-relative in CI)
    line: int          #: 1-based
    message: str
    col: int = 0

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule_id}] {self.message}"


@dataclasses.dataclass(frozen=True)
class Suppression:
    """A parsed ``lint-ignore`` comment."""

    rule_ids: tuple[str, ...]
    line: int            #: line the comment sits on
    justification: str   #: empty string when missing (=> bad-suppression)
    own_line: bool       #: comment-only line (suppresses the next code line)


class ModuleInfo:
    """A parsed source file handed to every applicable rule."""

    def __init__(self, path: str, source: str, tree: ast.Module,
                 relpath: str = ""):
        self.path = path
        self.source = source
        self.tree = tree
        #: package-relative path (forward slashes) when linted from a
        #: tree root; "" for ad-hoc single files. Rules with per-call
        #: path scoping (untimed-blocking-io's call_paths) match on it.
        self.relpath = relpath
        self.lines = source.splitlines()
        self._suppressions: tuple[Suppression, ...] | None = None
        self._parents: dict[ast.AST, ast.AST] | None = None
        self._stmt_ends: dict[int, int] | None = None

    @property
    def suppressions(self) -> tuple["Suppression", ...]:
        """Parsed lint-ignore comments, tokenized lazily: a warm cached
        run only pays the tokenize cost for modules that actually have
        project-pass findings to filter."""
        if self._suppressions is None:
            self._suppressions = parse_suppressions(self.source)
        return self._suppressions

    @property
    def parents(self) -> dict[ast.AST, ast.AST]:
        """child AST node -> parent, built lazily once per module."""
        if self._parents is None:
            self._parents = {
                child: parent
                for parent in ast.walk(self.tree)
                for child in ast.iter_child_nodes(parent)
            }
        return self._parents

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = node
        while cur in self.parents:
            cur = self.parents[cur]
            yield cur

    def _stmt_end(self, start: int) -> int:
        """Last physical line a suppression anchored at ``start`` covers.

        For a simple statement that is its full span — findings anchor
        to continuation lines (a ``dtype=`` keyword on line 2 of a
        call) and the waiver must reach them. For a COMPOUND statement
        (def/class/if/for/with/try) only the header is covered, up to
        the first body statement: one comment above a function must
        never silently waive every current and future violation inside
        its 100-line body."""
        if self._stmt_ends is None:
            self._stmt_ends = {}
            for node in ast.walk(self.tree):
                if not isinstance(node, ast.stmt):
                    continue
                body = getattr(node, "body", None)
                if isinstance(body, list) and body and isinstance(body[0], ast.stmt):
                    end = max(node.lineno, body[0].lineno - 1)
                else:
                    end = getattr(node, "end_lineno", node.lineno)
                self._stmt_ends[node.lineno] = max(
                    self._stmt_ends.get(node.lineno, 0), end)
        return self._stmt_ends.get(start, start)

    def suppressed_lines(self, rule_id: str) -> set[int]:
        """Code lines waived for ``rule_id`` (with a justification).

        A trailing suppression covers its own line — and, when that
        line STARTS a statement, the statement's continuation lines
        too (same span rule as own-line comments, so suppressing at
        the statement head always works)."""
        lines: set[int] = set()
        for sup in self.suppressions:
            if rule_id not in sup.rule_ids or not sup.justification:
                continue
            lines.add(sup.line)
            start = (_next_code_line(self.lines, sup.line)
                     if sup.own_line else sup.line)
            if start > 0:
                lines.update(range(start, self._stmt_end(start) + 1))
        return lines


def parse_suppressions(source: str) -> tuple[Suppression, ...]:
    """Tokenize-based scan so strings containing the magic text don't
    count — only real comments do."""
    found: list[Suppression] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return ()
    code_lines = {
        t.start[0]
        for t in tokens
        if t.type not in (
            tokenize.COMMENT, tokenize.NL, tokenize.NEWLINE,
            tokenize.INDENT, tokenize.DEDENT, tokenize.ENCODING,
            tokenize.ENDMARKER,
        )
    }
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        rule_ids = tuple(
            r.strip() for r in m.group("rules").split(",") if r.strip()
        )
        found.append(Suppression(
            rule_ids=rule_ids,
            line=tok.start[0],
            justification=(m.group("why") or "").strip(),
            own_line=tok.start[0] not in code_lines,
        ))
    return tuple(found)


def _next_code_line(lines: list[str], after: int) -> int:
    """First non-blank, non-comment line after ``after`` (1-based)."""
    for i in range(after, len(lines)):
        text = lines[i].strip()
        if text and not text.startswith("#"):
            return i + 1
    return -1


def suppression_findings(module: ModuleInfo, path: str) -> list[Finding]:
    """Framework-level findings: lint-ignore comments that are missing
    their justification or name an unknown rule."""
    findings: list[Finding] = []
    for sup in module.suppressions:
        if not sup.justification:
            findings.append(Finding(
                BAD_SUPPRESSION, path, sup.line,
                "lint-ignore requires a justification: "
                "`# pio: lint-ignore[rule]: why this is safe`",
            ))
        for rid in sup.rule_ids:
            if rid not in _REGISTRY:
                findings.append(Finding(
                    BAD_SUPPRESSION, path, sup.line,
                    f"lint-ignore names unknown rule {rid!r} "
                    f"(known: {', '.join(sorted(_REGISTRY))})",
                ))
    return findings


class Rule:
    """Base class for a lint rule.

    Subclasses set ``rule_id``/``description``/``default_paths`` and
    implement :meth:`check`. ``default_paths`` are package-relative
    prefixes ('' means the whole tree) that scope where the rule runs;
    per-run config may override them (see config.LintConfig).
    """

    rule_id: str = ""
    description: str = ""
    #: package-relative path prefixes this rule applies to by default
    default_paths: tuple[str, ...] = ("",)

    def check(self, module: ModuleInfo, options: dict[str, Any]) -> list[Finding]:
        raise NotImplementedError

    # -- shared AST helpers (used by several rules) --------------------------

    @staticmethod
    def call_name(node: ast.Call) -> str | None:
        """Trailing name of the called object: ``urlopen`` for both
        ``urlopen(...)`` and ``urllib.request.urlopen(...)``."""
        if isinstance(node.func, ast.Attribute):
            return node.func.attr
        if isinstance(node.func, ast.Name):
            return node.func.id
        return None

    @staticmethod
    def dotted_name(node: ast.AST) -> str | None:
        """``a.b.c`` for nested Attribute/Name chains, else None."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        return None

    @staticmethod
    def walk_with_stack(
        tree: ast.AST,
    ) -> Iterator[tuple[ast.AST, tuple[str, ...]]]:
        """Yield (node, enclosing def/class qualname stack) pairs."""

        def visit(node: ast.AST, stack: tuple[str, ...]):
            yield node, stack
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                stack = stack + (node.name,)
            for child in ast.iter_child_nodes(node):
                yield from visit(child, stack)

        yield from visit(tree, ())


class ProjectRule(Rule):
    """A rule with a whole-program pass.

    The runner builds one :class:`analysis.project.ProjectModel` from
    every parsed module in the run and hands it to
    :meth:`check_project` AFTER the per-module phase. Findings must
    carry the package-relative ``path`` of the module they anchor to —
    the runner applies that module's suppressions and the rule's path
    scope to them exactly as it does for per-module findings.

    ``check`` defaults to no per-module findings so a ProjectRule can
    be purely global; hybrids may implement both.
    """

    def check(self, module: ModuleInfo, options: dict[str, Any]) -> list[Finding]:
        return []

    def check_project(self, project: "Any",
                      options: dict[str, Any]) -> list[Finding]:
        raise NotImplementedError


_REGISTRY: dict[str, Rule] = {}


def register_rule(cls: type[Rule]) -> type[Rule]:
    """Class decorator adding an instance to the global registry."""
    if not cls.rule_id:
        raise ValueError(f"{cls.__name__} has no rule_id")
    if cls.rule_id == BAD_SUPPRESSION:
        raise ValueError(f"rule id {BAD_SUPPRESSION!r} is reserved")
    if cls.rule_id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.rule_id!r}")
    _REGISTRY[cls.rule_id] = cls()
    return cls


def all_rules() -> dict[str, Rule]:
    return dict(_REGISTRY)


def get_rule(rule_id: str) -> Rule:
    return _REGISTRY[rule_id]
