"""CI-facing output formats: SARIF 2.1.0 and finding baselines.

A baseline is a JSON snapshot of accepted legacy findings; a run with
``--baseline`` reports (and fails on) only findings NOT in it, so a
stricter rule can land before the tree is fully clean. Fingerprints
are (rule, path, message) — line numbers shift with unrelated edits
and deliberately do not participate.
"""

from __future__ import annotations

import json
from typing import Iterable

from predictionio_tpu.analysis.core import Finding

BASELINE_VERSION = 1


def to_sarif(findings: list[Finding], rule_descriptions: dict[str, str],
             tool_version: str = "0") -> str:
    rules_seen = sorted({f.rule_id for f in findings})
    return json.dumps({
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "pio-lint",
                "version": tool_version,
                "informationUri":
                    "https://example.invalid/predictionio_tpu/docs/static-analysis.md",
                "rules": [
                    {"id": rid,
                     "shortDescription": {
                         "text": rule_descriptions.get(rid, rid)}}
                    for rid in rules_seen
                ],
            }},
            "results": [
                {
                    "ruleId": f.rule_id,
                    "level": "error",
                    "message": {"text": f.message},
                    "locations": [{
                        "physicalLocation": {
                            "artifactLocation": {"uri": f.path},
                            "region": {
                                "startLine": max(f.line, 1),
                                "startColumn": f.col + 1,
                            },
                        },
                    }],
                }
                for f in findings
            ],
        }],
    }, indent=2)


def _fingerprint(f: Finding) -> tuple[str, str, str]:
    return (f.rule_id, f.path, f.message)


def write_baseline(path: str, findings: Iterable[Finding]) -> int:
    items = [
        {"rule": f.rule_id, "path": f.path, "line": f.line,
         "message": f.message}
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule_id))
    ]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": BASELINE_VERSION, "findings": items}, fh,
                  indent=2)
        fh.write("\n")
    return len(items)


def load_baseline(path: str) -> set[tuple[str, str, str]]:
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"unsupported baseline version {doc.get('version')!r} in {path}")
    return {
        (d["rule"], d["path"], d["message"])
        for d in doc.get("findings", ())
    }


def apply_baseline(
    findings: list[Finding], accepted: set[tuple[str, str, str]],
) -> tuple[list[Finding], int]:
    """(new findings, count suppressed by the baseline)."""
    fresh = [f for f in findings if _fingerprint(f) not in accepted]
    return fresh, len(findings) - len(fresh)
