"""Per-file lint result cache.

Entries are keyed by (relpath) and validated against (mtime, size) plus
a run-wide *rules fingerprint* covering the effective rule config AND
the analysis package's own sources — editing a rule invalidates
everything, editing one module invalidates one entry. Only per-module
findings are cached (the project pass is whole-program by definition
and always re-runs), so a warm run pays parse + fact extraction but
skips every per-module rule walk and the suppression tokenize.

The cache is best-effort: unreadable/corrupt files and write failures
degrade to a cold run, never to an error.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Any

from predictionio_tpu.analysis.core import Finding

_VERSION = 1


def default_cache_path(root: str) -> str:
    """~/.cache/pio-lint/<hash-of-root>.json (overridable via
    $PIO_LINT_CACHE_DIR)."""
    base = os.environ.get("PIO_LINT_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "pio-lint")
    digest = hashlib.sha256(os.path.abspath(root).encode()).hexdigest()[:12]
    return os.path.join(base, f"{digest}.json")


def rules_fingerprint(config: Any, rule_ids: Any = None) -> str:
    """Hash of the effective rule policy + the analysis package source
    state (any rule/framework edit must invalidate the cache)."""
    h = hashlib.sha256()
    h.update(repr(sorted(
        (rid, rc.enabled, rc.paths, sorted(map(repr, rc.options.items())))
        for rid, rc in config.rules.items())).encode())
    h.update(repr(tuple(config.exclude)).encode())
    h.update(repr(sorted(rule_ids) if rule_ids is not None else None).encode())
    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames.sort()
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            fpath = os.path.join(dirpath, fname)
            try:
                st = os.stat(fpath)
            except OSError:
                continue
            rel = os.path.relpath(fpath, pkg_dir)
            h.update(f"{rel}:{st.st_mtime_ns}:{st.st_size};".encode())
    return h.hexdigest()


class LintCache:
    """Load-mutate-save wrapper around one cache file."""

    def __init__(self, path: str, fingerprint: str):
        self.path = path
        self.fingerprint = fingerprint
        self.hits = 0
        self.misses = 0
        self._files: dict[str, dict] = {}
        self._dirty = False
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path, encoding="utf-8") as f:
                doc = json.load(f)
            if (doc.get("version") == _VERSION
                    and doc.get("fingerprint") == self.fingerprint):
                self._files = doc.get("files", {})
        except (OSError, ValueError):
            pass

    def get(self, relpath: str, mtime_ns: int,
            size: int) -> list[Finding] | None:
        entry = self._files.get(relpath)
        if (entry is None or entry.get("mtime_ns") != mtime_ns
                or entry.get("size") != size):
            self.misses += 1
            return None
        self.hits += 1
        return [
            Finding(d["rule"], d["path"], d["line"], d["message"],
                    d.get("col", 0))
            for d in entry.get("findings", ())
        ]

    def put(self, relpath: str, mtime_ns: int, size: int,
            findings: list[Finding]) -> None:
        self._files[relpath] = {
            "mtime_ns": mtime_ns,
            "size": size,
            "findings": [
                {"rule": f.rule_id, "path": f.path, "line": f.line,
                 "col": f.col, "message": f.message}
                for f in findings
            ],
        }
        self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        doc = {"version": _VERSION, "fingerprint": self.fingerprint,
               "files": self._files}
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            fd, tmp = tempfile.mkstemp(
                dir=os.path.dirname(self.path) or ".", suffix=".tmp")
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(doc, f)
            os.replace(tmp, self.path)
        except OSError:
            pass
