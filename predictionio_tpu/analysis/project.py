"""Whole-program model backing the project-level lint passes.

``ProjectModel`` is built once per run from every parsed module and
resolves what a single-file pass cannot: the import graph, a
class/attribute model with per-class lock inventory, a call graph over
resolvable receivers (``self.m()``, typed locals/params, ``self.attr``
chains, module functions through imports), every thread-spawn /
executor-submit / timer site with the objects it hands across the
boundary, lock-acquisition spans, and the registry of ``@jax.jit`` /
``pjit`` entry points with their static-argument menus.

What it deliberately gives up on (documented for rule authors and in
docs/static-analysis.md):

- untyped receivers — a call through a bare parameter or a container
  subscript (``self._queue.get()``) resolves to nothing, so state that
  only travels through such an edge is invisible to the race pass (the
  per-file ``lock-discipline`` rule stays on as the fallback there);
- instance identity — locks are identified by ``(class, attr)`` or
  ``(module, name)``, not by object, so edges reached through a
  non-``self`` receiver of the holder's own class are skipped rather
  than risk a different-instance false positive;
- nested ``def`` thread targets — ``Thread(target=runner)`` where
  ``runner`` is a closure is not treated as an entry (its accesses
  would be attributed to the enclosing function);
- dynamic dispatch, ``getattr``, monkey-patching, and anything behind
  ``exec``.

Two soundness refinements keep the race pass usable on real code:

- writes in ``__init__``/``__post_init__`` via ``self``, and accesses
  through a local name bound to a constructor call in the same
  function, are pre-publication and excluded;
- a function whose every resolved call site holds lock L inherits L
  (3-round intersection fixpoint), so the ``_swap``-style "caller
  holds the lock" idiom does not false-positive.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Any, Iterable

from predictionio_tpu.analysis.core import ModuleInfo, Rule

#: constructors (threading.*) whose assignment marks an attr/var a lock;
#: value = reentrant (with-ing one you already hold is legal)
LOCK_CTORS = {
    "Lock": False,
    "RLock": True,
    # Condition() wraps an RLock by default
    "Condition": True,
    "Semaphore": False,
    "BoundedSemaphore": False,
}

#: lock identity for a with-expression we could not resolve but that
#: looks lock-ish — conservatively treated as matching every lock
WILDCARD_LOCK = ("?", "?")

READ, WRITE = "read", "write"

_THREAD_CTORS = ("Thread",)
_TIMER_CTORS = ("Timer",)

_LOCKISH = ("lock", "mutex", "_cv", "cond")


def _lockish(name: str) -> bool:
    low = name.lower()
    return any(tag in low for tag in _LOCKISH)


def module_key(relpath: str) -> str:
    """``fleet/gateway.py`` -> ``fleet.gateway``; ``fleet/__init__.py``
    -> ``fleet`` (the package itself)."""
    key = relpath[:-3] if relpath.endswith(".py") else relpath
    key = key.replace("/", ".")
    if key.endswith(".__init__"):
        key = key[: -len(".__init__")]
    return key or "__init__"


@dataclasses.dataclass
class ClassModel:
    key: str                 #: ``fleet.gateway:EngineGroup``
    name: str
    module: str              #: package-relative path
    node: ast.ClassDef
    methods: dict[str, ast.FunctionDef] = dataclasses.field(default_factory=dict)
    properties: set[str] = dataclasses.field(default_factory=set)
    #: attr -> reentrant? for attrs assigned a threading lock ctor
    lock_attrs: dict[str, bool] = dataclasses.field(default_factory=dict)
    #: attr -> class key, from ``self.x = Cls(...)`` / annotations
    attr_types: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class AttrAccess:
    """One read/write of ``<cls_key>.<attr>`` observed in ``func``."""

    cls_key: str
    attr: str
    kind: str                #: READ or WRITE
    func: str                #: function unit key where it happens
    module: str              #: package-relative path of that unit
    line: int
    col: int
    node: ast.AST
    via_self: bool
    #: pre-publication (ctor-local object / __init__ self-write)
    fresh: bool = False


@dataclasses.dataclass
class CallEdge:
    callee: str              #: function unit key
    node: ast.Call | ast.Attribute
    #: receiver is literally ``self`` — lock identity provably shared
    same_instance: bool


@dataclasses.dataclass
class Acquire:
    lock: tuple[str, str]
    node: ast.With


@dataclasses.dataclass
class Spawn:
    """A Thread/Timer construction or an executor ``.submit``."""

    kind: str                #: "thread" | "timer" | "submit"
    target: str              #: function unit key the new context enters
    module: str
    line: int
    func: str                #: spawning function unit key
    #: target param name -> class key, for args escaping the boundary
    bindings: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class JitEntry:
    key: str
    name: str
    module: str
    line: int
    params: tuple[str, ...]
    static_params: tuple[str, ...]


@dataclasses.dataclass
class JitCallSite:
    entry: str               #: JitEntry key
    node: ast.Call
    func: str                #: calling unit key
    module: str


class FunctionUnit:
    """One top-level ``def`` (module function or method); nested defs
    fold into their parent unit."""

    def __init__(self, key: str, module: str, mkey: str,
                 node: ast.FunctionDef, cls: ClassModel | None, name: str):
        self.key = key
        self.module = module
        self.mkey = mkey
        self.node = node
        self.cls = cls
        self.name = name
        self.env: dict[str, str] = {}
        self.assigns: dict[str, ast.expr] = {}
        self.calls: list[CallEdge] = []
        self.accesses: list[AttrAccess] = []
        self.acquires: list[Acquire] = []
        #: local names bound to a constructor call in this unit
        self.fresh_locals: set[str] = set()


_JIT_DECOS = ("jit", "pjit", "instrumented_jit")


class ProjectModel:
    """See module docstring. Construct with ``{relpath: ModuleInfo}``."""

    def __init__(self, modules: dict[str, ModuleInfo]):
        self.modules = modules
        self.mkeys: dict[str, str] = {module_key(rp): rp for rp in modules}
        self.classes: dict[str, ClassModel] = {}
        self.functions: dict[str, FunctionUnit] = {}
        #: mkey -> local name -> dotted import target
        self.imports: dict[str, dict[str, str]] = {}
        #: mkey -> module-level var -> class key
        self.module_var_types: dict[str, dict[str, str]] = {}
        #: mkey -> module-level lock var -> reentrant?
        self.module_locks: dict[str, dict[str, bool]] = {}
        #: mkey -> UPPERCASE module-level constant names
        self.module_constants: dict[str, set[str]] = {}
        self.spawns: list[Spawn] = []
        self.jit_entries: dict[str, JitEntry] = {}
        self.jit_call_sites: list[JitCallSite] = []

        self._thread_reach: dict[str, Spawn] | None = None
        self._inherited: dict[str, frozenset] = {}
        self._closure_memo: dict[str, frozenset] = {}
        self._callers: dict[str, list[tuple[str, ast.AST]]] = {}

        for rp, mod in sorted(modules.items()):
            self._collect_module(rp, mod)
        # resolve class attr annotations now that every class exists
        for cls in self.classes.values():
            self._finish_class(cls)
        self._resolve_module_var_types()
        for unit in self.functions.values():
            self._build_env(unit)
        self._collect_spawns()
        self._seed_spawn_bindings()
        for unit in self.functions.values():
            self._collect_facts(unit)
        self._index_callers()
        self._solve_inherited_locks()

    # ------------------------------------------------------------------
    # pass A: symbols
    # ------------------------------------------------------------------

    def _collect_module(self, relpath: str, mod: ModuleInfo) -> None:
        mkey = module_key(relpath)
        imports: dict[str, str] = {}
        self.imports[mkey] = imports
        self.module_var_types[mkey] = {}
        self.module_locks[mkey] = {}
        self.module_constants[mkey] = set()

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        imports[alias.asname] = alias.name
                    else:
                        # ``import a.b`` binds ``a``; attribute chains
                        # rejoin the rest at resolution time
                        top = alias.name.split(".")[0]
                        imports[top] = top
            elif isinstance(node, ast.ImportFrom):
                base = self._import_base(mkey, node)
                if base is None:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    imports[local] = f"{base}.{alias.name}" if base else alias.name

        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                self._collect_class(relpath, mkey, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = f"{mkey}:{node.name}"
                self.functions[key] = FunctionUnit(
                    key, relpath, mkey, node, None, node.name)
                self._maybe_jit_entry(key, relpath, mkey, node)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if name.isupper():
                    self.module_constants[mkey].add(name)
                ctor = self._lock_ctor(node.value)
                if ctor is not None:
                    self.module_locks[mkey][name] = ctor
                elif isinstance(node.value, ast.Call):
                    # module-level shared object: ``CURSOR = SharedCursor()``
                    self.module_var_types[mkey][name] = "?pending"
            elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                if node.target.id.isupper():
                    self.module_constants[mkey].add(node.target.id)

    def _import_base(self, mkey: str, node: ast.ImportFrom) -> str | None:
        if node.level == 0:
            return node.module or ""
        parts = mkey.split(".")
        # the module's package = mkey minus the final component (mkey of
        # an __init__ already IS the package)
        pkg = parts if self.mkeys.get(mkey, "").endswith("__init__.py") else parts[:-1]
        drop = node.level - 1
        if drop > len(pkg):
            return None
        base = pkg[: len(pkg) - drop]
        if node.module:
            base = base + node.module.split(".")
        return ".".join(base)

    def _collect_class(self, relpath: str, mkey: str, node: ast.ClassDef) -> None:
        key = f"{mkey}:{node.name}"
        cls = ClassModel(key=key, name=node.name, module=relpath, node=node)
        self.classes[key] = cls
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls.methods[item.name] = item
                for deco in item.decorator_list:
                    dotted = Rule.dotted_name(deco) or ""
                    if dotted.split(".")[-1] in ("property", "cached_property"):
                        cls.properties.add(item.name)
                fkey = f"{mkey}:{node.name}.{item.name}"
                self.functions[fkey] = FunctionUnit(
                    fkey, relpath, mkey, item, cls, f"{node.name}.{item.name}")
            elif isinstance(item, ast.AnnAssign) and isinstance(item.target, ast.Name):
                # dataclass-style field declaration
                cls.attr_types.setdefault(item.target.id, "?ann")
        # lock attrs / attr types from self-assignments anywhere in the body
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Assign) or len(sub.targets) != 1:
                continue
            tgt = sub.targets[0]
            if not (isinstance(tgt, ast.Attribute) and
                    isinstance(tgt.value, ast.Name) and tgt.value.id == "self"):
                continue
            ctor = self._lock_ctor(sub.value)
            if ctor is not None:
                cls.lock_attrs[tgt.attr] = ctor

    def _finish_class(self, cls: ClassModel) -> None:
        mkey = module_key(cls.module)
        # annotated fields: resolve the annotation to a class now
        for item in cls.node.body:
            if isinstance(item, ast.AnnAssign) and isinstance(item.target, ast.Name):
                resolved = self._annotation_class(mkey, item.annotation)
                if resolved:
                    cls.attr_types[item.target.id] = resolved
                elif cls.attr_types.get(item.target.id) == "?ann":
                    del cls.attr_types[item.target.id]
        # self.x = Cls(...), annotated self.x: Cls, and self.x = <param>
        # where the enclosing method annotates the param
        for meth in cls.methods.values():
            params: dict[str, str] = {}
            margs = meth.args
            for a in (list(margs.posonlyargs) + list(margs.args)
                      + list(margs.kwonlyargs)):
                t = self._annotation_class(mkey, a.annotation)
                if t:
                    params[a.arg] = t
            for sub in ast.walk(meth):
                tgt = None
                ann = None
                value = None
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    tgt, value = sub.targets[0], sub.value
                elif isinstance(sub, ast.AnnAssign):
                    tgt, ann, value = sub.target, sub.annotation, sub.value
                if not (isinstance(tgt, ast.Attribute) and
                        isinstance(tgt.value, ast.Name) and tgt.value.id == "self"):
                    continue
                resolved = (self._annotation_class(mkey, ann)
                            if ann is not None else None)
                if resolved is None and isinstance(value, ast.Call):
                    resolved = self._resolve_class(
                        mkey, Rule.dotted_name(value.func) or "")
                if resolved is None and isinstance(value, ast.Name):
                    resolved = params.get(value.id)
                if resolved:
                    cls.attr_types.setdefault(tgt.attr, resolved)

    @staticmethod
    def _lock_ctor(value: ast.AST) -> bool | None:
        """reentrant-flag when ``value`` constructs a threading lock."""
        if not isinstance(value, ast.Call):
            return None
        name = Rule.dotted_name(value.func) or ""
        last = name.split(".")[-1]
        return LOCK_CTORS.get(last)

    # ------------------------------------------------------------------
    # name resolution
    # ------------------------------------------------------------------

    def _find_module(self, dotted: str) -> tuple[str, list[str]] | None:
        """Split ``dotted`` into (known module key, symbol chain),
        matching the longest module prefix; up to two leading package
        components (e.g. ``predictionio_tpu.``) may be stripped."""
        parts = dotted.split(".")
        for strip in range(0, 3):
            rest = parts[strip:]
            if not rest:
                continue
            for cut in range(len(rest), 0, -1):
                cand = ".".join(rest[:cut])
                if cand in self.mkeys:
                    return cand, rest[cut:]
        return None

    def _resolve_symbol(self, mkey: str, dotted: str) -> tuple[str, str] | None:
        """Resolve ``dotted`` as seen from module ``mkey`` to
        ("class"|"func", key)."""
        if not dotted:
            return None
        parts = dotted.split(".")
        imports = self.imports.get(mkey, {})
        if parts[0] not in imports:
            got = self._symbol_in(mkey, parts)
            if got is not None:
                return got
        else:
            dotted = ".".join([imports[parts[0]]] + parts[1:])
        # absolute path (possibly package-prefixed) to another module
        found = self._find_module(dotted)
        if not found:
            return None
        mk, chain = found
        return self._symbol_in(mk, chain)

    def _symbol_in(self, mk: str, chain: list[str]) -> tuple[str, str] | None:
        if not chain:
            return None
        ckey = f"{mk}:{chain[0]}"
        if len(chain) == 1:
            if ckey in self.classes:
                return "class", ckey
            if ckey in self.functions:
                return "func", ckey
            return None
        if len(chain) == 2 and ckey in self.classes:
            fkey = f"{ckey}.{chain[1]}"
            if fkey in self.functions:
                return "func", fkey
        return None

    def _resolve_class(self, mkey: str, dotted: str) -> str | None:
        got = self._resolve_symbol(mkey, dotted)
        return got[1] if got and got[0] == "class" else None

    def _annotation_class(self, mkey: str, ann: ast.AST | None) -> str | None:
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            return (self._annotation_class(mkey, ann.left)
                    or self._annotation_class(mkey, ann.right))
        if isinstance(ann, ast.Constant):   # the None half of "X | None"
            return None
        if isinstance(ann, ast.Subscript):
            base = (Rule.dotted_name(ann.value) or "").split(".")[-1]
            if base in ("Optional", "Final", "Annotated", "ClassVar"):
                inner = ann.slice
                if isinstance(inner, ast.Tuple) and inner.elts:
                    inner = inner.elts[0]
                return self._annotation_class(mkey, inner)
            return None   # generics (dict[str, X]) are a documented give-up
        dotted = Rule.dotted_name(ann)
        if dotted:
            return self._resolve_class(mkey, dotted)
        return None

    # ------------------------------------------------------------------
    # pass B1: per-unit type environments
    # ------------------------------------------------------------------

    def _build_env(self, unit: FunctionUnit) -> None:
        env = unit.env
        if unit.cls is not None:
            env["self"] = unit.cls.key
        args = unit.node.args
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            t = self._annotation_class(unit.mkey, a.annotation)
            if t:
                env[a.arg] = t
        # two mini-passes so ``y = x`` after ``x = Cls()`` resolves
        for _ in range(2):
            for node in ast.walk(unit.node):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    name = node.targets[0].id
                    unit.assigns[name] = node.value
                    t = self._expr_class(unit, node.value)
                    if t:
                        env[name] = t
                        if isinstance(node.value, ast.Call):
                            unit.fresh_locals.add(name)
                elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                    t = self._annotation_class(unit.mkey, node.annotation)
                    if t:
                        env[node.target.id] = t
        # resolve module-level shared objects visible from this unit
        mod_vars = self.module_var_types.get(unit.mkey, {})
        for name, t in mod_vars.items():
            env.setdefault(name, t)

    def _expr_class(self, unit: FunctionUnit, expr: ast.AST) -> str | None:
        got: str | None = None
        if isinstance(expr, ast.Name):
            got = unit.env.get(expr.id)
        elif isinstance(expr, ast.Call):
            sym = self._resolve_symbol(unit.mkey, Rule.dotted_name(expr.func) or "")
            if sym and sym[0] == "class":
                got = sym[1]
            elif sym and sym[0] == "func":
                fn = self.functions[sym[1]]
                got = self._annotation_class(fn.mkey, fn.node.returns)
            elif isinstance(expr.func, ast.Attribute):
                # method call on a typed receiver with a typed return
                owner = self._expr_class(unit, expr.func.value)
                if owner and expr.func.attr in self.classes[owner].methods:
                    m = self.classes[owner].methods[expr.func.attr]
                    got = self._annotation_class(
                        module_key(self.classes[owner].module), m.returns)
        elif isinstance(expr, ast.Attribute):
            owner = self._expr_class(unit, expr.value)
            if owner is not None:
                got = self.classes[owner].attr_types.get(expr.attr)
        return got if got in self.classes else None

    # ------------------------------------------------------------------
    # module-level shared objects (needs classes + imports, no env)
    # ------------------------------------------------------------------

    def _resolve_module_var_types(self) -> None:
        for mkey in list(self.module_var_types):
            relpath = self.mkeys.get(mkey)
            if not relpath:
                continue
            mod = self.modules[relpath]
            for node in mod.tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call):
                    resolved = self._resolve_class(
                        mkey, Rule.dotted_name(node.value.func) or "")
                    name = node.targets[0].id
                    if resolved:
                        self.module_var_types[mkey][name] = resolved
                    else:
                        self.module_var_types[mkey].pop(name, None)

    # ------------------------------------------------------------------
    # pass B: spawns then facts
    # ------------------------------------------------------------------

    def _collect_spawns(self) -> None:
        for unit in self.functions.values():
            for node in ast.walk(unit.node):
                if not isinstance(node, ast.Call):
                    continue
                spawn = self._spawn_of(unit, node)
                if spawn is not None:
                    self.spawns.append(spawn)

    def _spawn_of(self, unit: FunctionUnit, call: ast.Call) -> Spawn | None:
        last = (Rule.dotted_name(call.func) or "").split(".")[-1]
        target_expr: ast.AST | None = None
        escaped: list[ast.AST] = []
        kind = None
        if last in _THREAD_CTORS:
            kind = "thread"
            for kw in call.keywords:
                if kw.arg == "target":
                    target_expr = kw.value
                elif kw.arg == "args" and isinstance(kw.value, (ast.Tuple, ast.List)):
                    escaped = list(kw.value.elts)
        elif last in _TIMER_CTORS:
            kind = "timer"
            if len(call.args) >= 2:
                target_expr = call.args[1]
            for kw in call.keywords:
                if kw.arg == "function":
                    target_expr = kw.value
                elif kw.arg == "args" and isinstance(kw.value, (ast.Tuple, ast.List)):
                    escaped = list(kw.value.elts)
        elif isinstance(call.func, ast.Attribute) and call.func.attr == "submit" \
                and call.args:
            kind = "submit"
            target_expr = call.args[0]
            escaped = list(call.args[1:])
        if kind is None or target_expr is None:
            return None
        target = self._callable_key(unit, target_expr)
        if target is None:
            return None
        bindings: dict[str, str] = {}
        if escaped:
            tunit = self.functions[target]
            params = [a.arg for a in tunit.node.args.args]
            if tunit.cls is not None and params and params[0] == "self":
                params = params[1:]
            for p, arg in zip(params, escaped):
                t = self._expr_class(unit, arg)
                if t:
                    bindings[p] = t
        return Spawn(kind=kind, target=target, module=unit.module,
                     line=call.lineno, func=unit.key, bindings=bindings)

    def _callable_key(self, unit: FunctionUnit, expr: ast.AST) -> str | None:
        """Resolve a callable reference (not a call) to a unit key."""
        if isinstance(expr, ast.Attribute):
            owner = self._expr_class(unit, expr.value)
            if owner and expr.attr in self.classes[owner].methods:
                return f"{owner}.{expr.attr}"
            got = self._resolve_symbol(unit.mkey, Rule.dotted_name(expr) or "")
            if got and got[0] == "func":
                return got[1]
            return None
        if isinstance(expr, ast.Name):
            got = self._resolve_symbol(unit.mkey, expr.id)
            if got and got[0] == "func":
                return got[1]
            if got and got[0] == "class":
                init = f"{got[1]}.__init__"
                return init if init in self.functions else None
        return None

    def _seed_spawn_bindings(self) -> None:
        reseed: set[str] = set()
        for spawn in self.spawns:
            if not spawn.bindings:
                continue
            tunit = self.functions[spawn.target]
            for p, t in spawn.bindings.items():
                if tunit.env.setdefault(p, t) == t:
                    reseed.add(tunit.key)
        # param typing may unlock ``x = param`` propagation inside
        for key in reseed:
            self._build_env(self.functions[key])

    def _collect_facts(self, unit: FunctionUnit) -> None:
        init_like = unit.cls is not None and unit.node.name in (
            "__init__", "__post_init__")
        mod = self.modules[unit.module]
        for node in ast.walk(unit.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = self._lock_id(unit, item.context_expr)
                    if lid is not None:
                        unit.acquires.append(Acquire(lock=lid, node=node))
            elif isinstance(node, ast.Call):
                self._record_call(unit, node)
            elif isinstance(node, ast.Attribute):
                self._record_access(unit, node, mod, init_like)
            elif isinstance(node, ast.AugAssign) and isinstance(node.target, ast.Attribute):
                # the Store-ctx target is also a read
                self._record_access(unit, node.target, mod, init_like,
                                    force_kind=READ)

    def _record_call(self, unit: FunctionUnit, call: ast.Call) -> None:
        func = call.func
        callee: str | None = None
        same_instance = False
        if isinstance(func, ast.Attribute):
            owner = self._expr_class(unit, func.value)
            if owner and func.attr in self.classes[owner].methods:
                callee = f"{owner}.{func.attr}"
                same_instance = (isinstance(func.value, ast.Name)
                                 and func.value.id == "self")
            else:
                got = self._resolve_symbol(unit.mkey, Rule.dotted_name(func) or "")
                if got and got[0] == "func":
                    callee = got[1]
        elif isinstance(func, ast.Name):
            got = self._resolve_symbol(unit.mkey, func.id)
            if got and got[0] == "func":
                callee = got[1]
            elif got and got[0] == "class":
                init = f"{got[1]}.__init__"
                callee = init if init in self.functions else None
        if callee is not None and callee in self.functions:
            unit.calls.append(CallEdge(callee=callee, node=call,
                                       same_instance=same_instance))
            if callee in self.jit_entries:
                self.jit_call_sites.append(JitCallSite(
                    entry=callee, node=call, func=unit.key, module=unit.module))

    def _record_access(self, unit: FunctionUnit, node: ast.Attribute,
                       mod: ModuleInfo, init_like: bool,
                       force_kind: str | None = None) -> None:
        owner = self._expr_class(unit, node.value)
        if owner is None:
            return
        cls = self.classes[owner]
        if node.attr in cls.methods and node.attr not in cls.properties:
            return                          # method reference, not state
        if node.attr in cls.lock_attrs:
            return                          # the lock itself is not data
        via_self = isinstance(node.value, ast.Name) and node.value.id == "self"
        if node.attr in cls.properties:
            # a property read is a call to its getter
            if isinstance(node.ctx, ast.Load):
                unit.calls.append(CallEdge(
                    callee=f"{owner}.{node.attr}", node=node,
                    same_instance=via_self))
            return
        if force_kind is not None:
            kind = force_kind
        elif isinstance(node.ctx, (ast.Store, ast.Del)):
            kind = WRITE
        else:
            kind = READ
        fresh = bool(
            (init_like and via_self)
            or (isinstance(node.value, ast.Name)
                and node.value.id in unit.fresh_locals)
        )
        unit.accesses.append(AttrAccess(
            cls_key=owner, attr=node.attr, kind=kind, func=unit.key,
            module=unit.module, line=node.lineno, col=node.col_offset,
            node=node, via_self=via_self, fresh=fresh))

    def _lock_id(self, unit: FunctionUnit, expr: ast.AST) -> tuple[str, str] | None:
        if isinstance(expr, ast.Attribute):
            owner = self._expr_class(unit, expr.value)
            if owner is not None and (expr.attr in self.classes[owner].lock_attrs
                                      or _lockish(expr.attr)):
                return (owner, expr.attr)
            return WILDCARD_LOCK if _lockish(expr.attr) else None
        if isinstance(expr, ast.Name):
            if expr.id in self.module_locks.get(unit.mkey, {}):
                return (f"module:{unit.mkey}", expr.id)
            return WILDCARD_LOCK if _lockish(expr.id) else None
        try:
            text = ast.unparse(expr)
        except Exception:
            return None
        return WILDCARD_LOCK if _lockish(text) else None

    def lock_reentrant(self, lock: tuple[str, str]) -> bool:
        owner, name = lock
        if owner.startswith("module:"):
            return self.module_locks.get(owner[len("module:"):], {}).get(name, False)
        cls = self.classes.get(owner)
        if cls is None:
            return False
        return cls.lock_attrs.get(name, False)

    # ------------------------------------------------------------------
    # jit entries
    # ------------------------------------------------------------------

    def _maybe_jit_entry(self, key: str, relpath: str, mkey: str,
                         node: ast.FunctionDef) -> None:
        static: set[str] = set()
        nums: set[int] = set()
        is_jit = False
        for deco in node.decorator_list:
            call = deco if isinstance(deco, ast.Call) else None
            base = call.func if call else deco
            dotted = (Rule.dotted_name(base) or "").split(".")[-1]
            if dotted in _JIT_DECOS:
                is_jit = True
            elif dotted == "partial" and call and call.args:
                inner = (Rule.dotted_name(call.args[0]) or "").split(".")[-1]
                if inner in _JIT_DECOS:
                    is_jit = True
            if not is_jit or call is None:
                continue
            for kw in call.keywords:
                if kw.arg == "static_argnames":
                    static |= set(_const_strs(kw.value))
                elif kw.arg == "static_argnums":
                    nums |= set(_const_ints(kw.value))
        if not is_jit:
            return
        params = tuple(a.arg for a in (list(node.args.posonlyargs)
                                       + list(node.args.args)))
        for i in nums:
            if 0 <= i < len(params):
                static.add(params[i])
        self.jit_entries[key] = JitEntry(
            key=key, name=node.name, module=relpath, line=node.lineno,
            params=params, static_params=tuple(sorted(static)))

    # ------------------------------------------------------------------
    # derived graphs
    # ------------------------------------------------------------------

    def _index_callers(self) -> None:
        for unit in self.functions.values():
            for edge in unit.calls:
                self._callers.setdefault(edge.callee, []).append(
                    (unit.key, edge.node))

    def thread_reachable(self) -> dict[str, Spawn]:
        """function unit key -> the Spawn whose context first reaches it."""
        if self._thread_reach is not None:
            return self._thread_reach
        reach: dict[str, Spawn] = {}
        frontier: list[tuple[str, Spawn]] = []
        for spawn in self.spawns:
            if spawn.target not in reach:
                reach[spawn.target] = spawn
                frontier.append((spawn.target, spawn))
        while frontier:
            key, origin = frontier.pop()
            unit = self.functions.get(key)
            if unit is None:
                continue
            for edge in unit.calls:
                if edge.callee not in reach:
                    reach[edge.callee] = origin
                    frontier.append((edge.callee, origin))
        self._thread_reach = reach
        return reach

    def ancestor_locks(self, unit: FunctionUnit, node: ast.AST) -> frozenset:
        """Locks held at ``node`` by enclosing ``with`` statements in
        the same unit (inherited caller-held locks NOT included)."""
        mod = self.modules[unit.module]
        held: set = set()
        for anc in mod.ancestors(node):
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                for item in anc.items:
                    lid = self._lock_id(unit, item.context_expr)
                    if lid is not None:
                        held.add(lid)
            if anc is unit.node:
                break
        return frozenset(held)

    def inherited_locks(self, key: str) -> frozenset:
        """Locks held at EVERY resolved call site of ``key`` (empty for
        thread entries and functions with no resolved callers)."""
        return self._inherited.get(key, frozenset())

    def _solve_inherited_locks(self) -> None:
        entries = {s.target for s in self.spawns}
        inherited: dict[str, frozenset] = {k: frozenset() for k in self.functions}
        for _ in range(3):
            nxt: dict[str, frozenset] = {}
            for key in self.functions:
                callers = self._callers.get(key)
                if not callers or key in entries:
                    nxt[key] = frozenset()
                    continue
                acc: frozenset | None = None
                for caller_key, node in callers:
                    caller = self.functions[caller_key]
                    held = self.ancestor_locks(caller, node) | inherited[caller_key]
                    acc = held if acc is None else (acc & held)
                nxt[key] = acc or frozenset()
            if nxt == inherited:
                break
            inherited = nxt
        self._inherited = inherited

    def locks_held_at(self, unit: FunctionUnit, node: ast.AST) -> frozenset:
        return self.ancestor_locks(unit, node) | self.inherited_locks(unit.key)

    def lock_closure(self, key: str, _depth: int = 0,
                     _seen: frozenset = frozenset()) -> frozenset:
        """All locks ``key`` may acquire, directly or through resolved
        calls (bounded depth, memoized)."""
        memo = self._closure_memo.get(key)
        if memo is not None:
            return memo
        if _depth > 10 or key in _seen:
            return frozenset()
        unit = self.functions.get(key)
        if unit is None:
            return frozenset()
        out: set = {a.lock for a in unit.acquires if a.lock != WILDCARD_LOCK}
        seen = _seen | {key}
        for edge in unit.calls:
            out |= self.lock_closure(edge.callee, _depth + 1, seen)
        result = frozenset(out)
        if not _seen:                       # only memoize complete walks
            self._closure_memo[key] = result
        return result

    def direct_acquires(self, key: str) -> frozenset:
        unit = self.functions.get(key)
        if unit is None:
            return frozenset()
        return frozenset(a.lock for a in unit.acquires
                         if a.lock != WILDCARD_LOCK)


def _const_strs(node: ast.AST) -> Iterable[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node.value
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            yield from _const_strs(e)


def _const_ints(node: ast.AST) -> Iterable[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        yield node.value
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            yield from _const_ints(e)


def lock_label(lock: tuple[str, str]) -> str:
    owner, name = lock
    if owner.startswith("module:"):
        return f"{owner[len('module:'):]}.{name}"
    return f"{owner.split(':')[-1]}.{name}" if owner != "?" else "<unresolved lock>"
