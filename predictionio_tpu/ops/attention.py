"""Attention kernels: full causal attention and ring attention for
sequence/context parallelism.

The reference has no sequence models (SURVEY.md §5 "long-context:
absent") — this is the TPU build's own scale axis, powering the
session-based sequential recommendation engine (models/seqrec.py). Long
sessions shard over a mesh "seq" axis: each device holds one block of
the sequence, and K/V blocks rotate around the ring with
``lax.ppermute`` while a flash-style online softmax accumulates partial
results — compute overlaps the ICI transfer and no device ever holds
the full sequence (Liu et al., Ring Attention; blockwise transformers).

All logits accumulate in f32 regardless of input dtype (bf16 inputs
recommended on TPU — the matmuls tile onto the MXU).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from predictionio_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_NEG = jnp.float32(-1e30)  # large-negative instead of -inf: keeps exp() NaN-free


def full_attention(
    q: jax.Array,  # (B, H, S, D)
    k: jax.Array,  # (B, H, S, D)
    v: jax.Array,  # (B, H, S, D)
    *,
    causal: bool = True,
    kv_mask: jax.Array | None = None,  # (B, S) 1=real, 0=pad
) -> jax.Array:
    """Reference single-device attention; returns (B, H, S, D) in q.dtype."""
    d = q.shape[-1]
    logits = jnp.einsum("bhsd,bhtd->bhst", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits / jnp.float32(math.sqrt(d))
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((s, t), dtype=bool))
        logits = jnp.where(cmask[None, None], logits, _NEG)
    if kv_mask is not None:
        logits = jnp.where(kv_mask[:, None, None, :].astype(bool), logits, _NEG)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhst,bhtd->bhsd", probs, v.astype(jnp.float32)
                      ).astype(q.dtype)


def blockwise_attention(
    q: jax.Array,  # (B, H, S, D)
    k: jax.Array,  # (B, H, S, D)
    v: jax.Array,  # (B, H, S, D)
    *,
    causal: bool = True,
    kv_mask: jax.Array | None = None,  # (B, S)
    q_block: int | None = None,
) -> jax.Array:
    """Memory-bounded, DIFFERENTIABLE attention: lax.scan over query
    tiles, each tile computing its (q_block, S) logits and softmax; the
    rematerialised body recomputes tile logits in the backward pass, so
    peak memory is O(B*H*q_block*S) instead of O(B*H*S^2).

    ``q_block=None`` (default) auto-picks the largest divisor of S
    that is <= 128 (falling back to S itself, one full tile), so
    default calls work at any S. The 128 target comes from the r5
    sweep on the real chip (S=4096 B=4 seqrec TRAIN step, fwd+bwd,
    order-independent across two sessions): 1024 → 168k, 512 → 170k,
    256 → 254k, 128 → 306-319k, 64 → 321k tokens/sec — smaller query
    tiles keep the remat backward's (q_block, S) logits VMEM-resident,
    and the curve is flat below 128. The old 512 default cost 1.8x.
    An EXPLICIT q_block must divide S (raises otherwise).

    This is the single-device long-context TRAINING path: full_attention
    materializes the (S, S) logits (~8.6 GB at S=16384, OOM on one
    v5e), the pallas flash kernel (ops/pallas_attention) is
    forward-only, and ring_attention needs a mesh "seq" axis. Matches
    full_attention to f32 rounding in both values and gradients
    (tests/test_attention.py). ``S`` must divide by ``q_block``; pad
    with ``kv_mask`` otherwise.
    """
    B, H, S, D = q.shape
    if kv_mask is None:
        kv_mask = jnp.ones((B, S), dtype=jnp.float32)
    if q_block is None:
        q_block = next((b for b in (128, 64, 32, 16, 8) if S % b == 0), S)
    q_block = min(q_block, S)
    if S % q_block:
        raise ValueError(f"S={S} must divide by q_block={q_block}")
    n_tiles = S // q_block
    scale = jnp.float32(1.0 / math.sqrt(D))
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    k_pos = lax.iota(jnp.int32, S)
    valid_k = kv_mask[:, None, None, :].astype(bool)       # (B, 1, 1, S)

    qt = q.reshape(B, H, n_tiles, q_block, D).transpose(2, 0, 1, 3, 4)

    def tile(_, xs):
        q_tile, t = xs                                     # (B, H, Tq, D)
        logits = jnp.einsum("bhsd,bhtd->bhst", q_tile.astype(jnp.float32),
                            kf) * scale                    # (B, H, Tq, S)
        valid = valid_k
        if causal:
            q_pos = t * q_block + lax.iota(jnp.int32, q_block)
            valid = valid & (q_pos[None, None, :, None] >= k_pos[None, None, None, :])
        logits = jnp.where(valid, logits, _NEG)
        probs = jax.nn.softmax(logits, axis=-1)
        # fully-masked rows (padding queries) get zero output
        any_valid = jnp.any(valid, axis=-1, keepdims=True)
        probs = jnp.where(any_valid, probs, 0.0)
        out = jnp.einsum("bhst,bhtd->bhsd", probs, vf)
        return None, out.astype(q.dtype)

    _, tiles = lax.scan(
        jax.checkpoint(tile), None,
        (qt, jnp.arange(n_tiles, dtype=jnp.int32)))
    return tiles.transpose(1, 2, 0, 3, 4).reshape(B, H, S, D)


def _ring_attention_local(
    q: jax.Array,        # (B, H, Sl, D) local query block
    k: jax.Array,        # (B, H, Sl, D) local key block (rotates)
    v: jax.Array,        # (B, H, Sl, D) local value block (rotates)
    kv_mask: jax.Array,  # (B, Sl) local key padding mask (rotates)
    *,
    axis_name: str,
    causal: bool,
) -> jax.Array:
    """Per-device body run under shard_map: online-softmax accumulation
    over ring-rotated K/V blocks."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, H, Sl, D = q.shape
    scale = jnp.float32(1.0 / math.sqrt(D))

    q_pos = idx * Sl + lax.iota(jnp.int32, Sl)          # global query positions
    block_pos = lax.iota(jnp.int32, Sl)

    qf = q.astype(jnp.float32)
    m0 = jnp.full((B, H, Sl), _NEG, dtype=jnp.float32)   # running max
    l0 = jnp.zeros((B, H, Sl), dtype=jnp.float32)        # running denominator
    o0 = jnp.zeros((B, H, Sl, D), dtype=jnp.float32)     # running numerator

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(i, carry):
        m, l, o, k_blk, v_blk, mask_blk = carry
        # the block arriving at step i originated on device (idx - i) mod n
        src = (idx - i) % n
        k_pos = src * Sl + block_pos
        logits = jnp.einsum("bhsd,bhtd->bhst", qf, k_blk.astype(jnp.float32))
        logits = logits * scale
        valid = mask_blk[:, None, None, :].astype(bool)
        if causal:
            valid = valid & (q_pos[None, None, :, None] >= k_pos[None, None, None, :])
        logits = jnp.where(valid, logits, _NEG)

        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        # blocks that are entirely masked contribute nothing; alpha/p stay
        # finite because _NEG - _NEG == 0 and exp(0)=1 is cancelled by the
        # seen-mask below
        seen = m_new > _NEG / 2
        alpha = jnp.where(seen, jnp.exp(m - m_new), 0.0)
        p = jnp.exp(logits - m_new[..., None])
        p = jnp.where(valid & seen[..., None], p, 0.0)
        l = l * alpha + jnp.sum(p, axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhst,bhtd->bhsd", p, v_blk.astype(jnp.float32))

        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        mask_blk = lax.ppermute(mask_blk, axis_name, perm)
        return m_new, l, o, k_blk, v_blk, mask_blk

    m, l, o, *_ = lax.fori_loop(0, n, step, (m0, l0, o0, k, v, kv_mask))
    out = o / jnp.maximum(l, 1e-20)[..., None]
    out = jnp.where((l > 0)[..., None], out, 0.0)
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    seq_axis: str = "seq",
    causal: bool = True,
    kv_mask: jax.Array | None = None,
) -> jax.Array:
    """Sequence-parallel attention: (B, H, S, D) arrays whose S dimension
    is sharded over ``mesh`` axis ``seq_axis``. S must divide evenly by
    the axis size. Works inside jit (shard_map composes with pjit)."""
    if kv_mask is None:
        kv_mask = jnp.ones(q.shape[:1] + q.shape[2:3], dtype=jnp.float32)
    spec4 = P(None, None, seq_axis, None)
    spec2 = P(None, seq_axis)
    fn = functools.partial(_ring_attention_local, axis_name=seq_axis,
                           causal=causal)
    return shard_map(
        fn, mesh=mesh,
        in_specs=(spec4, spec4, spec4, spec2),
        out_specs=spec4,
        check_vma=False,
    )(q, k, v, kv_mask)
