"""Sharded top-k scoring — the batchPredict/recommendation hot path.

Replaces the reference templates' per-user `recommendProducts` /
item-score sort over RDDs (reference: tests/pio_tests/engines/
recommendation-engine/src/main/scala/ALSAlgorithm.scala:90-120 and
examples/scala-parallel-similarproduct/.../ALSAlgorithm.scala cosine
ranking). One matmul (queries × item-factor table) feeds
``jax.lax.top_k`` — MXU for the scores, fused masking for seen/business
-rule filters, no per-query host loops.
"""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.obs.compile import instrumented_jit

NEG_INF = jnp.float32(-jnp.inf)


@partial(instrumented_jit, static_argnames=("k",))
def topk_scores(scores: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    """(values, indices) of the top-k per row. ``k`` beyond the
    candidate count clamps (fewer columns back, never an XLA assert) —
    the contract every serving top-k in this module shares: a tiny
    catalog, or a shortlist smaller than the requested width, returns
    what exists."""
    return jax.lax.top_k(scores, min(k, scores.shape[-1]))


@partial(instrumented_jit, static_argnames=("k",))
def recommend_topk(
    user_vecs: jax.Array,    # (B, K) query user factors
    item_f: jax.Array,       # (I, K) item factor table
    seen_cols: jax.Array,    # (B, S) int32 item indices already seen (padded)
    seen_mask: jax.Array,    # (B, S) 1=real, 0=pad
    allow: jax.Array,        # (I,) or (B, I) multiplicative 0/1 eligibility
    k: int,
) -> tuple[jax.Array, jax.Array]:
    """Top-k unseen, eligible items per query user.

    ``allow`` carries business rules (category whitelist, unavailable
    items — the ecommerce template's filters) as a precomputed 0/1
    vector; seen items are masked via scatter so padding slots (mask=0)
    leave scores untouched. ``k`` clamps to the catalog size
    (``topk_scores`` contract).
    """
    scores = jnp.einsum("bk,ik->bi", user_vecs, item_f)          # MXU
    scores = jnp.where(allow > 0, scores, NEG_INF)
    b = scores.shape[0]
    rows = jnp.broadcast_to(jnp.arange(b)[:, None], seen_cols.shape)
    hide = jnp.where(seen_mask > 0, NEG_INF, jnp.float32(jnp.inf))
    scores = scores.at[rows, seen_cols].min(hide)
    return jax.lax.top_k(scores, min(k, scores.shape[-1]))


@partial(instrumented_jit, static_argnames=("k", "chunk"))
def recommend_topk_chunked(
    user_vecs: jax.Array,    # (B, K)
    item_f: jax.Array,       # (I, K)
    seen_cols: jax.Array,    # (B, S) int32, padded
    seen_mask: jax.Array,    # (B, S) 1=real, 0=pad
    allow: jax.Array,        # (I,) 0/1 eligibility
    k: int,
    chunk: int = 1 << 18,
) -> tuple[jax.Array, jax.Array]:
    """recommend_topk without materialising the (B, I) score matrix:
    lax.scan over item tiles (dynamic_slice views — the table is never
    copied), per-tile ``lax.top_k``, running merge. Seen items are
    masked with the same O(B x S) scatter as the flat path, translated
    to tile-local coordinates. A non-divisible catalog is covered by a
    final overlapping tile whose already-scored prefix is masked out.

    Matches the flat path's indices on every finite-score slot. Slots
    beyond the eligible-item count carry -inf values and out-of-range
    sentinel indices (>= I, never colliding with a real pick) — callers
    must treat non-finite slots as absent, which both in-repo consumers
    (ALSModel._gather_results, batch_predict) already do. Restricted to
    1-D ``allow``; measured 1.6-2.5x faster than the flat path from
    ~1M items with batched queries (peak memory O(B x chunk)); the
    flat path stays better for small catalogs and B=1 serving."""
    B = user_vecs.shape[0]
    I = item_f.shape[0]
    k = min(k, I)                   # the shared clamp-not-assert contract
    if I <= chunk:
        return recommend_topk(user_vecs, item_f, seen_cols, seen_mask,
                              allow, k)
    n_full = I // chunk
    has_rem = (I % chunk) != 0
    # tile t starts at starts[t]; positions below valid_from[t] were
    # already scored by an earlier tile (only the final overlapping
    # remainder tile has valid_from > start)
    starts = [t * chunk for t in range(n_full)]
    valid_from = [t * chunk for t in range(n_full)]
    if has_rem:
        starts.append(I - chunk)
        valid_from.append(n_full * chunk)
    starts = jnp.asarray(starts, dtype=jnp.int32)
    valid_from = jnp.asarray(valid_from, dtype=jnp.int32)

    rows = jnp.broadcast_to(jnp.arange(B)[:, None], seen_cols.shape)

    def body(carry, xs):
        bv, bi = carry                     # (B, k) running best
        start, vfrom = xs
        tile = jax.lax.dynamic_slice(
            item_f, (start, 0), (chunk, item_f.shape[1]))
        tallow = jax.lax.dynamic_slice(allow, (start,), (chunk,))
        scores = jnp.einsum("bk,ik->bi", user_vecs, tile)
        idx = start + jax.lax.iota(jnp.int32, chunk)[None, :]
        scores = jnp.where(tallow[None, :] > 0, scores, NEG_INF)
        scores = jnp.where(idx >= vfrom, scores, NEG_INF)
        # seen scatter in tile-local coordinates (out-of-tile entries
        # clip to column 0 with a no-op +inf update)
        local = seen_cols - start
        in_tile = (local >= 0) & (local < chunk) & (seen_mask > 0)
        hide = jnp.where(in_tile, NEG_INF, jnp.float32(jnp.inf))
        scores = scores.at[rows, jnp.clip(local, 0, chunk - 1)].min(hide)
        v, sel = jax.lax.top_k(jnp.concatenate([bv, scores], axis=1), k)
        alli = jnp.concatenate(
            [bi, jnp.broadcast_to(idx, (B, chunk))], axis=1)
        return (v, jnp.take_along_axis(alli, sel, axis=1)), None

    init = (
        jnp.full((B, k), NEG_INF),
        # out-of-range sentinels: a -inf carry slot must never share an
        # index with a real (finite) pick, or a caller ignoring score
        # finiteness would serve duplicates
        jnp.broadcast_to(I + jnp.arange(k, dtype=jnp.int32), (B, k)),
    )
    (v, i), _ = jax.lax.scan(body, init, (starts, valid_from))
    return v, i


#: static seen-array widths shared by batch_predict's menu — a small
#: fixed set keeps the number of compiled kernel shapes bounded
_SEEN_WIDTHS = (8, 32, 128, 512)

#: static BATCH widths (power-of-two menu, serving scale): every
#: distinct batch dim is a fresh jit signature, and the serving
#: micro-batcher produces arbitrary coalesce counts — both the
#: templates' batch_predict padding and the adaptive batch policy
#: (serving/batch_policy.py) snap to this one menu so adaptivity can
#: never mint a batch shape the compiled-program cache hasn't seen
BATCH_WIDTHS = (1, 2, 4, 8, 16, 32, 64, 128, 256)


def serving_batch(b: int) -> int:
    """Round a serving batch size up to the ``BATCH_WIDTHS`` menu.

    Batches beyond the menu (eval-scale: engine.eval routes whole folds
    through batch_predict) pass through unchanged — they compile once
    anyway, and padding them would inflate the score matmul for
    nothing."""
    if b <= 0:
        return BATCH_WIDTHS[0]
    if b > BATCH_WIDTHS[-1] or (b & (b - 1)) == 0:
        return b
    return 1 << b.bit_length()

#: static top_k widths shared by every serving path — k is a jit
#: signature arg fed by client-controlled ``query.num``
_K_WIDTHS = (10, 32, 100, 320, 1000)


def serving_k(k: int, n_max: int) -> int:
    """Round a requested top-k width up to the ``_K_WIDTHS`` menu
    (power of two beyond it), clamped to the catalog/vocab size.

    ``k`` feeds jit signatures as a STATIC argument, and ``query.num``
    is client-controlled: without the menu, a client cycling num
    values retraces the serving program per distinct value — behind
    the query micro-batcher that stalls every other client's batch
    for the compile. Callers already trim results to each query's own
    num, so a wider k only widens the ``top_k``. One helper for all
    serving paths (ALS single-query, recommendation batch, sessionrec
    batch) so the trace-width buckets can't drift apart."""
    for cap in _K_WIDTHS:
        if k <= cap:
            return min(cap, n_max)
    return min(1 << (max(k, 2) - 1).bit_length(), n_max)

#: catalog/batch envelope where the chunked-scan formulation beats the
#: flat materialize+top_k (measured with the forcing protocol:
#: B=256 x I=2M, chunked 73ms vs flat 141ms; at B=32 x I=1M the flat
#: path wins, 8ms vs ~1ms-level noise either way)
_MIN_ITEMS = 786_432
_MIN_BATCH = 24


def _trim_seen(seen_cols, seen_mask):
    """Shrink the seen-item pad to the smallest static width covering
    the batch's real max seen count. Host-side only: the seen arrays
    originate as NumPy in the templates, and a device reduction here
    would cost one synchronous host<->device scalar fetch per call —
    the same per-dispatch RTT the static lam/alpha args eliminate
    elsewhere. Device arrays / tracers and menu-width inputs pass
    through untouched (templates/recommendation.py already right-sizes
    to the ``_SEEN_WIDTHS`` menu)."""
    if not isinstance(seen_mask, np.ndarray) or seen_mask.ndim != 2 \
            or seen_mask.shape[1] in _SEEN_WIDTHS:
        return seen_cols, seen_mask
    # bound by the last occupied slot (not the count): entries need not
    # be left-packed
    occupied = np.where(
        seen_mask > 0,
        np.arange(1, seen_mask.shape[1] + 1, dtype=np.int64)[None, :],
        0,
    )
    real = int(occupied.max()) if occupied.size else 0
    for width in _SEEN_WIDTHS:
        if real <= width < seen_mask.shape[1]:
            return seen_cols[:, :width], seen_mask[:, :width]
    return seen_cols, seen_mask


def recommend_topk_fused(
    user_vecs: jax.Array,    # (B, K)
    item_f: jax.Array,       # (I, K)
    seen_cols: jax.Array,    # (B, S) int32, padded
    seen_mask: jax.Array,    # (B, S) 1=real, 0=pad
    allow: jax.Array,        # (I,) eligibility (0/1); (B, I) -> flat path
    k: int,
) -> tuple[jax.Array, jax.Array]:
    """Top-k recommendation dispatcher: picks between the two XLA
    formulations — flat materialize+top_k (:func:`recommend_topk`, best
    for small catalogs and B=1 serving) and the chunked-scan merge
    (:func:`recommend_topk_chunked`, O(B x chunk) memory, faster from
    ~1M items with batched queries).

    A pallas streaming-select kernel used to sit behind this dispatch;
    it was deleted after re-measurement with the forcing protocol
    (bench.py header): 168ms vs the flat path's 8ms at B=32 x I=1M and
    188ms vs the chunked path's 73ms at B=256 x I=2M — its per-tile VPU
    selection loop loses to ``lax.top_k`` at every envelope point."""
    if allow.ndim == 1 and item_f.shape[0] >= _MIN_ITEMS \
            and user_vecs.shape[0] >= _MIN_BATCH:
        seen_cols, seen_mask = _trim_seen(seen_cols, seen_mask)
        return recommend_topk_chunked(
            user_vecs, item_f, seen_cols, seen_mask, allow, k)
    return recommend_topk(user_vecs, item_f, seen_cols, seen_mask, allow, k)


def recommend_topk_sharded(
    user_vecs: jax.Array,    # (B, K) — B divisible by mesh "data"
    item_f: jax.Array,       # (I, K) — I divisible by mesh "model"
    seen_cols: jax.Array,    # (B, S) int32, padded
    seen_mask: jax.Array,    # (B, S) 1=real, 0=pad
    allow: jax.Array,        # (I,) 0/1 eligibility
    k: int,
    mesh,
) -> tuple[jax.Array, jax.Array]:
    """Distributed batch top-k — the EVAL hot path on a mesh
    (reference analogue: Engine.eval's batchPredictBase over RDD
    partitions, Engine.scala:783-799; here the catalog's score space
    is the sharded axis instead of the query RDD).

    Queries shard over ``data``; the item-factor table row-shards over
    ``model``. Each shard computes a LOCAL top-k over its catalog rows
    (with seen/eligibility masks translated to shard-local
    coordinates), then the ``n_model * k`` candidates all-gather over
    ``model`` — k entries per shard, not the (B, I) score matrix — and
    a second ``top_k`` picks the global winners in global item
    coordinates. Per-device traffic is O(B_local * n_model * k), the
    classic distributed top-k merge; ICI carries only candidates.

    Shape contracts match the other top-k paths where the mesh allows:
    ``k`` clamps to the catalog (a shard's local top-k clamps to its
    own rows and the merge recovers the global k — tall-skinny meshes
    like 1×8 serve k > rows-per-shard correctly), and a query batch
    not divisible by the ``data`` axis pads with zero query rows whose
    results are sliced off (B=1 single-query serving works on any
    mesh). The catalog itself MUST divide the ``model`` axis — the
    table is persistent sharded state, so padding it per call would
    copy the one array this path exists to avoid copying; callers pad
    once at staging/load time (models/als.py does)."""
    I = item_f.shape[0]
    n_model = int(mesh.shape["model"])
    if I % n_model:
        raise ValueError(
            f"catalog rows ({I}) must divide the model axis ({n_model}); "
            "pad the item table")
    k = min(k, I)                   # the shared clamp-not-assert contract
    n_data = int(mesh.shape["data"])
    b = user_vecs.shape[0]
    pad = (-b) % n_data
    if pad:
        user_vecs = jnp.concatenate(
            [user_vecs, jnp.zeros((pad, user_vecs.shape[1]),
                                  dtype=user_vecs.dtype)])
        seen_cols = jnp.concatenate(
            [jnp.asarray(seen_cols, dtype=jnp.int32),
             jnp.zeros((pad, seen_cols.shape[1]), dtype=jnp.int32)])
        sm = jnp.asarray(seen_mask)
        seen_mask = jnp.concatenate(
            [sm, jnp.zeros((pad, sm.shape[1]), dtype=sm.dtype)])
    fn = _sharded_topk_fn(mesh, k, I // n_model)
    vals, idxs = fn(user_vecs, item_f, seen_cols, seen_mask, allow)
    if pad:
        vals, idxs = vals[:b], idxs[:b]
    return vals, idxs


@functools.lru_cache(maxsize=16)
def _sharded_topk_fn(mesh, k: int, shard_rows: int):
    """Cached jitted shard_map program — jit caches by function
    identity, so rebuilding the closure per call would retrace and
    recompile the eval hot path on every invocation."""
    from jax.sharding import PartitionSpec as P

    from predictionio_tpu.utils.jax_compat import shard_map

    # a shard can only contribute its own rows: on tall-skinny meshes
    # (model axis > I/k, e.g. 1×8 serving a small catalog) the local
    # top-k clamps to shard_rows and the gathered n_model * k_loc >= k
    # candidates still recover the exact global top-k
    k_loc = min(k, shard_rows)

    def local(uv, itf, sc, sm, al):
        start = jax.lax.axis_index("model") * shard_rows
        scores = jnp.einsum("bk,ik->bi", uv, itf)           # (b, rows)
        scores = jnp.where(al > 0, scores, NEG_INF)
        loc = sc - start
        in_shard = (loc >= 0) & (loc < shard_rows) & (sm > 0)
        rows = jnp.broadcast_to(jnp.arange(uv.shape[0])[:, None], sc.shape)
        hide = jnp.where(in_shard, NEG_INF, jnp.float32(jnp.inf))
        scores = scores.at[rows, jnp.clip(loc, 0, shard_rows - 1)].min(hide)
        v, i = jax.lax.top_k(scores, k_loc)                 # local winners
        gi = (i + start).astype(jnp.int32)
        vg = jax.lax.all_gather(v, "model", axis=1, tiled=True)
        ig = jax.lax.all_gather(gi, "model", axis=1, tiled=True)
        vv, sel = jax.lax.top_k(vg, k)
        return vv, jnp.take_along_axis(ig, sel, axis=1)

    specs = dict(
        in_specs=(P("data", None), P("model", None), P("data", None),
                  P("data", None), P("model")),
        out_specs=(P("data", None), P("data", None)),
    )
    # the all-gather makes both outputs replicated over "model", which
    # the static replication checker cannot infer — disable it (the
    # jax_compat shim normalizes the check_rep -> check_vma rename)
    return instrumented_jit(
        shard_map(local, mesh=mesh, check_vma=False, **specs),
        jit_name="sharded_topk")


@partial(instrumented_jit, static_argnames=("k",))
def similar_topk(
    query_vecs: jax.Array,   # (B, K) query item factors
    item_f: jax.Array,       # (I, K)
    exclude_cols: jax.Array,  # (B, E) the query items themselves (padded)
    exclude_mask: jax.Array,  # (B, E)
    allow: jax.Array,         # (I,) or (B, I)
    k: int,
) -> tuple[jax.Array, jax.Array]:
    """Cosine-similarity top-k — the similarproduct template's ranking."""
    qn = query_vecs / jnp.maximum(
        jnp.linalg.norm(query_vecs, axis=-1, keepdims=True), 1e-9
    )
    itn = item_f / jnp.maximum(
        jnp.linalg.norm(item_f, axis=-1, keepdims=True), 1e-9
    )
    scores = jnp.einsum("bk,ik->bi", qn, itn)
    scores = jnp.where(allow > 0, scores, NEG_INF)
    b = scores.shape[0]
    rows = jnp.broadcast_to(jnp.arange(b)[:, None], exclude_cols.shape)
    hide = jnp.where(exclude_mask > 0, NEG_INF, jnp.float32(jnp.inf))
    scores = scores.at[rows, exclude_cols].min(hide)
    return jax.lax.top_k(scores, min(k, scores.shape[-1]))
