"""Sharded top-k scoring — the batchPredict/recommendation hot path.

Replaces the reference templates' per-user `recommendProducts` /
item-score sort over RDDs (reference: tests/pio_tests/engines/
recommendation-engine/src/main/scala/ALSAlgorithm.scala:90-120 and
examples/scala-parallel-similarproduct/.../ALSAlgorithm.scala cosine
ranking). One matmul (queries × item-factor table) feeds
``jax.lax.top_k`` — MXU for the scores, fused masking for seen/business
-rule filters, no per-query host loops.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

NEG_INF = jnp.float32(-jnp.inf)


@partial(jax.jit, static_argnames=("k",))
def topk_scores(scores: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    """(values, indices) of the top-k per row."""
    return jax.lax.top_k(scores, k)


@partial(jax.jit, static_argnames=("k",))
def recommend_topk(
    user_vecs: jax.Array,    # (B, K) query user factors
    item_f: jax.Array,       # (I, K) item factor table
    seen_cols: jax.Array,    # (B, S) int32 item indices already seen (padded)
    seen_mask: jax.Array,    # (B, S) 1=real, 0=pad
    allow: jax.Array,        # (I,) or (B, I) multiplicative 0/1 eligibility
    k: int,
) -> tuple[jax.Array, jax.Array]:
    """Top-k unseen, eligible items per query user.

    ``allow`` carries business rules (category whitelist, unavailable
    items — the ecommerce template's filters) as a precomputed 0/1
    vector; seen items are masked via scatter so padding slots (mask=0)
    leave scores untouched.
    """
    scores = jnp.einsum("bk,ik->bi", user_vecs, item_f)          # MXU
    scores = jnp.where(allow > 0, scores, NEG_INF)
    b = scores.shape[0]
    rows = jnp.broadcast_to(jnp.arange(b)[:, None], seen_cols.shape)
    hide = jnp.where(seen_mask > 0, NEG_INF, jnp.float32(jnp.inf))
    scores = scores.at[rows, seen_cols].min(hide)
    return jax.lax.top_k(scores, k)


@partial(jax.jit, static_argnames=("k",))
def similar_topk(
    query_vecs: jax.Array,   # (B, K) query item factors
    item_f: jax.Array,       # (I, K)
    exclude_cols: jax.Array,  # (B, E) the query items themselves (padded)
    exclude_mask: jax.Array,  # (B, E)
    allow: jax.Array,         # (I,) or (B, I)
    k: int,
) -> tuple[jax.Array, jax.Array]:
    """Cosine-similarity top-k — the similarproduct template's ranking."""
    qn = query_vecs / jnp.maximum(
        jnp.linalg.norm(query_vecs, axis=-1, keepdims=True), 1e-9
    )
    itn = item_f / jnp.maximum(
        jnp.linalg.norm(item_f, axis=-1, keepdims=True), 1e-9
    )
    scores = jnp.einsum("bk,ik->bi", qn, itn)
    scores = jnp.where(allow > 0, scores, NEG_INF)
    b = scores.shape[0]
    rows = jnp.broadcast_to(jnp.arange(b)[:, None], exclude_cols.shape)
    hide = jnp.where(exclude_mask > 0, NEG_INF, jnp.float32(jnp.inf))
    scores = scores.at[rows, exclude_cols].min(hide)
    return jax.lax.top_k(scores, k)
