"""Alternating Least Squares on the MXU — the framework's north-star kernel.

Replaces: org.apache.spark.mllib.recommendation.ALS as invoked by the
reference's recommendation templates (reference: tests/pio_tests/engines/
recommendation-engine/src/main/scala/ALSAlgorithm.scala:79-85 and
examples/scala-parallel-{recommendation,similarproduct,
ecommercerecommendation}). Supports explicit ratings (ALS-WR weighted-λ
regularization) and implicit feedback (Hu-Koren-Volinsky confidence
weighting), like MLlib's `ALS.train` / `ALS.trainImplicit`.

TPU-first design (NOT a translation of MLlib's block solver):

- **Bucketed dense layout.** Ratings are grouped per row (user for the
  user half-step, item for the item half-step) and padded to power-of-two
  lengths, rows of similar degree sharing a bucket. Each bucket is a dense
  ``(rows, pad_len)`` slab, so the normal-equation build
  ``A_u = Σ v_i v_iᵀ`` is one batched matmul ``einsum('blk,blm->bkm')``
  that tiles straight onto the MXU — no scatter/segment ops, which are
  slow on TPU. Padding waste is bounded by the bucket growth factor.
- **Static shapes.** Bucket shapes are the only compile keys; iteration
  count, λ, α are runtime values. lax.scan over fixed-size slabs bounds
  the solver's working set; rating slabs are HBM-resident by default
  (fastest) or streamed per bucket with ``hbm_resident=False`` when the
  padded rating set exceeds device memory.
- **Batched conjugate-gradient solves.** Per-row K×K SPD systems are
  solved with batched-matvec CG (``_cg_solve_batched``) — XLA's batched
  cholesky/triangular_solve lower to sequential scalar loops and run
  ~10-20x slower on TPU; the ridge-regularised systems hit CG's f32
  accuracy floor within ~16-24 steps at every rank.
- **Mesh sharding.** Slab row dimensions carry a NamedSharding over the
  "data" mesh axis while factor tables stay replicated; XLA inserts the
  all-gathers/psums on ICI — the analogue of MLlib's block shuffles,
  without the shuffle.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.obs.compile import instrumented_jit

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Host-side layout: COO ratings -> padded per-row buckets
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RatingsCOO:
    """Host ratings triple; rows/cols are dense indices (see utils.bimap)."""

    rows: np.ndarray  # int32 (R,)
    cols: np.ndarray  # int32 (R,)
    vals: np.ndarray  # float32 (R,)
    num_rows: int
    num_cols: int

    @property
    def nnz(self) -> int:
        return int(self.rows.shape[0])

    def transpose(self) -> "RatingsCOO":
        return RatingsCOO(self.cols, self.rows, self.vals, self.num_cols, self.num_rows)


@dataclasses.dataclass(frozen=True)
class Bucket:
    """All rows whose degree pads to ``pad_len``: dense (n, pad_len) slabs.

    Entries are packed to the row prefix, so the pad mask is fully
    determined by ``deg`` and derived on demand."""

    row_ids: np.ndarray  # int32 (n,) original row indices
    cols: np.ndarray     # int32 (n, pad_len)
    vals: np.ndarray     # float32 (n, pad_len)
    deg: np.ndarray      # int32 (n,) real entries per row

    @property
    def pad_len(self) -> int:
        return int(self.cols.shape[1])

    @property
    def mask(self) -> np.ndarray:
        """(n, pad_len) f32 — 1 for real entries, 0 for padding."""
        return (
            np.arange(self.pad_len, dtype=np.int32)[None, :]
            < self.deg[:, None]
        ).astype(np.float32)


@dataclasses.dataclass(frozen=True)
class BucketedRatings:
    buckets: tuple[Bucket, ...]
    num_rows: int
    num_cols: int
    nnz: int


def bucket_rows(
    coo: RatingsCOO, min_len: int = 8, growth: int = 2,
    max_len: int | None = None, use_native: bool = True,
) -> BucketedRatings:
    """Group ratings by row into padded power-of-``growth`` buckets.

    ``max_len`` caps a row's kept ratings (highest-value kept) — the
    recompile-control knob for pathological heavy rows.

    The packing pass runs in native C++ when available (one counting
    sort + one fill over nnz entries, native/bucketize.cc); the NumPy
    path below is the fallback with an identical slab layout.
    """
    if use_native:
        native = _bucket_rows_native(coo, min_len, growth, max_len)
        if native is not None:
            return native
    order = np.argsort(coo.rows, kind="stable")
    rows = coo.rows[order]
    cols = coo.cols[order]
    vals = coo.vals[order]
    uniq, start, counts = np.unique(rows, return_index=True, return_counts=True)

    if max_len is not None:
        capped = np.minimum(counts, max_len)
    else:
        capped = counts
    # bucket length per unique row: min_len * growth^k >= count
    lens = np.maximum(capped, min_len)
    exps = np.ceil(np.log(lens / min_len) / np.log(growth) - 1e-12).astype(np.int64)
    pad_lens = (min_len * growth ** np.maximum(exps, 0)).astype(np.int64)

    buckets = []
    for pl in np.unique(pad_lens):
        sel = np.nonzero(pad_lens == pl)[0]
        n = len(sel)
        b_cols = np.zeros((n, pl), dtype=np.int32)
        b_vals = np.zeros((n, pl), dtype=np.float32)
        for j, ui in enumerate(sel):
            s, c = start[ui], capped[ui]
            if c < counts[ui]:  # keep the top-valued ratings of a capped row
                seg = np.argsort(vals[s : s + counts[ui]])[::-1][:c] + s
            else:
                seg = slice(s, s + c)
            b_cols[j, :c] = cols[seg]
            b_vals[j, :c] = vals[seg]
        buckets.append(
            Bucket(uniq[sel].astype(np.int32), b_cols, b_vals,
                   capped[sel].astype(np.int32))
        )
    return BucketedRatings(tuple(buckets), coo.num_rows, coo.num_cols, coo.nnz)


def _native_i32p():
    import ctypes

    return ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float)


def _native_ptr(a, ty):
    return a.ctypes.data_as(ty)


def _native_coo_args(coo: RatingsCOO):
    """Contiguous input buffers + typed pointers for the native layout
    entry points. The returned arrays must stay referenced while the
    native handle is alive."""
    i32_p, f32_p = _native_i32p()
    rows = np.ascontiguousarray(coo.rows, dtype=np.int32)
    cols = np.ascontiguousarray(coo.cols, dtype=np.int32)
    vals = np.ascontiguousarray(coo.vals, dtype=np.float32)
    return (rows, cols, vals,
            _native_ptr(rows, i32_p), _native_ptr(cols, i32_p),
            _native_ptr(vals, f32_p))


def _native_read_slabs(handle, num_fn, info_fn, fill_fn, free_fn, make):
    """Shared readback loop for the handle-based native layout APIs
    (bucketizer and chunker share the same (ids, cols, vals, deg) slab
    contract): query each slab's shape, let the native side fill
    NumPy-allocated buffers, and free the handle."""
    import ctypes

    i32_p, f32_p = _native_i32p()
    try:
        out = []
        for b in range(num_fn(handle)):
            length = ctypes.c_int32()
            n = ctypes.c_int64()
            if info_fn(handle, b, ctypes.byref(length), ctypes.byref(n)):
                return None
            pl, nn = int(length.value), int(n.value)
            b_ids = np.empty((nn,), dtype=np.int32)
            b_cols = np.empty((nn, pl), dtype=np.int32)
            b_vals = np.empty((nn, pl), dtype=np.float32)
            b_deg = np.empty((nn,), dtype=np.int32)
            if fill_fn(handle, b, _native_ptr(b_ids, i32_p),
                       _native_ptr(b_cols, i32_p), _native_ptr(b_vals, f32_p),
                       _native_ptr(b_deg, i32_p)):
                return None
            out.append(make(b_ids, b_cols, b_vals, b_deg))
        return tuple(out)
    finally:
        free_fn(handle)


def _bucket_rows_native(
    coo: RatingsCOO, min_len: int, growth: int, max_len: int | None
) -> BucketedRatings | None:
    """C++ packing path; None when the native toolchain is unavailable."""
    from predictionio_tpu.native import load_bucketize

    lib = load_bucketize()
    if lib is None or coo.nnz == 0:
        return None
    rows, cols, vals, rp, cp, vp = _native_coo_args(coo)
    handle = lib.pio_bucketize(
        coo.nnz, rp, cp, vp, coo.num_rows, min_len, growth,
        0 if max_len is None else max_len,
    )
    if not handle:
        return None
    buckets = _native_read_slabs(
        handle, lib.pio_bucketize_num_buckets, lib.pio_bucketize_bucket_info,
        lib.pio_bucketize_fill, lib.pio_bucketize_free, Bucket)
    if buckets is None:
        return None
    return BucketedRatings(buckets, coo.num_rows, coo.num_cols, coo.nnz)


# ---------------------------------------------------------------------------
# Chunked layout: rows split into fixed-size chunks, per-row accumulation
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ChunkSlab:
    """All chunks of one fixed length ``L``: dense (n, L) slabs plus the
    row each chunk belongs to. Multiple chunks may share a row — their
    normal-equation contributions are accumulated on device."""

    row_ids: np.ndarray  # int32 (n,) owning row per chunk
    cols: np.ndarray     # int32 (n, L)
    vals: np.ndarray     # float32 (n, L)
    deg: np.ndarray      # int32 (n,) real entries in this chunk


@dataclasses.dataclass(frozen=True)
class ChunkedRatings:
    slabs: tuple[ChunkSlab, ...]   # one per chunk size, descending L
    num_rows: int
    num_cols: int
    nnz: int


def _chunk_rows_native(
    coo: RatingsCOO, sizes: Sequence[int]
) -> ChunkedRatings | None:
    """C++ chunking path (native/bucketize.cc pio_chunk*); None when the
    native toolchain is unavailable — chunk_rows falls back to NumPy
    with an identical slab layout."""
    from predictionio_tpu.native import load_bucketize

    lib = load_bucketize()
    if lib is None or coo.nnz == 0:
        return None
    i32_p, _ = _native_i32p()
    rows, cols, vals, rp, cp, vp = _native_coo_args(coo)
    sz = np.ascontiguousarray(sizes, dtype=np.int32)
    handle = lib.pio_chunk(
        coo.nnz, rp, cp, vp, coo.num_rows, _native_ptr(sz, i32_p), len(sz))
    if not handle:
        return None
    slabs = _native_read_slabs(
        handle, lib.pio_chunk_num_slabs, lib.pio_chunk_slab_info,
        lib.pio_chunk_fill, lib.pio_chunk_free, ChunkSlab)
    if slabs is None:
        return None
    return ChunkedRatings(slabs, coo.num_rows, coo.num_cols, coo.nnz)


def chunk_rows(
    coo: RatingsCOO, sizes: Sequence[int] = (512, 128),
    use_native: bool = True,
) -> ChunkedRatings:
    """Decompose every row into fixed-size chunks — the recompile- and
    MXU-friendly alternative to :func:`bucket_rows`.

    Greedy: full chunks of the largest size first, cascading down; the
    final remainder pads to the smallest size. Properties that make this
    the default training layout:

    - **No dropped ratings** (bucket_rows' ``max_len`` cap silently
      drops the tail of heavy rows — 14% of the item half at ML-20M
      skew).
    - **Bounded shape count**: ``len(sizes)`` compile keys per side
      regardless of the degree distribution (a growth-2 bucket ladder
      needs ~15), so cold-start compiles stay minutes, not tens of
      minutes, on slow-compile links.
    - **MXU-aligned contraction**: with the smallest size >= 128 every
      normal-equation einsum contracts a full MXU lane width; measured
      on one v5e-class chip this beats the low-padding small-bucket
      layout ~5x despite doing ~1.5x more padded work.
    - **Padding bounded by the smallest size** per row (< 128 entries),
      vs growth-factor multiplicative padding.

    Chunks of one row carry partial sums that :func:`solve_half`
    accumulates per row before a single batched solve.

    The decomposition runs in native C++ when available (one counting
    sort + one packing pass, native/bucketize.cc ``pio_chunk*`` —
    measured 6.2x the NumPy path at ML-20M scale); the NumPy fallback
    below produces an identical slab layout.
    """
    sizes = sorted({int(s) for s in sizes}, reverse=True)
    if not sizes or sizes[-1] < 1:
        raise ValueError(f"invalid chunk sizes {sizes}")
    if use_native:
        native = _chunk_rows_native(coo, sizes)
        if native is not None:
            return native
    order = np.argsort(coo.rows, kind="stable")
    rows_s = coo.rows[order]
    cols_s = coo.cols[order]
    vals_s = coo.vals[order]
    deg = np.bincount(rows_s, minlength=coo.num_rows).astype(np.int64)
    start = np.zeros(coo.num_rows, dtype=np.int64)
    np.cumsum(deg[:-1], out=start[1:])
    # position of each entry within its row
    pos = np.arange(coo.nnz, dtype=np.int64) - start[rows_s]

    slabs = []
    # per-row entry offset where each size-class begins (cascade)
    class_begin = np.zeros(coo.num_rows, dtype=np.int64)
    remaining = deg.copy()
    for i, L in enumerate(sizes):
        if i < len(sizes) - 1:
            n_full = remaining // L           # only full chunks this size
            covered = n_full * L
        else:
            n_full = -(-remaining // L)       # remainder pads to last size
            covered = remaining
        class_end = class_begin + covered
        sel = (pos >= class_begin[rows_s]) & (pos < class_end[rows_s])
        chunk_base = np.zeros(coo.num_rows, dtype=np.int64)
        np.cumsum(n_full[:-1], out=chunk_base[1:])
        total = int(n_full.sum())
        if total:
            p = pos[sel] - class_begin[rows_s[sel]]
            chunk_of = chunk_base[rows_s[sel]] + p // L
            within = p % L
            b_cols = np.zeros((total, L), dtype=np.int32)
            b_vals = np.zeros((total, L), dtype=np.float32)
            b_cols[chunk_of, within] = cols_s[sel]
            b_vals[chunk_of, within] = vals_s[sel]
            b_deg = np.bincount(chunk_of, minlength=total).astype(np.int32)
            # owning row of each chunk
            has = n_full > 0
            b_rows = np.repeat(
                np.nonzero(has)[0].astype(np.int32), n_full[has]
            )
            slabs.append(ChunkSlab(b_rows, b_cols, b_vals, b_deg))
        class_begin = class_end
        remaining = remaining - covered
    return ChunkedRatings(tuple(slabs), coo.num_rows, coo.num_cols, coo.nnz)


@dataclasses.dataclass(frozen=True)
class DeviceChunkSlab:
    row_ids: jax.Array  # int32 (S, B) owning row (0 for pad chunks)
    cols: jax.Array     # int32 (S, B, L)
    vals: jax.Array     # float32 (S, B, L)
    deg: jax.Array      # int32 (S, B) real entries (0 for pad chunks)


@dataclasses.dataclass(frozen=True)
class DeviceChunkedRatings:
    """Chunk slabs resident in HBM; build once with :func:`stage_chunks`."""

    slabs: tuple[DeviceChunkSlab, ...]
    num_rows: int
    num_cols: int
    nnz: int


def pad_chunk_slab(
    slab: ChunkSlab, rank: int, data_axis: int, max_slab_elems: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Pad one chunk slab to its full (S, B, ...) device shape on the
    host: (row_ids, cols, vals, deg). Pad chunks carry row 0 with zero
    degree — zero contribution. Shared by single-process staging
    (:func:`stage_chunks`) and multi-process staging, where each
    process pads identically and contributes its local B-slice via
    ``jax.make_array_from_process_local_data``
    (tests/multihost_als_child.py)."""
    n, L = slab.cols.shape
    s, b = _slab_shape(n, L, rank, data_axis, max_slab_elems)
    total = s * b

    def pad2(a):
        p = np.zeros((total, a.shape[1]), dtype=a.dtype)
        p[:n] = a
        return p.reshape(s, b, a.shape[1])

    deg = np.zeros((total,), dtype=np.int32)
    deg[:n] = slab.deg
    rids = np.zeros((total,), dtype=np.int32)
    rids[:n] = slab.row_ids
    return (rids.reshape(s, b), pad2(slab.cols), pad2(slab.vals),
            deg.reshape(s, b))


def stage_chunks(
    chunked: ChunkedRatings,
    rank: int,
    mesh: Mesh | None = None,
    max_slab_elems: int = 1 << 24,
) -> DeviceChunkedRatings:
    data_axis = int(mesh.shape["data"]) if mesh is not None else 1
    out = []
    for slab in chunked.slabs:
        rids, cols, vals, deg = pad_chunk_slab(
            slab, rank, data_axis, max_slab_elems)
        if mesh is not None:
            slab_sh = NamedSharding(mesh, P(None, "data", None))
            vec_sh = NamedSharding(mesh, P(None, "data"))
            cols = jax.device_put(cols, slab_sh)
            vals = jax.device_put(vals, slab_sh)
            deg = jax.device_put(deg, vec_sh)
            rids = jax.device_put(rids, vec_sh)
        else:
            cols, vals, deg, rids = map(jax.device_put, (cols, vals, deg, rids))
        out.append(DeviceChunkSlab(rids, cols, vals, deg))
    return DeviceChunkedRatings(
        tuple(out), chunked.num_rows, chunked.num_cols, chunked.nnz
    )


def half_step_flops(
    bucketed: "BucketedRatings | ChunkedRatings",
    rank: int,
    data_axis: int = 1,
    max_slab_elems: int = 1 << 24,
    cg_steps: int | None = None,
    solver: str = "cg",
) -> dict[str, float]:
    """Useful vs executed FLOPs for one ALS half-step on this layout.

    Useful work per *real* rating entry: the normal-equation build costs
    ``2K²`` FLOPs (outer-product accumulate into A) plus ``2K`` (rhs);
    per active row the solve is priced at the ALGORITHMIC MINIMUM —
    Cholesky ``K³/3`` + ``2K²`` (two triangular solves) — regardless of
    the solver actually run, so MFU never earns credit for extra solver
    work. Executed work replaces real entries with padded slab entries
    (chunk/row padding and slab-shape rounding from :func:`_slab_shape`)
    and prices the solve at what the solver actually run executes:
    batched CG at ``steps × (2K² + 8K)`` (one batched matvec + the CG
    vector updates per step, ``steps = cg_steps or min(K+4,
    _CG_STEP_CAP)``), or — when ``solver="cholesky"`` is the path being
    measured — the direct factorization + two triangular solves
    (``K³/3 + 2K²``, i.e. the algorithmic minimum). Pass the same
    ``solver``/``cg_steps`` the measured run used, or MFU/padding_x
    misattribute the solve cost (ADVICE r3). Executed work also
    replaces real entries with padded slab entries — for the chunked
    layout over every row (inactive rows solve the identity). The
    ratio ``executed / useful`` therefore carries BOTH the layout's
    padding overhead and the solver-vs-minimum overhead (ADVICE r2:
    a Cholesky-priced executed figure understates executed CG solve
    FLOPs by ~4.5x at rank 32)."""
    if solver not in ("cg", "cholesky"):
        raise ValueError(f"solver must be 'cg' or 'cholesky', got {solver!r}")
    k = float(rank)
    per_entry = 2.0 * k * k + 2.0 * k
    per_solve = (k ** 3) / 3.0 + 2.0 * k * k
    if solver == "cholesky":
        per_solve_exec = per_solve
    else:
        steps = (cg_steps if cg_steps is not None
                 else min(rank + 4, _CG_STEP_CAP))
        per_solve_exec = float(steps) * (2.0 * k * k + 8.0 * k)
    useful = executed = 0.0
    if isinstance(bucketed, ChunkedRatings):
        active = set()
        for slab in bucketed.slabs:
            n, L = slab.cols.shape
            useful += float(slab.deg.sum()) * per_entry
            active.update(np.unique(slab.row_ids).tolist())
            s, rows = _slab_shape(n, L, rank, data_axis, max_slab_elems)
            executed += float(s * rows) * L * per_entry
        useful += len(active) * per_solve
        executed += bucketed.num_rows * per_solve_exec
        return {"useful_flops": useful, "executed_flops": executed}
    for b in bucketed.buckets:
        n = int(b.row_ids.shape[0])
        useful += float(b.deg.sum()) * per_entry + n * per_solve
        s, rows = _slab_shape(n, b.pad_len, rank, data_axis, max_slab_elems)
        executed += float(s * rows) * (b.pad_len * per_entry + per_solve_exec)
    return {"useful_flops": useful, "executed_flops": executed}


# ---------------------------------------------------------------------------
# Ladder layout: MXU-width row buckets for the fused single-program path
# ---------------------------------------------------------------------------

#: pad-length ladder for :func:`ladder_rows`, in units of 128-entry MXU
#: chunks; count-padding is bounded by the gap ratio (<= 1.5x, and only
#: on multi-chunk rows where the absolute slack is small relative to
#: the row)
LADDER_COUNTS = (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96, 128,
                 192, 256, 384, 512, 768, 1024, 1536, 2048)


def _ladder_rows_native(
    coo: RatingsCOO, width: int, small: int
) -> BucketedRatings | None:
    """C++ packing path (native/bucketize.cc pio_ladder — one counting
    sort + one fill, same handle contract as the bucketizer); None when
    the native toolchain is unavailable."""
    from predictionio_tpu.native import load_bucketize

    lib = load_bucketize()
    if lib is None or coo.nnz == 0:
        return None
    import ctypes

    rows, cols, vals, rp, cp, vp = _native_coo_args(coo)
    ladder = np.ascontiguousarray(LADDER_COUNTS, dtype=np.int64)
    handle = lib.pio_ladder(
        coo.nnz, rp, cp, vp, coo.num_rows, width, small,
        ladder.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(ladder))
    if not handle:
        return None
    buckets = _native_read_slabs(
        handle, lib.pio_bucketize_num_buckets, lib.pio_bucketize_bucket_info,
        lib.pio_bucketize_fill, lib.pio_bucketize_free, Bucket)
    if buckets is None:
        return None
    return BucketedRatings(buckets, coo.num_rows, coo.num_cols, coo.nnz)


def ladder_rows(
    coo: RatingsCOO, width: int = 128, small: int = 64,
    use_native: bool = True,
) -> BucketedRatings:
    """Whole-row buckets padded to the MXU-width ladder — the layout
    behind ``layout="fused"``.

    Every row's entries land in ONE bucket whose pad length is either
    ``small`` (rows with degree <= small; half-lane contraction beats
    2x padding for the light-user mass) or ``width * c`` with ``c`` the
    smallest :data:`LADDER_COUNTS` entry covering ``ceil(deg/width)``.
    Unlike :func:`bucket_rows`'s power-of-``growth`` ladder this keeps
    every contraction at (or at worst half of) the 128-lane MXU width,
    and unlike :func:`chunk_rows` it needs no cross-chunk accumulation
    — each bucket row IS a complete row, so the normal equations can be
    built and solved inside one scan step with no scatter and no
    (num_rows, K, K) accumulator (the two phases measured at 100ms +
    113ms per ML-20M iteration on the chunked path, scratch profile
    r3). No ratings are dropped.

    The packing runs in native C++ when available (one counting sort +
    one fill, native/bucketize.cc ``pio_ladder``); the NumPy fallback
    below is vectorized (one stable argsort over nnz + contiguous
    per-bucket slices) and produces an identical slab layout.
    """
    if coo.nnz == 0:
        return BucketedRatings((), coo.num_rows, coo.num_cols, 0)
    if use_native:
        native = _ladder_rows_native(coo, width, small)
        if native is not None:
            return native
    order = np.argsort(coo.rows, kind="stable")
    rows_s = coo.rows[order]
    cols_s = coo.cols[order]
    vals_s = coo.vals[order]
    deg = np.bincount(rows_s, minlength=coo.num_rows).astype(np.int64)
    start = np.zeros(coo.num_rows, dtype=np.int64)
    np.cumsum(deg[:-1], out=start[1:])
    pos = np.arange(coo.nnz, dtype=np.int64) - start[rows_s]

    counts = list(LADDER_COUNTS)
    need = -(-deg // width)                       # ceil chunks per row
    # rows beyond the base ladder extend it by doubling — arbitrary
    # degrees train, they just land in their own (tiny) buckets
    top = int(need.max()) if len(need) else 1
    while counts[-1] < top:
        counts.append(counts[-1] * 2)
    counts = np.asarray(counts, dtype=np.int64)
    ci = np.searchsorted(counts, need)
    pad_lens = counts[ci] * width
    pad_lens = np.where((deg > 0) & (deg <= small), small, pad_lens)

    # one stable sort groups entries by bucket (row/pos order preserved
    # within); per-bucket work is then a contiguous slice, not an
    # nnz-wide mask per pad length
    ekey = pad_lens[rows_s]
    e_order = np.argsort(ekey, kind="stable")
    key_b = ekey[e_order]
    rows_b, cols_b = rows_s[e_order], cols_s[e_order]
    vals_b, pos_b = vals_s[e_order], pos[e_order]

    # rows grouped the same way; slot = rank of the row within its bucket
    act_rows = np.nonzero(deg > 0)[0]
    r_order = np.argsort(pad_lens[act_rows], kind="stable")
    sorted_rows = act_rows[r_order]
    sorted_pl = pad_lens[sorted_rows]
    slot_of = np.empty(coo.num_rows, dtype=np.int64)

    buckets = []
    for pl in np.unique(sorted_pl):
        rs, re = np.searchsorted(sorted_pl, [pl, pl + 1])
        sel_rows = sorted_rows[rs:re]
        slot_of[sel_rows] = np.arange(re - rs)
        es, ee = np.searchsorted(key_b, [pl, pl + 1])
        b_cols = np.zeros((re - rs, pl), dtype=np.int32)
        b_vals = np.zeros((re - rs, pl), dtype=np.float32)
        slots = slot_of[rows_b[es:ee]]
        b_cols[slots, pos_b[es:ee]] = cols_b[es:ee]
        b_vals[slots, pos_b[es:ee]] = vals_b[es:ee]
        buckets.append(Bucket(
            sel_rows.astype(np.int32), b_cols, b_vals,
            deg[sel_rows].astype(np.int32)))
    return BucketedRatings(tuple(buckets), coo.num_rows, coo.num_cols,
                           coo.nnz)


# ---------------------------------------------------------------------------
# Device staging: pad buckets into slabs ONCE, keep them HBM-resident
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DeviceBucket:
    """One bucket staged on device as (S, B, L) slabs.

    The pad mask is not materialised — each slab row carries its real
    degree and the kernel derives ``mask = iota(L) < deg`` on device,
    saving a third of the transfer and HBM footprint.
    """

    row_ids: jax.Array  # int32 (n,)
    cols: jax.Array     # int32 (S, B, L)
    vals: jax.Array     # float32 (S, B, L) zero-padded
    deg: jax.Array      # int32 (S, B) real entries per row (0 for pad rows)
    n: int
    pad_len: int


@dataclasses.dataclass(frozen=True)
class DeviceBucketedRatings:
    """Bucketed ratings resident in HBM — build once with
    :func:`stage_buckets`, reuse across every ALS iteration. Re-staging
    per half-step (the naive path) moves hundreds of MB over PCIe per
    iteration and dominates wall-clock; HBM-resident slabs leave only
    the MXU work."""

    buckets: tuple[DeviceBucket, ...]
    num_rows: int
    num_cols: int
    nnz: int


def pad_bucket_slabs(
    bucket: Bucket, rank: int, data_axis: int, max_slab_elems: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad one bucket to its full (S, B, L)/(S, B) device shape on the
    host: (cols, vals, deg). Pad rows carry zero degree — zero
    contribution. Shared by single-process staging (:func:`_stage_bucket`)
    and multi-process staging, where each process pads identically and
    contributes its local B-slice via
    ``jax.make_array_from_process_local_data``
    (tests/multihost_fused_child.py) — the ladder-layout analogue of
    :func:`pad_chunk_slab`."""
    n = bucket.row_ids.shape[0]
    s, b = _slab_shape(n, bucket.pad_len, rank, data_axis, max_slab_elems)
    total = s * b

    def pad3(a):
        p = np.zeros((total, a.shape[1]), dtype=a.dtype)
        p[:n] = a
        return p.reshape(s, b, a.shape[1])

    deg = np.zeros((total,), dtype=np.int32)
    deg[:n] = bucket.deg
    return pad3(bucket.cols), pad3(bucket.vals), deg.reshape(s, b)


def _stage_bucket(
    bucket: Bucket,
    rank: int,
    mesh: Mesh | None,
    max_slab_elems: int,
) -> DeviceBucket:
    """Transfer one bucket's slabs to the device (sharded over the mesh's
    data axis when given), padding row counts up to full slabs."""
    data_axis = int(mesh.shape["data"]) if mesh is not None else 1
    n = bucket.row_ids.shape[0]
    cols, vals, deg = pad_bucket_slabs(bucket, rank, data_axis,
                                       max_slab_elems)
    if mesh is not None:
        slab_sh = NamedSharding(mesh, P(None, "data", None))
        deg_sh = NamedSharding(mesh, P(None, "data"))
        cols = jax.device_put(cols, slab_sh)
        vals = jax.device_put(vals, slab_sh)
        deg = jax.device_put(deg, deg_sh)
    else:
        cols, vals, deg = map(jax.device_put, (cols, vals, deg))
    return DeviceBucket(
        row_ids=jax.device_put(jnp.asarray(bucket.row_ids)),
        cols=cols, vals=vals, deg=deg, n=n, pad_len=bucket.pad_len,
    )


def stage_buckets(
    bucketed: BucketedRatings,
    rank: int,
    mesh: Mesh | None = None,
    max_slab_elems: int = 1 << 24,
) -> DeviceBucketedRatings:
    """Stage every bucket HBM-resident. Peak device memory is the full
    padded rating set (~8 bytes x padded nnz per orientation) — for sets
    that don't fit, keep host ``BucketedRatings`` and let ``solve_half``
    stream one bucket at a time instead (``als_train(hbm_resident=False)``)."""
    return DeviceBucketedRatings(
        tuple(_stage_bucket(b, rank, mesh, max_slab_elems)
              for b in bucketed.buckets),
        bucketed.num_rows, bucketed.num_cols, bucketed.nnz,
    )


# ---------------------------------------------------------------------------
# Device kernels
# ---------------------------------------------------------------------------

_HI = jax.lax.Precision.HIGHEST  # normal equations need true f32 accumulation


def _cho_solve_batched(A: jax.Array, b: jax.Array) -> jax.Array:
    """Solve SPD systems A x = b for (..., K, K) / (..., K).

    The exact direct solver — kept as the opt-in ``solver="cholesky"``
    path (als_train) and as the oracle the high-rank CG accuracy test
    measures against (tests/test_als.py). Not the default: XLA's batched
    cholesky/triangular_solve lower to sequential scalar loops on TPU,
    measured 17x slower than :func:`_cg_solve_batched` at rank 32."""
    chol = jnp.linalg.cholesky(A)
    y = jax.lax.linalg.triangular_solve(
        chol, b[..., None], left_side=True, lower=True
    )
    x = jax.lax.linalg.triangular_solve(
        chol, y, left_side=True, lower=True, transpose_a=True
    )
    return x[..., 0]


#: default CG step cap: batched f32 CG on ridge-regularised ALS normal
#: matrices reaches its float32 accuracy floor (~2e-7 rel err vs an f64
#: oracle) well before K steps. Round-3 measurement on real ALS-WR and
#: Hu-Koren system families (48 systems each, f64 oracle):
#:   explicit K=200 lam*deg ridge, deg 800-2000:  floor by step 6
#:   explicit K=200 lam=0.01 (weak ridge):        floor by step 16
#:   explicit K=32  lam=0.01 (weakest measured):  9.6e-6 @16, floor @24
#:   implicit K=10..32, alpha 5-10, flat lam:     floor by step 12
#: The cap at 16 keeps worst-case solve error ~1e-5 relative — orders
#: below the alternation's own statistical noise — and each step past
#: it only re-streams A (measured ~23ms/step at the ML-20M rank-200
#: shape). Raise via als_train(cg_steps=...) for pathological
#: conditioning; solver="cholesky" is the exact escape hatch.
_CG_STEP_CAP = 16


def _cg_solve_batched(A: jax.Array, b: jax.Array,
                      steps: int | None = None,
                      bf16_matvec: bool = False) -> jax.Array:
    """Solve SPD systems A x = b for (..., K, K) / (..., K) by batched
    conjugate gradients — the TPU-fast solver.

    XLA's cholesky + triangular_solve lower to sequential scalar loops
    for small batched systems: measured 506ms for 138k rank-32 solves on
    one v5e-class chip, vs 30ms for this CG (HBM-bound batched matvecs,
    the layout the VPU/MXU actually likes); at rank 200 the gap is 1154ms
    vs 104ms (20k systems). ``steps`` defaults to ``min(K + 4, 16)`` —
    exact-in-exact-arithmetic for K <= 12, and at the measured f32
    accuracy floor for every larger rank (see ``_CG_STEP_CAP``). The
    ALS normal matrices carry a ``lam * n`` (or flat ``lam``) ridge, so
    they are well-conditioned by construction; inactive rows pass the
    identity. Callers can raise ``steps`` (als_train(cg_steps=...)) for
    pathologically conditioned data.

    ``bf16_matvec=True`` streams A in bfloat16 through the per-step
    matvec (f32 accumulation; the CG vectors and scalars stay f32) —
    halving the A-traffic that dominates high-rank solves. Round-4
    measurement at the ML-20M rank-200 config: 1.51x the iteration
    (731.6 -> 484.4 ms in a controlled A/B); accuracy vs an f64 oracle
    2.4-2.6e-3 relative on both measured system families (f32 matvec:
    ~1.5e-7) — inside the ~5e-3 band the default bf16 normal-equation
    build already accepts. ``als_train(cg_matvec_dtype=...)`` applies
    the "auto" policy: bf16 at rank >= 64 (traffic-bound), f32 below
    (VMEM-resident blocks, nothing to win)."""
    if steps is None:
        steps = min(A.shape[-1] + 4, _CG_STEP_CAP)
    A_mm = A.astype(jnp.bfloat16) if bf16_matvec else A
    x = jnp.zeros_like(b)
    r = b
    p = r
    rs = jnp.sum(r * r, axis=-1)

    def step(carry, _):
        x, r, p, rs = carry
        if bf16_matvec:
            Ap = jnp.einsum("...ij,...j->...i", A_mm,
                            p.astype(jnp.bfloat16),
                            preferred_element_type=jnp.float32)
        else:
            Ap = jnp.einsum("...ij,...j->...i", A_mm, p)
        denom = jnp.sum(p * Ap, axis=-1)
        # denom <= 0 only from rounding on a (near-)singular system —
        # exact-arithmetic SPD quadratic forms are positive, but the
        # bf16 matvec's ~4e-3 perturbation can cross zero when the
        # ridge is weak. Taking a zero step (not a 1e30 one) freezes
        # that system at its current iterate instead of poisoning the
        # whole training scan with inf/NaN.
        alpha = jnp.where(denom > 0, rs / jnp.where(denom > 0, denom, 1.0),
                          0.0)
        x = x + alpha[..., None] * p
        r = r - alpha[..., None] * Ap
        rs_new = jnp.sum(r * r, axis=-1)
        beta = rs_new / jnp.maximum(rs, 1e-30)
        p = r + beta[..., None] * p
        return (x, r, p, rs_new), None

    (x, _, _, _), _ = jax.lax.scan(
        step, (x, r, p, rs), None, length=steps)
    return x


def _normal_eq_solve(V, c, v, d, lam, alpha, gram, implicit, mm, prec,
                     cg_steps, solver="cg", cg_bf16=False):
    """Build and solve one slab-row batch of per-row normal equations.

    ``(c, v, d)`` are (B, L) cols/vals plus (B,) degrees for B complete
    rows; returns (B, K) solved factors (zero for empty rows). Shared by
    the per-bucket dispatch path (:func:`_solve_slabs`) and the fused
    single-program path (:func:`_solve_half_fused`)."""
    K = V.shape[1]
    L = c.shape[-1]
    eye = jnp.eye(K, dtype=jnp.float32)
    m = (jnp.arange(L, dtype=jnp.int32)[None, :]
         < d[:, None]).astype(jnp.float32)
    # V arrives pre-cast to ``mm`` by the callers (gather-table width
    # optimization: casting the TABLE once per half-step instead of the
    # gathered rows halves the bytes the gather walks in bf16 mode —
    # measured 8.92 -> 6.11 ns/padded row on the rank-200 item half,
    # where the 110MB f32 table is past the fast-gather tier; the cast
    # commutes with a row-gather, so values are bit-identical); the
    # astype below is a no-op then, and covers direct callers
    F = V[c].astype(mm)                 # (B, L, K) the row-gather
    if implicit:
        # Hu-Koren with MLlib trainImplicit's negative-rating semantics:
        # confidence c_ui = 1 + α|r|, preference p_ui = [r > 0], so a
        # negative rating is a HIGH-CONFIDENCE zero preference (dislike)
        # and r = 0 contributes nothing. A = VᵀV + Σ (c-1) v vᵀ + λI,
        # b = Σ c p v.
        w = (alpha * jnp.abs(v) * m).astype(mm)   # (c - 1) on observed
        A = jnp.einsum("bl,blk,blm->bkm", w, F, F, precision=prec,
                       preferred_element_type=jnp.float32)
        A = A + gram + lam * eye
        bw = jnp.where(v > 0, 1.0 + alpha * v, 0.0) * m    # c * p
        b = jnp.einsum("bl,blk->bk", bw.astype(mm), F,
                       precision=prec, preferred_element_type=jnp.float32)
    else:
        # ALS-WR: A = Σ v vᵀ + λ n_u I ; b = Σ r v
        Fm = F * m[..., None].astype(mm)
        A = jnp.einsum("blk,blm->bkm", Fm, F, precision=prec,
                       preferred_element_type=jnp.float32)
        n_u = jnp.sum(m, axis=1)
        A = A + (lam * n_u)[:, None, None] * eye
        b = jnp.einsum("bl,blk->bk", (v * m).astype(mm), F, precision=prec,
                       preferred_element_type=jnp.float32)
    # rows with zero ratings (padding rows): A = λ'I -> x = 0
    A = jnp.where(d[:, None, None] > 0, A, eye)
    if solver == "cholesky":
        x = _cho_solve_batched(A, b)
    else:
        x = _cg_solve_batched(A, b, steps=cg_steps, bf16_matvec=cg_bf16)
    return jnp.where(d[:, None] > 0, x, 0.0)


@partial(instrumented_jit,
         static_argnames=("implicit", "bf16", "lam", "alpha", "cg_steps",
                          "solver", "cg_bf16"),
         donate_argnums=())
def _solve_slabs(
    V: jax.Array,      # (num_cols, K) opposite factors, replicated
    cols: jax.Array,   # (S, B, L) int32
    vals: jax.Array,   # (S, B, L) f32, zero-padded
    deg: jax.Array,    # (S, B) int32 real entries per row
    lam: float,        # STATIC — baked into the program: a traced scalar
    alpha: float,      # would cost one synchronous host->device transfer
    gram: jax.Array,   # per call, which dominates on remote-attached
    implicit: bool,    # devices (measured ~350ms/call on the axon tunnel)
    bf16: bool = False,
    cg_steps: int | None = None,
    solver: str = "cg",
    cg_bf16: bool = False,
) -> jax.Array:
    """Per-slab batched normal-equation solve; scan bounds peak memory.

    ``bf16=True`` feeds the normal-equation einsums bf16 operands with
    f32 accumulation. Measured with the forcing protocol (bench.py
    header) on one v5e-class chip, ML-20M shapes, rank 32, chunked
    layout: 322ms vs 393ms per iteration (~22% faster; a round-1 claim
    that bf16 was slower came from the broken timing protocol and is
    retracted). Factor tables diverge ~5e-3 relative from the f32 path
    after 10 iterations — inside quality-parity tolerances but not
    bit-comparable, so f32-HIGHEST stays the default. The solve and
    regularisation stay f32. Opt in via
    ``als_train(matmul_dtype="bfloat16")``."""
    mm = jnp.bfloat16 if bf16 else jnp.float32
    prec = None if bf16 else _HI
    V = V.astype(mm)      # narrow gather table (gram is precomputed)

    def body(_, xs):
        c, v, d = xs                    # (B, L), (B, L), (B,)
        x = _normal_eq_solve(V, c, v, d, lam, alpha, gram, implicit,
                             mm, prec, cg_steps, solver, cg_bf16)
        return None, x

    _, X = jax.lax.scan(body, None, (cols, vals, deg))
    return X  # (S, B, K)


@instrumented_jit
def _gramian(V: jax.Array) -> jax.Array:
    return jnp.einsum("ik,im->km", V, V, precision=_HI)


@partial(instrumented_jit,
         static_argnames=("implicit", "bf16", "num_rows", "lam", "alpha",
                          "cg_steps", "cg_bf16"))
def _solve_half_chunked(
    V: jax.Array,           # (num_cols, K) opposite factors
    slabs: tuple,           # per size: (rids(S,B), cols(S,B,L), vals, deg)
    lam: float,             # static — see _solve_slabs note
    alpha: float,
    gram: jax.Array | None,  # VᵀV (implicit only; None otherwise)
    implicit: bool,
    num_rows: int,
    bf16: bool = False,
    cg_steps: int | None = None,
    cg_bf16: bool = False,
) -> jax.Array:
    """One ALS half-step over the chunked layout as a SINGLE program:
    per-chunk partial normal equations (batched einsums on the MXU),
    scatter-accumulated per row, then one batched conjugate-gradient
    solve over all rows (:func:`_cg_solve_batched` — its step count and
    clamps govern solve accuracy). One dispatch per half-step — launch
    count independent of the degree distribution (the bucketed path
    pays one dispatch per bucket, which dominates on high-latency
    links)."""
    K = V.shape[1]
    eye = jnp.eye(K, dtype=jnp.float32)
    mm = jnp.bfloat16 if bf16 else jnp.float32
    prec = None if bf16 else _HI
    V = V.astype(mm)      # narrow gather table (gram is precomputed)

    A_acc = jnp.zeros((num_rows, K, K), dtype=jnp.float32)
    b_acc = jnp.zeros((num_rows, K), dtype=jnp.float32)
    n_acc = jnp.zeros((num_rows,), dtype=jnp.float32)

    for rids, cols, vals, deg in slabs:
        L = cols.shape[-1]

        def body(carry, xs):
            A_acc, b_acc, n_acc = carry
            r, c, v, d = xs               # (B,), (B, L), (B, L), (B,)
            m = (jnp.arange(L, dtype=jnp.int32)[None, :]
                 < d[:, None]).astype(jnp.float32)
            F = V[c].astype(mm)           # (B, L, K)
            if implicit:
                # same c = 1 + α|r|, p = [r > 0] semantics as
                # _normal_eq_solve (MLlib trainImplicit parity)
                w = (alpha * jnp.abs(v) * m).astype(mm)
                A = jnp.einsum("bl,blk,blm->bkm", w, F, F, precision=prec,
                               preferred_element_type=jnp.float32)
                bw = jnp.where(v > 0, 1.0 + alpha * v, 0.0) * m
                b = jnp.einsum("bl,blk->bk", bw.astype(mm),
                               F, precision=prec,
                               preferred_element_type=jnp.float32)
            else:
                Fm = F * m[..., None].astype(mm)
                A = jnp.einsum("blk,blm->bkm", Fm, F, precision=prec,
                               preferred_element_type=jnp.float32)
                b = jnp.einsum("bl,blk->bk", (v * m).astype(mm), F,
                               precision=prec,
                               preferred_element_type=jnp.float32)
            A_acc = A_acc.at[r].add(A)
            b_acc = b_acc.at[r].add(b)
            n_acc = n_acc.at[r].add(jnp.sum(m, axis=1))
            return (A_acc, b_acc, n_acc), None

        (A_acc, b_acc, n_acc), _ = jax.lax.scan(
            body, (A_acc, b_acc, n_acc), (rids, cols, vals, deg))

    if implicit:
        A = A_acc + gram[None] + jnp.float32(lam) * eye[None]
    else:
        A = A_acc + (jnp.float32(lam) * n_acc)[:, None, None] * eye[None]
    active = n_acc > 0
    A = jnp.where(active[:, None, None], A, eye[None])
    x = _cg_solve_batched(A, b_acc, steps=cg_steps, bf16_matvec=cg_bf16)
    return jnp.where(active[:, None], x, 0.0)


def _solve_half_fused(V, buckets, lam, alpha, implicit, num_rows, bf16,
                      cg_steps, solver="cg", out_sharding=None,
                      cg_bf16=False):
    """One ALS half-step over the ladder layout, traced inline.

    Per bucket slab: build the complete per-row normal equations (every
    bucket row IS a whole row — no cross-chunk accumulation) and solve
    them in the same scan step, so A lives and dies slab-locally
    instead of streaming a (num_rows, K, K) HBM accumulator through the
    build (100ms/iter) and the CG (113ms/iter) as the chunked path does
    (scratch profile, ML-20M rank 32). The only scatter left is the
    (n, K) factor write-back per bucket — row-count-bound like the
    gather, ~0.5ms at ML-20M scale.

    ``out_sharding`` (tensor parallelism): a NamedSharding that pins the
    produced factor table row-sharded over the mesh's "model" axis. The
    opposite table V arrives with the same sharding; XLA inserts ONE
    all-gather of V for the slab gathers (cheaper than psum-of-partials
    whenever avg degree > 1) and scatters the write-back to the owning
    shard, so the PERSISTENT state — both factor tables — stays sharded
    and only one table at a time materialises transiently."""
    K = V.shape[1]
    mm = jnp.bfloat16 if bf16 else jnp.float32
    prec = None if bf16 else _HI
    gram = jnp.einsum("ik,im->km", V, V, precision=_HI) if implicit else None
    # gramian from the f32 table above; the slab gathers walk the
    # narrow table (see _normal_eq_solve's gather note)
    V = V.astype(mm)
    out = jnp.zeros((num_rows, K), dtype=jnp.float32)
    if out_sharding is not None:
        out = jax.lax.with_sharding_constraint(out, out_sharding)
    for row_ids, cols, vals, deg in buckets:
        n = row_ids.shape[0]   # static: row_ids is the (n,) unpadded id list

        def body(_, xs):
            c, v, d = xs
            x = _normal_eq_solve(V, c, v, d, lam, alpha, gram, implicit,
                                 mm, prec, cg_steps, solver, cg_bf16)
            return None, x

        _, X = jax.lax.scan(body, None, (cols, vals, deg))
        out = out.at[row_ids].set(X.reshape(-1, K)[:n])
    if out_sharding is not None:
        out = jax.lax.with_sharding_constraint(out, out_sharding)
    return out


@partial(instrumented_jit,
         static_argnames=("iterations", "lam", "alpha", "implicit",
                          "num_users", "num_items", "bf16", "cg_steps",
                          "solver", "mesh", "shard_factors", "cg_bf16"),
         donate_argnums=(0,))
def _als_iterate_fused(
    item0: jax.Array,
    user_buckets: tuple,    # per bucket: (row_ids(n,), cols(S,B,L), vals, deg(S,B))
    item_buckets: tuple,
    iterations: int,
    lam: float,
    alpha: float,
    implicit: bool,
    num_users: int,
    num_items: int,
    bf16: bool = False,
    cg_steps: int | None = None,
    solver: str = "cg",
    mesh: Mesh | None = None,
    shard_factors: bool = False,
    cg_bf16: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Full ALS training as ONE device program: ``lax.scan`` over
    alternating :func:`_solve_half_fused` half-steps. One dispatch per
    training run — on remote-attached devices (axon tunnel) per-call
    dispatch overhead is material, and the scan also lets XLA overlap
    consecutive iterations' transfers.

    ``shard_factors=True`` (with a ``mesh`` carrying a "model" axis
    > 1) is the tensor-parallel layout: BOTH carried factor tables stay
    row-sharded over "model" through every scan step (the BASELINE
    DP×MP configuration — MLlib's block-partitioned factors,
    ALSAlgorithm.scala:79-85). See :func:`_solve_half_fused` for the
    collective structure. ``num_users``/``num_items`` must be padded to
    a multiple of the model-axis size by the caller (als_train does)."""
    K = item0.shape[1]
    sh = None
    if shard_factors and mesh is not None and "model" in mesh.shape \
            and int(mesh.shape["model"]) > 1:
        sh = NamedSharding(mesh, P("model", None))
    u0 = jnp.zeros((num_users, K), dtype=jnp.float32)
    if sh is not None:
        u0 = jax.lax.with_sharding_constraint(u0, sh)

    def it_body(carry, _):
        _, item = carry
        user = _solve_half_fused(item, user_buckets, lam, alpha, implicit,
                                 num_users, bf16, cg_steps, solver,
                                 out_sharding=sh, cg_bf16=cg_bf16)
        item = _solve_half_fused(user, item_buckets, lam, alpha, implicit,
                                 num_items, bf16, cg_steps, solver,
                                 out_sharding=sh, cg_bf16=cg_bf16)
        return (user, item), None

    (user, item), _ = jax.lax.scan(
        it_body, (u0, item0), None, length=iterations)
    return user, item


def _fused_bucket_args(staged: DeviceBucketedRatings) -> tuple:
    return tuple((b.row_ids, b.cols, b.vals, b.deg)
                 for b in staged.buckets)


#: cap on the per-slab normal-matrix block: slab_rows * rank^2 floats.
#: 8M floats = 32 MB keeps the (B, K, K) systems VMEM-resident through
#: the in-scan CG at any rank — at rank 200 the default element budget
#: alone allowed B=655 (a 105 MB block that spilled to HBM and was
#: re-streamed by all 24 CG steps: measured 1.15 s/iter at the ML-20M
#: shape vs 0.56 s/iter once the block fits).
_MAX_SOLVE_ELEMS = 8 << 20


def _slab_shape(
    n: int, pad_len: int, rank: int, data_axis: int, max_slab_elems: int
) -> tuple[int, int]:
    """Pick (num_slabs, slab_rows): slab_rows a multiple of the data-axis
    size with slab_rows*pad_len*rank <= max_slab_elems and
    slab_rows*rank^2 <= _MAX_SOLVE_ELEMS (VMEM-sized solve blocks)."""
    per_row = pad_len * rank
    b = max(1, max_slab_elems // per_row)
    b = min(b, max(1, _MAX_SOLVE_ELEMS // (rank * rank)))
    b = max(data_axis, (b // data_axis) * data_axis)
    b = min(b, ((n + data_axis - 1) // data_axis) * data_axis)
    s = (n + b - 1) // b
    return s, b


#: rank at or above which the "auto" CG matvec policy streams A in
#: bfloat16: the per-slab (B, K, K) blocks stop fitting the CG's fast
#: path and each step re-streams A, so halving its width is ~free
#: speedup (1.51x measured at rank 200); below it the blocks are
#: VMEM-resident and f32 costs nothing
_CG_BF16_RANK = 64


def _resolve_cg_matvec(cg_matvec_dtype: str, rank: int) -> bool:
    if cg_matvec_dtype not in ("auto", "float32", "bfloat16"):
        raise ValueError(
            "cg_matvec_dtype must be 'auto', 'float32' or 'bfloat16', "
            f"got {cg_matvec_dtype!r}")
    if cg_matvec_dtype == "auto":
        return rank >= _CG_BF16_RANK
    return cg_matvec_dtype == "bfloat16"


def solve_half(
    V: jax.Array,
    bucketed: "BucketedRatings | DeviceBucketedRatings | ChunkedRatings | DeviceChunkedRatings",
    rank: int,
    lam: float,
    implicit: bool = False,
    alpha: float = 40.0,
    mesh: Mesh | None = None,
    max_slab_elems: int = 1 << 24,
    matmul_dtype: str = "float32",
    shard_factors: bool = False,
    cg_steps: int | None = None,
    solver: str = "cg",
    cg_matvec_dtype: str = "float32",
) -> jax.Array:
    """One ALS half-step: solve all row factors given opposite factors V.

    Returns a (num_rows, K) factor table (replicated under ``mesh``);
    rows with no ratings get zero factors, matching MLlib which simply
    omits them from the factor RDD.

    Dispatches on layout: chunked inputs (:func:`chunk_rows` /
    :func:`stage_chunks`) take the single-dispatch accumulate-then-solve
    program; bucketed inputs take the per-bucket solve.

    ``shard_factors=True`` (with a mesh that has a "model" axis) keeps
    the opposite factor table V row-sharded over that axis — the
    tensor-parallel layout for catalog-scale tables that exceed one
    device's HBM. XLA inserts the gathers for the slab lookups over ICI;
    with ``False`` (default) V is replicated, which is faster whenever
    it fits.

    Pass a :class:`DeviceBucketedRatings` (from :func:`stage_buckets`) /
    :class:`DeviceChunkedRatings` (:func:`stage_chunks`) when calling
    repeatedly — host layouts are staged per call (bounded device
    memory, but re-transferred every call, which is transfer-bound
    across iterations).
    """
    if matmul_dtype not in ("float32", "bfloat16"):
        raise ValueError(
            f"matmul_dtype must be 'float32' or 'bfloat16', got {matmul_dtype!r}"
        )
    cg_bf16 = _resolve_cg_matvec(cg_matvec_dtype, rank)
    # lam/alpha are STATIC jit args (hashable floats) and gram is None
    # unless needed: a host scalar argument costs one synchronous
    # host->device transfer per call, which dominates iteration time on
    # remote-attached devices (measured ~750ms/iteration of pure
    # transfer overhead on the axon tunnel before this change)
    lam_a = float(lam)
    alpha_a = float(alpha)
    gram = _gramian(V) if implicit else None

    if isinstance(bucketed, (ChunkedRatings, DeviceChunkedRatings)):
        if isinstance(bucketed, ChunkedRatings):
            bucketed = stage_chunks(bucketed, rank, mesh, max_slab_elems)
        if mesh is not None:
            rep = NamedSharding(mesh, P())
            if shard_factors and "model" in mesh.shape and \
                    int(mesh.shape["model"]) > 1:
                axis = int(mesh.shape["model"])
                pad = (-V.shape[0]) % axis
                if pad:
                    V = jnp.concatenate(
                        [V, jnp.zeros((pad, V.shape[1]), dtype=V.dtype)])
                V = jax.device_put(V, NamedSharding(mesh, P("model", None)))
            else:
                V = jax.device_put(V, rep)
        slabs = tuple(
            (s.row_ids, s.cols, s.vals, s.deg) for s in bucketed.slabs
        )
        if solver != "cg":
            raise ValueError(
                "solver='cholesky' is a bucketed/fused-layout option; the "
                "chunked path solves over the scan-carried accumulator")
        return _solve_half_chunked(
            V, slabs, lam_a, alpha_a, gram, implicit, bucketed.num_rows,
            bf16=(matmul_dtype == "bfloat16"), cg_steps=cg_steps,
            cg_bf16=cg_bf16,
        )

    out = jnp.zeros((bucketed.num_rows, rank), dtype=jnp.float32)
    if mesh is not None:
        rep = NamedSharding(mesh, P())
        if shard_factors and "model" in mesh.shape and \
                int(mesh.shape["model"]) > 1:
            axis = int(mesh.shape["model"])
            pad = (-V.shape[0]) % axis
            if pad:
                # zero rows: never indexed by any slab (col ids are
                # < num_cols) and contribute nothing to the gramian
                V = jnp.concatenate(
                    [V, jnp.zeros((pad, V.shape[1]), dtype=V.dtype)])
            V = jax.device_put(V, NamedSharding(mesh, P("model", None)))
        else:
            V = jax.device_put(V, rep)
        out = jax.device_put(out, rep)
    if matmul_dtype == "bfloat16":
        # narrow the gather table ONCE per half-step, not once per
        # bucket dispatch (gram above is taken from the f32 table; the
        # in-jit astype becomes a no-op)
        V = V.astype(jnp.bfloat16)

    streaming = isinstance(bucketed, BucketedRatings)
    for bucket in bucketed.buckets:
        if streaming:  # transient slabs, freed after this bucket's solve
            bucket = _stage_bucket(bucket, rank, mesh, max_slab_elems)
        X = _solve_slabs(V, bucket.cols, bucket.vals, bucket.deg,
                         lam_a, alpha_a, gram, implicit,
                         bf16=(matmul_dtype == "bfloat16"),
                         cg_steps=cg_steps, solver=solver,
                         cg_bf16=cg_bf16)
        X = X.reshape(-1, rank)[: bucket.n]
        out = out.at[bucket.row_ids].set(X)
    return out


# ---------------------------------------------------------------------------
# Training driver
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ALSFactors:
    user: jax.Array  # (num_users, K)
    item: jax.Array  # (num_items, K)


def resolve_shard_factors(param: bool) -> bool:
    """The engine-params ``shardFactors`` knob with its fleet-wide env
    override applied: ``PIO_TRAIN_SHARD_FACTORS=1`` forces DP×MP factor
    sharding on (retraining a grown catalog without editing every
    engine.json), ``=0`` forces replicated (an incident lever — sharded
    training needs a healthy multi-device mesh), unset defers to the
    param. All the ALS-family templates route through here so the env
    contract cannot drift between them (docs/parallelism.md)."""
    raw = os.environ.get("PIO_TRAIN_SHARD_FACTORS", "").strip().lower()
    if raw in ("1", "true", "on", "yes"):
        return True
    if raw in ("0", "false", "off", "no"):
        return False
    return bool(param)


def als_train(
    ratings: RatingsCOO,
    rank: int,
    iterations: int = 10,
    lam: float = 0.01,
    implicit: bool = False,
    alpha: float = 40.0,
    seed: int = 0,
    mesh: Mesh | None = None,
    min_bucket: int = 8,
    bucket_growth: int = 2,
    max_row_len: int | None = None,
    max_slab_elems: int = 1 << 24,
    hbm_resident: bool = True,
    matmul_dtype: str = "bfloat16",
    layout: str = "auto",
    chunk_sizes: Sequence[int] = (512, 128),
    chunked_acc_budget: int = 4 << 30,
    cg_steps: int | None = None,
    solver: str = "cg",
    shard_factors: bool = False,
    cg_matvec_dtype: str = "auto",
) -> ALSFactors:
    """Full alternating-least-squares training.

    Parity target: `ALS.train(ratings, rank, iterations, lambda)` /
    `ALS.trainImplicit(..., alpha)` semantics from the reference templates
    (ALSAlgorithm.scala:79-85); same hyperparameter meanings.

    ``layout="fused"`` (the ``"auto"`` default) pads whole rows to the
    MXU-width ladder (:func:`ladder_rows`) and runs ALL iterations as
    one device program (:func:`_als_iterate_fused`): normal equations
    are built and CG-solved slab-locally — no (num_rows, K, K)
    accumulator, no K×K scatter, one dispatch per training run. No
    ratings are dropped.
    ``layout="chunked"`` decomposes rows into fixed-size chunks
    (:func:`chunk_rows`): one dispatch per half-step, MXU-width
    contractions, no dropped ratings, ``len(chunk_sizes)`` compile keys
    — but carries a scan-threaded per-row accumulator (the phase
    profile that motivated the fused path: gather 119 / einsum 61 /
    scatter 100 / CG 113 ms per ML-20M rank-32 iteration).
    ``layout="bucketed"`` pads whole rows into a power-of-``bucket_growth``
    ladder (:func:`bucket_rows`) — the only mode supporting
    ``max_row_len``/streaming, at one dispatch per bucket.
    ``layout="auto"`` picks fused unless a bucketed-only knob
    (``max_row_len``, ``hbm_resident=False``) is set.
    ``chunked_acc_budget`` is unused since ``auto`` stopped routing on
    accumulator size (the fused layout is accumulator-free); retained
    for call-site compatibility.

    ``hbm_resident=True`` stages all rating slabs on device once (fast;
    needs ~8 bytes x padded nnz x 2 orientations of HBM).
    ``hbm_resident=False`` streams one slab batch at a time per
    half-step (bucketed layout only) — peak device memory bounded by
    ``max_slab_elems`` at the cost of re-transferring every iteration.

    ``matmul_dtype="bfloat16"`` (default) feeds the normal-equation
    einsums bf16 operands with f32 accumulation — measured 22-27%
    faster at ML-20M rank 32 with factors within ~5e-3 relative of the
    f32 path and every quality gate (RMSE parity, MAP seed band,
    implicit-beats-popularity) holding. Pass
    ``matmul_dtype="float32"`` for f32-HIGHEST bit-for-bit solver
    reproducibility.

    ``solver="cg"`` (default) uses the TPU-fast batched conjugate
    gradients at its measured-f32-plateau step cap (``cg_steps``
    overrides); ``solver="cholesky"`` opts into the exact direct solve
    (``_cho_solve_batched``) — 10-20x slower on TPU, useful as an
    accuracy oracle or for pathologically conditioned data. Fused and
    bucketed layouts only.

    ``cg_matvec_dtype="auto"`` (default) streams the CG's A-matrix in
    bfloat16 (f32 accumulation) at rank >= 64, where the per-slab
    systems are HBM-traffic-bound — measured 1.51x at the ML-20M
    rank-200 config with solve accuracy ~2.5e-3 relative vs an f64
    oracle (inside the band the bf16 normal-equation build already
    accepts; the rank-200 RMSE parity gate holds). ``"float32"`` /
    ``"bfloat16"`` force either way (see ``_cg_solve_batched``).

    ``shard_factors=True`` (with a ``mesh`` whose "model" axis is > 1)
    keeps BOTH factor tables row-sharded over the model axis for the
    whole run — the DP×MP tensor-parallel layout for catalog-scale
    tables that exceed one device's HBM (BASELINE's sharded-embeddings
    configuration). On the fused layout the tables are padded to a
    multiple of the model-axis size, stay sharded across every
    iteration of the scan, and the result tables come back sharded;
    XLA all-gathers one (opposite) table transiently per half-step for
    the slab gathers. Replicated (default) is faster whenever both
    tables fit. See docs/parallelism.md.
    """
    if layout not in ("auto", "fused", "chunked", "bucketed"):
        raise ValueError(
            f"layout must be 'auto', 'fused', 'chunked' or 'bucketed', "
            f"got {layout!r}")
    if layout == "auto":
        if max_row_len is not None or not hbm_resident:
            layout = "bucketed"   # row capping / streaming knobs
        else:
            layout = "fused"
    if layout == "fused" and (max_row_len is not None or not hbm_resident):
        raise ValueError(
            "max_row_len / hbm_resident=False are bucketed-layout knobs; "
            "pass layout='bucketed' (or 'auto') to use them")
    if layout == "fused":
        by_user = ladder_rows(ratings)
        by_item = ladder_rows(ratings.transpose())
        logger.info(
            "ALS(fused): %d ratings, %d users (%d buckets), %d items "
            "(%d buckets), rank %d",
            ratings.nnz, ratings.num_rows, len(by_user.buckets),
            ratings.num_cols, len(by_item.buckets), rank,
        )
        dev_user = stage_buckets(by_user, rank, mesh, max_slab_elems)
        dev_item = stage_buckets(by_item, rank, mesh, max_slab_elems)
        tp = bool(shard_factors and mesh is not None
                  and "model" in mesh.shape and int(mesh.shape["model"]) > 1)
        # table row counts pad to the model-axis size so every device
        # holds an equal shard; padded rows are never indexed by any
        # slab (col ids < num_cols) and are sliced off below
        model_ax = int(mesh.shape["model"]) if tp else 1
        num_users_p = ratings.num_rows + (-ratings.num_rows) % model_ax
        num_items_p = ratings.num_cols + (-ratings.num_cols) % model_ax
        key = jax.random.PRNGKey(seed)
        item0 = jax.random.normal(key, (ratings.num_cols, rank),
                                  dtype=jnp.float32)
        item0 = item0 / jnp.sqrt(jnp.float32(rank))
        if num_items_p != ratings.num_cols:
            # pad rows are ZERO: never gathered (col ids < num_cols),
            # and the implicit-mode gramian sums over every table row
            item0 = jnp.concatenate(
                [item0, jnp.zeros((num_items_p - ratings.num_cols, rank),
                                  dtype=jnp.float32)])
        if tp:
            item0 = jax.device_put(
                item0, NamedSharding(mesh, P("model", None)))
        user, item = _als_iterate_fused(
            item0, _fused_bucket_args(dev_user), _fused_bucket_args(dev_item),
            iterations, float(lam), float(alpha), implicit,
            num_users_p, num_items_p,
            bf16=(matmul_dtype == "bfloat16"), cg_steps=cg_steps,
            solver=solver, mesh=mesh if tp else None, shard_factors=tp,
            cg_bf16=_resolve_cg_matvec(cg_matvec_dtype, rank),
        )
        if num_users_p != ratings.num_rows:
            user = user[: ratings.num_rows]
        if num_items_p != ratings.num_cols:
            item = item[: ratings.num_cols]
        return ALSFactors(user=user, item=item)
    if layout == "chunked" and (max_row_len is not None or not hbm_resident):
        raise ValueError(
            "max_row_len / hbm_resident=False are bucketed-layout knobs "
            "(row capping and streaming); pass layout='bucketed' (or "
            "'auto') to use them — the chunked layout never drops ratings "
            "and stages slabs HBM-resident"
        )
    if layout == "chunked":
        by_user = chunk_rows(ratings, chunk_sizes)
        by_item = chunk_rows(ratings.transpose(), chunk_sizes)
        logger.info(
            "ALS: %d ratings, %d users, %d items, rank %d, chunks %s",
            ratings.nnz, ratings.num_rows, ratings.num_cols, rank,
            tuple(s.cols.shape for s in by_user.slabs),
        )
        by_user = stage_chunks(by_user, rank, mesh, max_slab_elems)
        by_item = stage_chunks(by_item, rank, mesh, max_slab_elems)
        key = jax.random.PRNGKey(seed)
        item = jax.random.normal(key, (ratings.num_cols, rank),
                                 dtype=jnp.float32)
        item = item / jnp.sqrt(jnp.float32(rank))
        user = None
        for _ in range(iterations):
            user = solve_half(item, by_user, rank, lam, implicit, alpha,
                              mesh, max_slab_elems, matmul_dtype,
                              shard_factors=shard_factors,
                              cg_steps=cg_steps, solver=solver,
                              cg_matvec_dtype=cg_matvec_dtype)
            item = solve_half(user, by_item, rank, lam, implicit, alpha,
                              mesh, max_slab_elems, matmul_dtype,
                              shard_factors=shard_factors,
                              cg_steps=cg_steps, solver=solver,
                              cg_matvec_dtype=cg_matvec_dtype)
        return ALSFactors(user=user, item=item)

    by_user = bucket_rows(ratings, min_bucket, bucket_growth, max_row_len)
    by_item = bucket_rows(ratings.transpose(), min_bucket, bucket_growth, max_row_len)
    logger.info(
        "ALS: %d ratings, %d users (%d buckets), %d items (%d buckets), rank %d",
        ratings.nnz, ratings.num_rows, len(by_user.buckets),
        ratings.num_cols, len(by_item.buckets), rank,
    )
    if hbm_resident:
        # stage slabs in HBM once — iterations are then pure device compute
        by_user = stage_buckets(by_user, rank, mesh, max_slab_elems)
        by_item = stage_buckets(by_item, rank, mesh, max_slab_elems)

    # MLlib-style init: scaled gaussian item factors, users solved first
    key = jax.random.PRNGKey(seed)
    item = jax.random.normal(key, (ratings.num_cols, rank), dtype=jnp.float32)
    item = item / jnp.sqrt(jnp.float32(rank))

    user = None
    for it in range(iterations):
        user = solve_half(item, by_user, rank, lam, implicit, alpha, mesh,
                          max_slab_elems, matmul_dtype,
                          shard_factors=shard_factors, cg_steps=cg_steps,
                          solver=solver, cg_matvec_dtype=cg_matvec_dtype)
        item = solve_half(user, by_item, rank, lam, implicit, alpha, mesh,
                          max_slab_elems, matmul_dtype,
                          shard_factors=shard_factors, cg_steps=cg_steps,
                          solver=solver, cg_matvec_dtype=cg_matvec_dtype)
    return ALSFactors(user=user, item=item)


# ---------------------------------------------------------------------------
# Prediction helpers
# ---------------------------------------------------------------------------


@instrumented_jit
def predict_ratings(user_f: jax.Array, item_f: jax.Array,
                    users: jax.Array, items: jax.Array) -> jax.Array:
    """Pointwise predicted ratings for (user, item) pairs."""
    return jnp.einsum("nk,nk->n", user_f[users], item_f[items])


def rmse(factors: ALSFactors, ratings: RatingsCOO, chunk: int = 1 << 20) -> float:
    """Root-mean-square error over the rating set, chunked to bound memory."""
    total = 0.0
    n = ratings.nnz
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        pred = predict_ratings(
            factors.user, factors.item,
            jnp.asarray(ratings.rows[s:e]), jnp.asarray(ratings.cols[s:e]),
        )
        err = np.asarray(pred) - ratings.vals[s:e]
        total += float(np.sum(err * err))
    return math.sqrt(total / max(n, 1))
