"""ANN maximum-inner-product retrieval: IVF-flat index + exact rescore.

The serving paths in ops/topk score the FULL item table per query —
O(catalog) forever, already past its cache cliff at 100k items on the
bench host and hopeless at the million-item north star. This module
adds the classic sublinear alternative (FAISS-style IVF-flat, the
survey's "shortlist then rescore" shape):

- **build** (train/persist time, host-side numpy): k-means over the
  item-factor table partitions the catalog into ``nlist`` cells; the
  membership lives in CSR form — ``flat_items`` (item ids grouped by
  cell), ``flat_vecs`` (their vectors in the same order, so each
  cell's block is contiguous), ``cell_offset`` — jit-friendly dense
  arrays, checkpointable through the existing ``utils/checkpoint``
  envelope, and device-resident at serving time. An earlier padded
  ``(nlist, pad, K)`` block layout paid MAX cell size per probe: with
  balanced lists capped at 2x the mean, HALF the gathered bytes were
  padding — the CSR gather of only real members measured 2.1x faster
  on the dominant stage at the 1M point (0.9ms vs 1.9ms) and stores
  one copy of the vectors instead of two;
- **probe** (serving time, one jitted dispatch): score the query
  against the ``nlist`` centroids (a (B, nlist) matmul — tiny), take
  the top ``nprobe`` cells, and walk their CSR runs into a
  statically-budgeted shortlist (:func:`_budget_width`: ~1.25x the
  mean probed mass; overflow truncates the tail of the WORST-scoring
  probed cells, and the quality harness measures the effect rather
  than assuming it away);
- **exact rescore**: the shortlist's item vectors are gathered from
  the SAME factor table brute force uses and scored with the SAME
  inner product — ranking within the shortlist is exact, so quality
  loss is purely recall (did the true top-k land in a probed cell),
  which the quality harness measures instead of assuming.

Seen-item and business-rule masking keep working on the shortlist: the
``allow`` vector is gathered per candidate, and seen lists mask by
membership test in global item coordinates (a ``lax.scan`` over the
seen width — O(B x S) per seen column, never a (B, S, seen) cube in
memory). Sentinel/-inf semantics match ``recommend_topk_chunked``:
slots beyond the eligible candidates carry -inf values and
out-of-range indices (>= n_items), and callers must treat non-finite
slots as absent — which every in-repo consumer already does.

Static-shape discipline (the serving contract): ``k``, ``nprobe`` and
the rescore budget are jit-static and snapped by callers to the shared
serving menus, so a client cycling query parameters can never mint a
fresh compile behind the micro-batcher.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from functools import partial
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.obs.compile import instrumented_jit

from predictionio_tpu.ops.topk import NEG_INF

logger = logging.getLogger(__name__)

#: below this catalog size the flat matmul beats any probe+gather trip
#: and the index is pure overhead — build refuses, serving falls back
#: to brute (also the guard that keeps tiny unit-test models index-free)
MIN_INDEX_ITEMS = 1024

#: bounds for the auto nlist heuristic (~sqrt(catalog), power of two)
_MIN_NLIST = 8
_MAX_NLIST = 4096


def auto_nlist(n_items: int) -> int:
    """Power-of-two cell count near 4*sqrt(catalog) — the FAISS-style
    IVF sizing band (4..16 x sqrt(n)). Finer cells beat the sqrt(n)
    textbook point on BOTH axes here: each probed column is likelier
    relevant (recall per rescored byte) and the per-probe run is
    smaller (measured at the 1M point: nlist=4096/nprobe=64 gives
    0.998 recall at 0.8ms where nlist=1024/nprobe=64 gave 0.969 at
    7.8ms); the probe matmul (B x nlist) stays trivial."""
    if n_items <= 0:
        return _MIN_NLIST
    target = 1 << round(math.log2(max(4.0 * math.sqrt(n_items), 2.0)))
    # floor the MEAN cell size at ~128 members: finer cells on small
    # catalogs are noise-dominated (k-means fits the sampling noise,
    # recall per probe drops — measured at 16k items) and their padded
    # blocks waste the probe's streaming advantage
    cap = 1 << max(int(math.log2(n_items // 128)), 3) \
        if n_items >= 1024 else _MIN_NLIST
    return max(_MIN_NLIST, min(_MAX_NLIST, target, cap))


def auto_nprobe(nlist: int) -> int:
    """Default probe count: 1/64 of the cells, floored at 16. At the
    auto nlist (4*sqrt(n) cells) this rescores ~2-3% of the catalog,
    the measured MAP@10-within-1%-of-brute point on factor-shaped data
    (1M items: 64/4096 probes = 0.998 recall; the floor covers small
    catalogs where recall per probed cell is lower); callers clamp to
    nlist via :meth:`AnnIndex.clamp_nprobe`."""
    return max(16, nlist // 64)


#: static shortlist budget = nprobe x mean cell size x this margin.
#: The CSR walk needs a jit-static candidate width; the mean probed
#: mass is nprobe x (n/nlist), and 1.25x absorbs most of the
#: sum-of-probed-cell-sizes variance (cells are capacity-capped at
#: ``balance``x the mean, so the worst case is bounded). When the
#: probed runs overflow the budget, the TAIL — the worst-scoring
#: probed cells, since runs concatenate in probe-score order — is
#: truncated; the quality harness measures that recall cost.
_BUDGET_MARGIN = 1.25


def _budget_width(n_items: int, nlist: int, nprobe: int,
                  rescore: int) -> int:
    """The static candidate-column count of a probe with these knobs
    (:data:`_BUDGET_MARGIN`); ``rescore > 0`` caps it."""
    mean = max(1.0, n_items / max(nlist, 1))
    width = min(n_items, int(math.ceil(nprobe * mean * _BUDGET_MARGIN)))
    if rescore > 0:
        width = min(width, rescore)
    return max(1, width)


@dataclasses.dataclass
class AnnIndex:
    """IVF-flat coarse quantizer over an item-factor table, CSR layout.

    Host numpy arrays are canonical (they serialize through the
    checkpoint envelope); device copies are materialised once on first
    query and cached — the same lazy-device pattern as
    ``ALSModel._default_allow``.
    """

    nlist: int
    n_items: int
    centroids: np.ndarray    # (nlist, K) f32
    #: item ids grouped by cell — cell c's members are
    #: flat_items[cell_offset[c]:cell_offset[c+1]]
    flat_items: np.ndarray   # (n_items,) int32
    #: the member vectors in the SAME cell-grouped order: each probed
    #: cell rescores from one contiguous run, which is the layout win
    #: IVF-flat exists for (module docstring: 2.1x over padded blocks,
    #: and one copy of the vectors instead of balance-x two). Values
    #: are bit-identical to the factor table rows — rescore is EXACT.
    flat_vecs: np.ndarray = None    # (n_items, K) f32
    cell_offset: np.ndarray = None  # (nlist + 1,) int32
    _device: tuple | None = dataclasses.field(default=None, repr=False,
                                              compare=False)

    @property
    def max_cell(self) -> int:
        return int(np.diff(self.cell_offset).max())

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_device"] = None
        return state

    def device_arrays(self) -> tuple:
        """(centroids, flat_items, flat_vecs, cell_offset) as
        device-resident jax.Arrays, uploaded once."""
        if self._device is None:
            self._device = (
                jax.device_put(jnp.asarray(self.centroids)),
                jax.device_put(jnp.asarray(self.flat_items)),
                jax.device_put(jnp.asarray(self.flat_vecs)),
                jax.device_put(jnp.asarray(self.cell_offset)),
            )
        return self._device

    def clamp_nprobe(self, nprobe: int) -> int:
        """Snap a requested probe count into [1, nlist]; 0 = auto."""
        if nprobe <= 0:
            return min(auto_nprobe(self.nlist), self.nlist)
        return min(nprobe, self.nlist)

    def shortlist_width(self, nprobe: int, rescore: int = 0) -> int:
        """The STATIC candidate-column count a query with these knobs
        walks and rescores (budget slots included) — the jit-signature
        width and the observability number `/stats.json` reports."""
        return _budget_width(self.n_items, self.nlist,
                             self.clamp_nprobe(nprobe), rescore)

    # ---- persistence (utils/checkpoint envelope) -----------------------
    def to_arrays(self) -> dict[str, np.ndarray]:
        return {
            "centroids": self.centroids,
            "flat_items": self.flat_items,
            "flat_vecs": self.flat_vecs,
            "cell_offset": self.cell_offset,
        }

    @staticmethod
    def from_arrays(arrays: Mapping[str, Any], n_items: int) -> "AnnIndex":
        # dtype-preserving on the persisted layout: the checkpoint
        # writes these at exactly these dtypes, so asarray is a VIEW —
        # under load_sharded(mmap_mode="r") the arrays (flat_vecs
        # above all) stay page-cache-backed and N prefork workers
        # share one physical copy (docs/serving-performance.md
        # "Model memory: replicated vs mmap")
        centroids = np.asarray(arrays["centroids"], dtype=np.float32)
        return AnnIndex(
            nlist=int(centroids.shape[0]),
            n_items=int(n_items),
            centroids=centroids,
            flat_items=np.asarray(arrays["flat_items"], dtype=np.int32),
            flat_vecs=np.asarray(arrays["flat_vecs"], dtype=np.float32),
            cell_offset=np.asarray(arrays["cell_offset"], dtype=np.int32),
        )


# ---------------------------------------------------------------------------
# build (host-side numpy; train/persist time, never on the query path)
# ---------------------------------------------------------------------------


def _assign(x: np.ndarray, centroids: np.ndarray,
            chunk: int = 65536) -> np.ndarray:
    """Nearest-centroid assignment, chunked so a million-item catalog
    never materialises the full (n, nlist) distance matrix. argmin of
    the L2 distance == argmax of (x·c - |c|^2/2)."""
    half = 0.5 * np.einsum("ck,ck->c", centroids, centroids)
    out = np.empty(len(x), dtype=np.int32)
    for lo in range(0, len(x), chunk):
        scores = x[lo:lo + chunk] @ centroids.T
        scores -= half[None, :]
        out[lo:lo + chunk] = np.argmax(scores, axis=1).astype(np.int32)
    return out


#: ranked alternative cells considered per item by the balanced
#: assignment before the any-cell-with-space fallback. 16 matters:
#: with 4 choices on clustered factors, overflow items landed in
#: geometrically unrelated cells and became unreachable at any sane
#: nprobe — recall PLATEAUED at 0.986 no matter how many cells a 1M
#: query probed; 16 ranked choices keep spills near their cluster and
#: lifted the same sweep to 0.998
_BALANCE_CHOICES = 16


def _assign_balanced(x: np.ndarray, centroids: np.ndarray, cap: int,
                     chunk: int = 65536) -> np.ndarray:
    """Capacity-bounded assignment: every cell holds at most ``cap``
    members. The shortlist budget is sized from the MEAN cell
    (:data:`_BUDGET_MARGIN`), so one hot k-means cell — measured 4x
    the mean on clustered factors — would eat the whole budget and
    truncate every other probed cell out of the rescore. Items
    overflowing their nearest cell spill to the next-nearest with
    space (up to ``_BALANCE_CHOICES`` ranked choices, then any cell
    with room); spilled items stay reachable, costing recall only when
    a query probes the full cell but not the neighbour — which the
    quality harness measures rather than assumes. (Tightening the cap
    toward 1x the mean is NOT free: at 1.05-1.3x, recall plateaued at
    ~0.93 no matter the nprobe — too many items spill beyond their
    cluster's neighbourhood; 2x keeps the 0.998+ sweeps.)"""
    nlist = len(centroids)
    half = 0.5 * np.einsum("ck,ck->c", centroids, centroids)
    n_choices = min(_BALANCE_CHOICES, nlist)
    choices = np.empty((len(x), n_choices), dtype=np.int32)
    for lo in range(0, len(x), chunk):
        scores = x[lo:lo + chunk] @ centroids.T
        scores -= half[None, :]
        top = np.argpartition(scores, -n_choices, axis=1)[:, -n_choices:]
        row = np.arange(len(top))[:, None]
        order = np.argsort(scores[row, top], axis=1)[:, ::-1]
        choices[lo:lo + chunk] = top[row, order].astype(np.int32)
    assign = np.full(len(x), -1, dtype=np.int32)
    counts = np.zeros(nlist, dtype=np.int64)
    for r in range(n_choices):
        unplaced = np.nonzero(assign < 0)[0]
        if not len(unplaced):
            break
        cells = choices[unplaced, r]
        order = np.argsort(cells, kind="stable")
        sorted_cells = cells[order]
        starts = np.searchsorted(sorted_cells, np.arange(nlist))
        rank = np.arange(len(sorted_cells)) - starts[sorted_cells]
        ok = rank < (cap - counts)[sorted_cells]
        assign[unplaced[order[ok]]] = sorted_cells[ok]
        counts += np.bincount(sorted_cells[ok], minlength=nlist)
    leftover = np.nonzero(assign < 0)[0]
    if len(leftover):
        space = np.repeat(np.arange(nlist, dtype=np.int32),
                          np.maximum(cap - counts, 0))
        assign[leftover] = space[:len(leftover)]
    return assign


#: rows per device_get chunk when the index build must gather a
#: sharded factor table to host — bounds the staging buffer to
#: ~chunk*rank*4 bytes (64 MiB at rank 512) regardless of table size
_GATHER_CHUNK_ROWS = 32768


def _host_vectors(item_f: Any) -> np.ndarray:
    """The item-factor table as host float32 rows, WITHOUT assuming it
    already lives on the host. Three sources, three behaviors:

    - plain ndarray / ``np.memmap`` (``--model-mmap`` deploys): pass
      through — ``ascontiguousarray`` on a contiguous f32 memmap is a
      view, so the page-cache sharing survives and no full copy is
      staged up front;
    - replicated / single-device ``jax.Array``: one device_get, as the
      build always did;
    - **row-sharded** ``jax.Array`` (a ``shard_factors`` model): the
      shards are gathered one bounded chunk at a time
      (:data:`_GATHER_CHUNK_ROWS` rows per ``device_get``) into one
      preallocated host buffer, with a pinned WARNING — the k-means
      build is the one consumer that genuinely needs the whole table
      host-resident, and a forced gather should be visible in deploy
      logs. Never replicates on device (the sharded table may not FIT
      replicated) and never stages more than one chunk of transfer at
      a time."""
    if isinstance(item_f, jax.Array) and not isinstance(item_f, np.ndarray):
        shards = list(getattr(item_f, "addressable_shards", ()) or ())
        if len(shards) > 1 and not item_f.is_fully_replicated:
            out = np.empty(item_f.shape, dtype=np.float32)
            logger.warning(
                "ann index build forcing a chunked host gather of the "
                "sharded item table (%d rows x %d, %d shards, %d-row "
                "chunks)", item_f.shape[0], item_f.shape[1],
                len(shards), _GATHER_CHUNK_ROWS)
            done_rows: set[int] = set()
            for shard in shards:
                rows = shard.index[0] if shard.index else slice(None)
                start = int(rows.start or 0)
                if start in done_rows:
                    continue  # data-axis replica of a row block
                done_rows.add(start)
                data = shard.data
                for lo in range(0, int(data.shape[0]), _GATHER_CHUNK_ROWS):
                    hi = min(lo + _GATHER_CHUNK_ROWS, int(data.shape[0]))
                    out[start + lo : start + hi] = np.asarray(
                        data[lo:hi], dtype=np.float32)
            return out
        return np.ascontiguousarray(np.asarray(item_f), dtype=np.float32)
    return np.ascontiguousarray(np.asarray(item_f), dtype=np.float32)


def build_index(item_f: Any, nlist: int = 0, seed: int = 0,
                iters: int = 8, sample: int = 131072,
                balance: float = 2.0) -> AnnIndex | None:
    """K-means coarse quantizer over the item-factor table.

    Lloyd iterations run on a seeded SAMPLE (k-means converges on the
    density, not the row count — a full-catalog fit would spend minutes
    of the persist stage for no recall gain), then ONE chunked
    full-catalog balanced-assignment pass builds the cell membership
    tables: list sizes are capped at ``balance`` x the mean so a hot
    cell cannot inflate every query's padded shortlist (the dense cell
    table gathers pad slots; see :func:`_assign_balanced`). Empty cells
    re-seed from random rows so every probe has members.

    Returns None for catalogs under :data:`MIN_INDEX_ITEMS`, where the
    flat matmul wins outright and an index is pure overhead.
    """
    x = _host_vectors(item_f)
    n = int(x.shape[0])
    if n < MIN_INDEX_ITEMS:
        return None
    nlist = nlist if nlist > 0 else auto_nlist(n)
    nlist = max(1, min(nlist, n))
    rng = np.random.default_rng(seed)
    train = x if n <= sample else x[rng.choice(n, size=sample,
                                               replace=False)]
    # a sampled k-means fit cannot seed more centroids than sample
    # rows: an oversized explicit nlist clamps (degrade-don't-die, like
    # every other config knob) instead of crashing the persist stage
    nlist = min(nlist, len(train))
    centroids = train[rng.choice(len(train), size=nlist,
                                 replace=False)].copy()
    for _ in range(max(1, iters)):
        assign = _assign(train, centroids)
        sums = np.zeros_like(centroids)
        np.add.at(sums, assign, train)
        counts = np.bincount(assign, minlength=nlist)
        nonempty = counts > 0
        centroids[nonempty] = (sums[nonempty]
                               / counts[nonempty, None].astype(np.float32))
        n_empty = int((~nonempty).sum())
        if n_empty:
            centroids[~nonempty] = train[rng.choice(
                len(train), size=n_empty, replace=False)]
    cap = max(1, int(math.ceil(max(balance, 1.0) * n / nlist)))
    assign = _assign_balanced(x, centroids, cap)
    counts = np.bincount(assign, minlength=nlist)
    # CSR cell grouping (class docstring): the stable argsort IS the
    # flat item order, and the vector copy in that order makes every
    # cell's rescore block contiguous
    flat_items = np.argsort(assign, kind="stable").astype(np.int32)
    cell_offset = np.concatenate(
        [[0], np.cumsum(counts)]).astype(np.int32)
    flat_vecs = np.ascontiguousarray(x[flat_items])
    return AnnIndex(nlist=nlist, n_items=n, centroids=centroids,
                    flat_items=flat_items, flat_vecs=flat_vecs,
                    cell_offset=cell_offset)


# ---------------------------------------------------------------------------
# probe + gather + exact rescore (jitted; the serving path)
# ---------------------------------------------------------------------------


def _shortlist(query_vecs, centroids, flat_items, flat_vecs, cell_offset,
               nprobe: int, rescore: int):
    """(candidate ids (B, S) int32, valid mask (B, S), candidate
    vectors (B, S, K)) for the top-nprobe cells per query: the probed
    cells' CSR runs concatenated in probe-score order into the static
    budget width (:func:`_budget_width`). Column j of the budget maps
    to (cell, offset) by binary search over the probed cells' running
    sizes; the vector gather then reads each cell's contiguous run
    from ``flat_vecs`` (module docstring — the 2.1x over padded
    blocks). Columns past the probed mass carry mask 0; probed mass
    past the budget drops from the tail (worst-scoring cells)."""
    n_items = int(flat_items.shape[0])
    nlist = int(cell_offset.shape[0]) - 1
    width = _budget_width(n_items, nlist, nprobe, rescore)
    cell_scores = jnp.einsum("bk,ck->bc", query_vecs, centroids)
    _, probes = jax.lax.top_k(cell_scores, nprobe)        # (B, P)

    def row(probes_r):
        sizes = cell_offset[probes_r + 1] - cell_offset[probes_r]
        cum = jnp.cumsum(sizes)                            # (P,)
        j = jnp.arange(width, dtype=jnp.int32)             # (S,)
        # j lands in probed cell p iff cum[p-1] <= j < cum[p]
        p = jnp.clip(jnp.searchsorted(cum, j, side="right"),
                     0, probes_r.shape[0] - 1)
        prev = jnp.where(p > 0, cum[p - 1], 0)
        valid = j < cum[-1]
        flat = jnp.where(valid, cell_offset[probes_r[p]] + (j - prev), 0)
        return flat, valid

    flat, valid = jax.vmap(row)(probes)                    # (B, S)
    b = query_vecs.shape[0]
    cand = flat_items[flat.reshape(-1)].reshape(b, width)
    vecs = flat_vecs[flat.reshape(-1)].reshape(b, width, -1)
    return cand, valid.astype(query_vecs.dtype), vecs


def _mask_seen(cand, scores, seen_cols, seen_mask):
    """-inf out candidates present in each row's seen list, by sorted
    membership test: sort each row's seen ids (pad slots pushed to
    int32-max, which no catalog index reaches), binary-search every
    candidate, and compare at the insertion point — O(S log seen) per
    row. The two obvious alternatives both lose at serving shapes: a
    ``lax.scan`` over seen columns is seen-pad sequential XLA dispatches
    (512 x ~35µs ≈ 18ms/query of pure scan overhead — 9x the whole
    probe+rescore kernel), and the one-shot (B, S, seen) comparison
    cube is S x seen-pad work per row (~13M compares at the 1M-point
    shortlist, measured ~4ms and linear in the pad)."""
    big = jnp.int32(np.iinfo(np.int32).max)
    seen = jnp.sort(jnp.where(seen_mask > 0, seen_cols, big), axis=1)

    def row(seen_r, cand_r):
        pos = jnp.clip(jnp.searchsorted(seen_r, cand_r), 0,
                       seen_r.shape[0] - 1)
        return seen_r[pos] == cand_r

    hit = jax.vmap(row)(seen, cand)
    return jnp.where(hit, NEG_INF, scores)


def _finish(cand, scores, k: int, n_items: int):
    """Top-k over the shortlist with the chunked-path result contract:
    k clamps to the shortlist width, -inf slots carry out-of-range
    sentinel indices so a caller ignoring score finiteness can never
    serve a pad/duplicate candidate as a real item."""
    k = min(k, scores.shape[1])
    vals, sel = jax.lax.top_k(scores, k)
    idxs = jnp.take_along_axis(cand, sel, axis=1)
    sentinels = n_items + jnp.arange(k, dtype=jnp.int32)[None, :]
    idxs = jnp.where(jnp.isfinite(vals), idxs, sentinels)
    return vals, idxs


def _ann_topk_impl(user_vecs, item_f, centroids, flat_items, flat_vecs,
                   cell_offset, seen_cols, seen_mask, allow, k: int,
                   nprobe: int, rescore: int):
    """Vectorized probe → CSR-run rescore → mask → top-k for one
    (B, ...) group — the body :func:`ann_topk` dispatches to."""
    cand, pad_mask, vecs = _shortlist(user_vecs, centroids, flat_items,
                                      flat_vecs, cell_offset, nprobe,
                                      rescore)
    scores = jnp.einsum("bk,bsk->bs", user_vecs, vecs)     # exact rescore
    scores = jnp.where(pad_mask > 0, scores, NEG_INF)
    if allow.ndim == 1:
        scores = jnp.where(allow[cand] > 0, scores, NEG_INF)
    else:
        scores = jnp.where(
            jnp.take_along_axis(allow, cand, axis=1) > 0, scores, NEG_INF)
    scores = _mask_seen(cand, scores, seen_cols, seen_mask)
    return _finish(cand, scores, k, item_f.shape[0])


@partial(instrumented_jit, static_argnames=("k", "nprobe", "rescore"))
def ann_topk(
    user_vecs: jax.Array,    # (B, K) query user factors
    item_f: jax.Array,       # (I, K) item factor table (the brute table)
    centroids: jax.Array,    # (C, K)
    flat_items: jax.Array,   # (I,) int32, cell-grouped item ids
    flat_vecs: jax.Array,    # (I, K) vectors in the same order
    cell_offset: jax.Array,  # (C + 1,) int32
    seen_cols: jax.Array,    # (B, S) int32, padded
    seen_mask: jax.Array,    # (B, S) 1=real 0=pad
    allow: jax.Array,        # (I,) or (B, I) 0/1 eligibility
    k: int,
    nprobe: int,
    rescore: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """ANN counterpart of :func:`ops.topk.recommend_topk`: probe the
    top-``nprobe`` cells, walk their CSR runs as the shortlist,
    exact-rescore with the true inner product, mask seen/ineligible
    candidates, top-k. One jitted dispatch; results in GLOBAL item
    coordinates. ``item_f`` only provides the sentinel base
    (``n_items``) — the rescore reads the cell-grouped runs.

    Batches run as a ``lax.map`` over rows rather than one vectorized
    gather: each row's probed runs then stream through cache one query
    at a time, where the batched (B, S, K) gather thrashes it —
    measured at the 1M point on the padded layout, 1.8ms/query mapped
    vs 4.4ms/query vectorized at B=24. Batching buys ANN no device
    win (there is no shared full-table traversal to amortize, unlike
    brute) — the map keeps batched callers at the B=1 rate, and the
    serving batcher still amortizes the per-dispatch HOST cost."""
    if user_vecs.shape[0] <= 1:
        return _ann_topk_impl(user_vecs, item_f, centroids, flat_items,
                              flat_vecs, cell_offset, seen_cols, seen_mask,
                              allow, k, nprobe, rescore)

    def one(args):
        if allow.ndim == 1:
            uv, sc, sm = args
            al = allow
        else:
            uv, sc, sm, al = args
        vals, idxs = _ann_topk_impl(
            uv[None], item_f, centroids, flat_items, flat_vecs,
            cell_offset, sc[None], sm[None], al, k, nprobe, rescore)
        return vals[0], idxs[0]

    xs = ((user_vecs, seen_cols, seen_mask) if allow.ndim == 1
          else (user_vecs, seen_cols, seen_mask, allow))
    return jax.lax.map(one, xs)


@partial(instrumented_jit, static_argnames=("k", "nprobe", "rescore"))
def ann_similar_topk(
    query_vecs: jax.Array,   # (B, K) query item factors (unnormalized)
    item_f: jax.Array,       # (I, K)
    centroids: jax.Array,    # (C, K)
    flat_items: jax.Array,   # (I,) int32, cell-grouped item ids
    flat_vecs: jax.Array,    # (I, K) vectors in the same order
    cell_offset: jax.Array,  # (C + 1,) int32
    exclude_cols: jax.Array,  # (B, E) the query items themselves
    exclude_mask: jax.Array,  # (B, E)
    allow: jax.Array,         # (I,) or (B, I)
    k: int,
    nprobe: int,
    rescore: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """ANN counterpart of :func:`ops.topk.similar_topk` (cosine): probe
    and rescore in the normalized space — cosine similarity is the
    inner product of unit vectors, so the SAME index (built on raw
    factors) answers it by normalizing the query, the centroids and the
    streamed candidate runs in-kernel. Ranking within the shortlist
    is exactly similar_topk's."""
    qn = query_vecs / jnp.maximum(
        jnp.linalg.norm(query_vecs, axis=-1, keepdims=True), 1e-9)
    cn = centroids / jnp.maximum(
        jnp.linalg.norm(centroids, axis=-1, keepdims=True), 1e-9)
    cand, pad_mask, vecs = _shortlist(qn, cn, flat_items, flat_vecs,
                                      cell_offset, nprobe, rescore)
    vn = vecs / jnp.maximum(
        jnp.linalg.norm(vecs, axis=-1, keepdims=True), 1e-9)
    scores = jnp.einsum("bk,bsk->bs", qn, vn)
    scores = jnp.where(pad_mask > 0, scores, NEG_INF)
    if allow.ndim == 1:
        scores = jnp.where(allow[cand] > 0, scores, NEG_INF)
    else:
        scores = jnp.where(
            jnp.take_along_axis(allow, cand, axis=1) > 0, scores, NEG_INF)
    scores = _mask_seen(cand, scores, exclude_cols, exclude_mask)
    return _finish(cand, scores, k, item_f.shape[0])


# ---------------------------------------------------------------------------
# quality measurement (shared by tests/test_ann.py and bench_serving.py:
# recall/MAP numbers in the artifact come from the same code the tests pin)
# ---------------------------------------------------------------------------


def quality_vs_brute(index: AnnIndex, user_vecs: np.ndarray,
                     item_f: Any, k: int = 10, nprobe: int = 0,
                     rescore: int = 0) -> dict:
    """Recall@shortlist and MAP@k of the ANN ranking against brute
    force as ground truth.

    - ``recall_at_shortlist``: fraction of each query's TRUE top-k
      (exact full-catalog MIPS) whose items landed in the probed
      shortlist at all — the only quality the index can lose, since
      rescoring is exact;
    - ``map_at_k``: mean average precision of the ANN top-k treating
      the brute top-k as the relevant set (brute MAP@k is 1.0 by
      construction, so "within 1% of brute" means map_at_k >= 0.99).
    """
    from predictionio_tpu.ops import topk as topk_ops

    nprobe = index.clamp_nprobe(nprobe)
    uv = jnp.asarray(np.asarray(user_vecs, dtype=np.float32))
    itf = jnp.asarray(item_f)
    b = int(uv.shape[0])
    no_seen_cols = jnp.zeros((b, 1), dtype=jnp.int32)
    no_seen_mask = jnp.zeros((b, 1), dtype=jnp.float32)
    allow = jnp.ones((itf.shape[0],), dtype=jnp.float32)
    bv, bi = topk_ops.recommend_topk(uv, itf, no_seen_cols, no_seen_mask,
                                     allow, min(k, int(itf.shape[0])))
    centroids, flat_items, flat_vecs, cell_offset = index.device_arrays()
    cand, pad_mask, _ = _shortlist(uv, centroids, flat_items, flat_vecs,
                                   cell_offset, nprobe, rescore)
    av, ai = ann_topk(uv, itf, centroids, flat_items, flat_vecs,
                      cell_offset, no_seen_cols, no_seen_mask, allow, k,
                      nprobe, rescore)
    bi_h, bv_h = np.asarray(bi), np.asarray(bv)
    ai_h, av_h = np.asarray(ai), np.asarray(av)
    cand_h = np.where(np.asarray(pad_mask) > 0, np.asarray(cand), -1)
    recalls, aps = [], []
    for row in range(b):
        truth = [int(i) for i, v in zip(bi_h[row], bv_h[row])
                 if np.isfinite(v)]
        if not truth:
            continue
        shortlist = set(int(c) for c in cand_h[row] if c >= 0)
        recalls.append(sum(1 for i in truth if i in shortlist) / len(truth))
        relevant = set(truth)
        hits, precision_sum = 0, 0.0
        ranked = [int(i) for i, v in zip(ai_h[row], av_h[row])
                  if np.isfinite(v)][:k]
        for rank, item in enumerate(ranked, start=1):
            if item in relevant:
                hits += 1
                precision_sum += hits / rank
        aps.append(precision_sum / min(k, len(relevant)))
    return {
        "recall_at_shortlist": float(np.mean(recalls)) if recalls else 1.0,
        "map_at_k": float(np.mean(aps)) if aps else 1.0,
        "k": k,
        "nprobe": nprobe,
        "shortlist_width": index.shortlist_width(nprobe, rescore),
        "queries": len(recalls),
    }
