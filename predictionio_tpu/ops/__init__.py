"""Numeric kernels: the in-tree replacement for the reference's external
MLlib dependency (SURVEY.md §2 "Native components: NONE" note — the TPU
build implements the compute kernels as in-tree JAX/XLA code)."""
