"""Pallas TPU kernel: flash attention — auto-dispatched for causal
serving shapes since the round-5 optimization pass.

Tile-streamed causal attention with the standard flash online softmax:
for each query tile, K/V tiles stream through the MXU and a running
(max, denominator, numerator) carry folds each tile — the S x S logits
matrix never exists in HBM.

**Measurement history, all with the forcing protocol** (min-endpoint
differential chains, feed-back inputs, B=1 H=4 D=64 f32 causal — the
serving shape). Round 2 claimed the kernel won from S=2048 on XLA
timings that were flat in S (impossible for O(S^2) attention) — caught
and retracted in round 3, whose re-measurement had XLA ahead at every
depth (S=4096: XLA 1.10ms vs pallas 1.88ms) and auto-dispatch turned
OFF. Round 5's optimization pass changed the verdict with two fixes:
(1) **causal KV-tile skip** — the inner loop's bound now stops at the
diagonal instead of visiting fully-masked tiles (the bound is traced
from ``program_id``; halves visited tiles on average), and (2) a
**block-size sweep** found 512x512 tiles ~2x faster than the original
128x128 from S=4096 (bigger per-tile MXU work, fewer carry updates).
Same-process A/B after the pass (fresh process, 64-128-call chains):

=======  ==========  ====================  =====
S        XLA (ms)    pallas (ms) [tiles]   win
=======  ==========  ====================  =====
2048     0.392       0.282  [128x128]      1.4x
4096     1.113       0.487  [512x512]      2.3x
8192     4.704       0.850  [512x512]      5.5x
16384    18.802      3.238  [512x512]      5.8x
=======  ==========  ====================  =====

The win grows with S: the kernel's HBM traffic is O(S * D) per query
tile against the materialized formulation's O(S^2) logits, plus the
causal skip XLA's fused softmax cannot apply. Numerics vs XLA:
max|diff| ~2-3e-4 (online vs materialized softmax). The bench tracks
``flash_s4096_ms``/``xla_s4096_ms`` so a regression re-flips the
dispatch decision on data.

**Auto-dispatch:** CAUSAL attention on a compiled TPU backend at
2048 <= S <= 16384 (the measured envelope; the skip only helps causal,
and non-causal remains unmeasured -> force-only). ``force=True`` still
runs the kernel anywhere it builds (incl. interpret mode for CPU
tests). Sequences beyond a chip shard over the mesh "seq" axis instead
(ops/attention.ring_attention).

Forward-only: no VJP — training paths (models/seqrec.next_item_loss,
ring attention local blocks) use ops/attention.full_attention /
blockwise_attention, which are differentiable.
"""

from __future__ import annotations

import functools
import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from predictionio_tpu.ops.attention import full_attention

_TILE_Q = 128
_TILE_K = 128
#: the r5 block-size sweep: 512x512 tiles win from S=4096 (module table)
_TILE_BIG = 512
_TILE_BIG_FROM = 4096
_NEG = -1e30  # python float: jnp scalars would be captured consts in the kernel
#: auto-dispatch envelope (round 5, causal only — module docstring
#: table): the causal-KV-skip + 512-tile kernel beats XLA 1.4-5.8x
#: across 2048 <= S <= 16384. _MAX_SEQ also bounds force-mode builds
#: (K/V residency exceeds VMEM around S=32768).
_MIN_SEQ = 2048
_MAX_SEQ = 16384


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, causal: bool,
                  seq_len: int, tile_k: int):
    """Grid: (batch*heads, seq_len // TILE_Q). Blocks:
    q (TILE_Q, D), k/v (seq_len, D) resident per bh, mask (1, seq_len),
    o (TILE_Q, D)."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                    # (TQ, D)
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d)
    tq = q.shape[0]
    q_pos = qi * tq + jax.lax.iota(jnp.int32, tq)       # global query rows

    n_kv = seq_len // tile_k
    if causal:
        # causal KV-tile skip (r5 optimization pass): tiles entirely
        # above the diagonal are fully masked — don't visit them. The
        # loop bound is traced (depends on program_id); lowers to a
        # while_loop. Halves the visited tiles on average.
        n_kv = jnp.minimum(n_kv, ((qi + 1) * tq + tile_k - 1) // tile_k)

    def body(t, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(t * tile_k, tile_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(t * tile_k, tile_k), :].astype(jnp.float32)
        msk = mask_ref[0, 0, pl.ds(t * tile_k, tile_k)]  # (TK,)
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                        # (TQ, TK)
        k_pos = t * tile_k + jax.lax.iota(jnp.int32, tile_k)
        valid = msk[None, :] > 0
        if causal:
            valid = valid & (q_pos[:, None] >= k_pos[None, :])
        logits = jnp.where(valid, logits, _NEG)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        seen = m_new > _NEG / 2
        alpha = jnp.where(seen, jnp.exp(m - m_new), 0.0)
        p = jnp.exp(logits - m_new[:, None])
        p = jnp.where(valid & seen[:, None], p, 0.0)
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    m0 = jnp.full((tq,), _NEG, dtype=jnp.float32)
    l0 = jnp.zeros((tq,), dtype=jnp.float32)
    a0 = jnp.zeros((tq, d), dtype=jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_kv, body, (m0, l0, a0))
    out = acc / jnp.maximum(l, 1e-20)[:, None]
    out = jnp.where((l > 0)[:, None], out, 0.0)
    o_ref[0] = out.astype(o_ref.dtype)


@partial(jax.jit,
         static_argnames=("causal", "interpret", "tile_q_", "tile_k_"))
def _flash_call(q, k, v, kv_mask, causal: bool, interpret: bool,
                tile_q_: int | None = None, tile_k_: int | None = None):
    B, H, S, D = q.shape
    bh = B * H
    qf = q.reshape(bh, S, D)
    kf = k.reshape(bh, S, D)
    vf = v.reshape(bh, S, D)
    # (bh, 1, S): the singleton keeps the block's trailing dims equal to
    # the array's (TPU lowering requires trailing block dims divisible by
    # (8, 128) or exactly equal)
    maskf = jnp.repeat(kv_mask.astype(jnp.float32), H, axis=0)[:, None, :]
    big = S >= _TILE_BIG_FROM and S % _TILE_BIG == 0
    tile_q = min(tile_q_ or (_TILE_BIG if big else _TILE_Q), S)
    tile_k = min(tile_k_ or (_TILE_BIG if big else _TILE_K), S)
    if S % tile_q or S % tile_k:
        # an explicit override must never silently truncate the grid
        # (grid = S // tile_q drops trailing query tiles otherwise)
        raise ValueError(
            f"S={S} not divisible by tiles ({tile_q}, {tile_k})")
    grid = (bh, S // tile_q)
    kernel = functools.partial(
        _flash_kernel, causal=causal, seq_len=S, tile_k=tile_k)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, S), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tile_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, S, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, maskf)
    return out.reshape(B, H, S, D)


@functools.cache
def _mode() -> str:
    """'compiled' on a TPU backend, 'interpret' elsewhere, 'off' when
    pallas is unusable."""
    try:
        on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return "off"
    return "compiled" if on_tpu else "interpret"


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    kv_mask: jax.Array | None = None,
    force: bool = False,
) -> jax.Array:
    """Streaming-tile attention. Auto-dispatches for CAUSAL attention
    on a compiled TPU backend within the measured 2048 <= S <= 16384
    envelope (module docstring: the round-5 causal-KV-skip + tile
    sweep beats XLA 1.4-5.8x there); everything else falls back to
    ops/attention.full_attention.

    ``force=True`` runs the pallas kernel anywhere it can build (incl.
    interpret mode for CPU tests, and the memory-bounded long-context
    fallback where XLA's materialized logits OOM). Forward-only — do
    not call under jax.grad (training uses full_attention /
    ring_attention).
    """
    B, H, S, D = q.shape
    if kv_mask is None:
        kv_mask = jnp.ones((B, S), dtype=jnp.float32)
    mode = _mode()
    auto = (
        mode == "compiled"  # interpret mode is force-only (too slow)
        and causal          # the KV-skip win is causal-only (measured)
        and _MIN_SEQ is not None
        and _MIN_SEQ <= S <= _MAX_SEQ
    )
    eligible = (
        mode != "off"
        and (force or auto)
        and S % min(_TILE_Q, S) == 0
    )
    if not eligible:
        return full_attention(q, k, v, causal=causal, kv_mask=kv_mask)
    try:
        return _flash_call(q, k, v, kv_mask, causal, mode == "interpret")
    except Exception:
        if force:
            raise  # the caller asked for the kernel; surface the failure
        import logging

        logging.getLogger(__name__).warning(
            "pallas flash_attention failed to build; using XLA path",
            exc_info=True,
        )
        return full_attention(q, k, v, causal=causal, kv_mask=kv_mask)
