"""Pallas TPU kernel: flash attention — kept force-only, on measurement.

Tile-streamed causal attention with the standard flash online softmax:
for each query tile, K/V tiles stream through the MXU and a running
(max, denominator, numerator) carry folds each tile — the S x S logits
matrix never exists in HBM.

**Auto-dispatch is OFF (round 3, re-measured).** The round-2 envelope
claimed the kernel wins from S=2048 ("XLA 53-68ms" across S=2048-8192)
— but those XLA timings were nearly flat in S, which no O(S^2)
attention can be, and the round-3 re-measurement with robust
min-endpoint differential chains (64-call chains, feed-back inputs,
B=1 H=4 D=64 f32 — the serving shape) shows XLA ahead at EVERY depth,
with no OOM at B=1:

=======  ==========  ============
S        XLA (ms)    pallas (ms)
=======  ==========  ============
2048     0.40        0.44
4096     1.10        1.88
8192     4.71        7.35
16384    18.8        29.3
=======  ==========  ============

(the bench line tracks the S=4096 pair as ``flash_s4096_ms`` /
``xla_s4096_ms``, which is how the round-2 claim was caught.) XLA's
timings scale ~4x per S-doubling and sit near the HBM-traffic floor of
the materialized formulation; the pallas kernel is correct but
~1.5-2.3x slower at these shapes, so — like the deleted pallas top-k
(ops/topk docstring) — it does not auto-dispatch. It remains available
via ``force=True`` (and powers the CPU interpret-mode tests) as the
memory-bounded fallback: the XLA path materializes (B, H, S, S) logits
(~4.3 GB at B=1 f32 S=16384) and will OOM for batched long-context
serving where the kernel's O(S * tile) footprint still fits; callers
with that shape opt in explicitly. Sequences beyond a chip shard over
the mesh "seq" axis instead (ops/attention.ring_attention).

Forward-only: no VJP — training paths (models/seqrec.next_item_loss,
ring attention local blocks) use ops/attention.full_attention, whose
per-device blocks stay small under sequence parallelism.
"""

from __future__ import annotations

import functools
import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from predictionio_tpu.ops.attention import full_attention

_TILE_Q = 128
_TILE_K = 128
_NEG = -1e30  # python float: jnp scalars would be captured consts in the kernel
#: auto-dispatch envelope: DISABLED (round-3 measurement table above —
#: XLA wins at every serving shape); ``force=True`` is the only way in.
#: _MAX_SEQ still bounds force-mode builds (K/V residency exceeds VMEM
#: around S=32768).
_MIN_SEQ = None
_MAX_SEQ = 16384


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, causal: bool,
                  seq_len: int, tile_k: int):
    """Grid: (batch*heads, seq_len // TILE_Q). Blocks:
    q (TILE_Q, D), k/v (seq_len, D) resident per bh, mask (1, seq_len),
    o (TILE_Q, D)."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                    # (TQ, D)
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d)
    tq = q.shape[0]
    q_pos = qi * tq + jax.lax.iota(jnp.int32, tq)       # global query rows

    n_kv = seq_len // tile_k

    def body(t, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(t * tile_k, tile_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(t * tile_k, tile_k), :].astype(jnp.float32)
        msk = mask_ref[0, 0, pl.ds(t * tile_k, tile_k)]  # (TK,)
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                        # (TQ, TK)
        k_pos = t * tile_k + jax.lax.iota(jnp.int32, tile_k)
        valid = msk[None, :] > 0
        if causal:
            valid = valid & (q_pos[:, None] >= k_pos[None, :])
        logits = jnp.where(valid, logits, _NEG)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        seen = m_new > _NEG / 2
        alpha = jnp.where(seen, jnp.exp(m - m_new), 0.0)
        p = jnp.exp(logits - m_new[:, None])
        p = jnp.where(valid & seen[:, None], p, 0.0)
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    m0 = jnp.full((tq,), _NEG, dtype=jnp.float32)
    l0 = jnp.zeros((tq,), dtype=jnp.float32)
    a0 = jnp.zeros((tq, d), dtype=jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_kv, body, (m0, l0, a0))
    out = acc / jnp.maximum(l, 1e-20)[:, None]
    out = jnp.where((l > 0)[:, None], out, 0.0)
    o_ref[0] = out.astype(o_ref.dtype)


@partial(jax.jit, static_argnames=("causal", "interpret"))
def _flash_call(q, k, v, kv_mask, causal: bool, interpret: bool):
    B, H, S, D = q.shape
    bh = B * H
    qf = q.reshape(bh, S, D)
    kf = k.reshape(bh, S, D)
    vf = v.reshape(bh, S, D)
    # (bh, 1, S): the singleton keeps the block's trailing dims equal to
    # the array's (TPU lowering requires trailing block dims divisible by
    # (8, 128) or exactly equal)
    maskf = jnp.repeat(kv_mask.astype(jnp.float32), H, axis=0)[:, None, :]
    tile_q = min(_TILE_Q, S)
    tile_k = min(_TILE_K, S)
    grid = (bh, S // tile_q)
    kernel = functools.partial(
        _flash_kernel, causal=causal, seq_len=S, tile_k=tile_k)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, S), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tile_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, S, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, maskf)
    return out.reshape(B, H, S, D)


@functools.cache
def _mode() -> str:
    """'compiled' on a TPU backend, 'interpret' elsewhere, 'off' when
    pallas is unusable."""
    try:
        on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return "off"
    return "compiled" if on_tpu else "interpret"


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    kv_mask: jax.Array | None = None,
    force: bool = False,
) -> jax.Array:
    """Streaming-tile attention, force-only (module docstring: the
    round-3 re-measurement found XLA ahead at every serving shape, so
    the auto envelope is disabled — ``_MIN_SEQ is None``).

    ``force=True`` runs the pallas kernel anywhere it can build (incl.
    interpret mode for CPU tests, and the memory-bounded long-context
    fallback where XLA's materialized logits OOM); otherwise this is
    exactly ops/attention.full_attention. Forward-only — do not call
    under jax.grad (training uses full_attention / ring_attention).
    """
    B, H, S, D = q.shape
    if kv_mask is None:
        kv_mask = jnp.ones((B, S), dtype=jnp.float32)
    mode = _mode()
    auto = (
        mode == "compiled"  # interpret mode is force-only (too slow)
        and _MIN_SEQ is not None
        and _MIN_SEQ <= S <= _MAX_SEQ
    )
    eligible = (
        mode != "off"
        and (force or auto)
        and S % min(_TILE_Q, S) == 0
    )
    if not eligible:
        return full_attention(q, k, v, causal=causal, kv_mask=kv_mask)
    try:
        return _flash_call(q, k, v, kv_mask, causal, mode == "interpret")
    except Exception:
        if force:
            raise  # the caller asked for the kernel; surface the failure
        import logging

        logging.getLogger(__name__).warning(
            "pallas flash_attention failed to build; using XLA path",
            exc_info=True,
        )
        return full_attention(q, k, v, causal=causal, kv_mask=kv_mask)
