"""Pallas TPU kernel: flash attention — the long-sequence serving path.

Tile-streamed causal attention with the standard flash online softmax:
for each query tile, K/V tiles stream through the MXU and a running
(max, denominator, numerator) carry folds each tile — the S x S logits
matrix never exists in HBM.

**Auto-dispatched for S >= 2048 on TPU, on measurement.** Round 1
concluded the opposite ("XLA 2.3ms at S=16384 vs pallas 34.8ms") from
timings taken with bare ``block_until_ready``, which on this
remote-attached backend can return before work executes (see bench.py's
measurement-protocol note). Re-measured with the forcing protocol
(bf16, B=2, H=4, D=64, chained calls, full-result fetch):

=======  ==========  ============
S        XLA (ms)    pallas (ms)
=======  ==========  ============
1024     ~noise      ~noise
2048     53          < 2
4096     56          1.5
8192     68          5.7
16384    OOM         50
=======  ==========  ============

XLA materializes the (S, S) logits — at S=16384 that is ~8.6 GB and
fails outright — so above the crossover this kernel is not only faster
but the only single-device path. At S=32768 the kernel's per-(batch,
head) K/V residency exceeds VMEM and it fails too; shard longer
sequences over the mesh "seq" axis instead (ops/attention.
ring_attention).

Forward-only: no VJP — training paths (models/seqrec.next_item_loss,
ring attention local blocks) use ops/attention.full_attention, whose
per-device blocks stay small under sequence parallelism. Serving paths
(models/seqrec.predict_topk*) route through :func:`flash_attention`.
Interpret mode covers CPU tests (force-only — interpret is too slow for
the auto envelope).
"""

from __future__ import annotations

import functools
import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from predictionio_tpu.ops.attention import full_attention

_TILE_Q = 128
_TILE_K = 128
_NEG = -1e30  # python float: jnp scalars would be captured consts in the kernel
#: auto-dispatch envelope (see module docstring's measurement table):
#: the kernel wins from S=2048 on a real TPU; the K/V-resident design
#: exceeds VMEM around S=32768 (shard longer sequences instead)
_MIN_SEQ = 2048
_MAX_SEQ = 16384


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, causal: bool,
                  seq_len: int, tile_k: int):
    """Grid: (batch*heads, seq_len // TILE_Q). Blocks:
    q (TILE_Q, D), k/v (seq_len, D) resident per bh, mask (1, seq_len),
    o (TILE_Q, D)."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                    # (TQ, D)
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d)
    tq = q.shape[0]
    q_pos = qi * tq + jax.lax.iota(jnp.int32, tq)       # global query rows

    n_kv = seq_len // tile_k

    def body(t, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(t * tile_k, tile_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(t * tile_k, tile_k), :].astype(jnp.float32)
        msk = mask_ref[0, 0, pl.ds(t * tile_k, tile_k)]  # (TK,)
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                        # (TQ, TK)
        k_pos = t * tile_k + jax.lax.iota(jnp.int32, tile_k)
        valid = msk[None, :] > 0
        if causal:
            valid = valid & (q_pos[:, None] >= k_pos[None, :])
        logits = jnp.where(valid, logits, _NEG)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        seen = m_new > _NEG / 2
        alpha = jnp.where(seen, jnp.exp(m - m_new), 0.0)
        p = jnp.exp(logits - m_new[:, None])
        p = jnp.where(valid & seen[:, None], p, 0.0)
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    m0 = jnp.full((tq,), _NEG, dtype=jnp.float32)
    l0 = jnp.zeros((tq,), dtype=jnp.float32)
    a0 = jnp.zeros((tq, d), dtype=jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_kv, body, (m0, l0, a0))
    out = acc / jnp.maximum(l, 1e-20)[:, None]
    out = jnp.where((l > 0)[:, None], out, 0.0)
    o_ref[0] = out.astype(o_ref.dtype)


@partial(jax.jit, static_argnames=("causal", "interpret"))
def _flash_call(q, k, v, kv_mask, causal: bool, interpret: bool):
    B, H, S, D = q.shape
    bh = B * H
    qf = q.reshape(bh, S, D)
    kf = k.reshape(bh, S, D)
    vf = v.reshape(bh, S, D)
    # (bh, 1, S): the singleton keeps the block's trailing dims equal to
    # the array's (TPU lowering requires trailing block dims divisible by
    # (8, 128) or exactly equal)
    maskf = jnp.repeat(kv_mask.astype(jnp.float32), H, axis=0)[:, None, :]
    tile_q = min(_TILE_Q, S)
    tile_k = min(_TILE_K, S)
    grid = (bh, S // tile_q)
    kernel = functools.partial(
        _flash_kernel, causal=causal, seq_len=S, tile_k=tile_k)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, S), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tile_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, S, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, maskf)
    return out.reshape(B, H, S, D)


@functools.cache
def _mode() -> str:
    """'compiled' on a TPU backend, 'interpret' elsewhere, 'off' when
    pallas is unusable."""
    try:
        on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return "off"
    return "compiled" if on_tpu else "interpret"


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    kv_mask: jax.Array | None = None,
    force: bool = False,
) -> jax.Array:
    """Streaming-tile attention for the serving path.

    Auto-dispatches to the pallas kernel on a real TPU for
    ``_MIN_SEQ <= S <= _MAX_SEQ`` (measured envelope — module
    docstring); ``force=True`` runs it anywhere it can build (incl.
    interpret mode for CPU tests); otherwise this is exactly
    ops/attention.full_attention. Forward-only — do not call under
    jax.grad (training uses full_attention / ring_attention).
    """
    B, H, S, D = q.shape
    if kv_mask is None:
        kv_mask = jnp.ones((B, S), dtype=jnp.float32)
    mode = _mode()
    auto = (
        mode == "compiled"  # interpret mode is force-only (too slow)
        and _MIN_SEQ is not None
        and _MIN_SEQ <= S <= _MAX_SEQ
    )
    eligible = (
        mode != "off"
        and (force or auto)
        and S % min(_TILE_Q, S) == 0
    )
    if not eligible:
        return full_attention(q, k, v, causal=causal, kv_mask=kv_mask)
    try:
        return _flash_call(q, k, v, kv_mask, causal, mode == "interpret")
    except Exception:
        if force:
            raise  # the caller asked for the kernel; surface the failure
        import logging

        logging.getLogger(__name__).warning(
            "pallas flash_attention failed to build; using XLA path",
            exc_info=True,
        )
        return full_attention(q, k, v, causal=causal, kv_mask=kv_mask)
