"""Pallas TPU kernel: flash attention (reference implementation).

Tile-streamed causal attention with the standard flash online softmax:
for each query tile, K/V tiles stream through the MXU and a running
(max, denominator, numerator) carry folds each tile — the S x S logits
matrix never exists in HBM.

**Disabled by default, on measurement.** XLA:TPU already emits a fused
flash-style attention for ops/attention.full_attention — measured on
one v5e-class chip (bf16, B=2-4, H=4, D=64): XLA 2.3 ms at S=16384 (≈
roofline) vs 34.8 ms for this kernel (in-kernel fori over K/V tiles
pipelines poorly, and small head dims underfill the MXU). Per the
framework's design rule — don't hand-schedule what the compiler already
does — auto-dispatch is OFF and every production path
(models/seqrec, ops/attention.ring_attention local blocks) uses the XLA
formulation. The kernel stays as a correct, tested baseline for
backends without the XLA attention fusion and as the starting point for
future tile-level tuning; opt in with ``force=True``.

Forward-only: no VJP (training always takes the XLA path). Interpret
mode covers CPU tests.
"""

from __future__ import annotations

import functools
import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from predictionio_tpu.ops.attention import full_attention

_TILE_Q = 128
_TILE_K = 128
_NEG = -1e30  # python float: jnp scalars would be captured consts in the kernel
#: auto-dispatch is disabled (see module docstring): XLA's fused
#: attention beat this kernel at every measured shape, so it only runs
#: when explicitly forced
_MIN_SEQ = None


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, causal: bool,
                  seq_len: int, tile_k: int):
    """Grid: (batch*heads, seq_len // TILE_Q). Blocks:
    q (TILE_Q, D), k/v (seq_len, D) resident per bh, mask (1, seq_len),
    o (TILE_Q, D)."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                    # (TQ, D)
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d)
    tq = q.shape[0]
    q_pos = qi * tq + jax.lax.iota(jnp.int32, tq)       # global query rows

    n_kv = seq_len // tile_k

    def body(t, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(t * tile_k, tile_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(t * tile_k, tile_k), :].astype(jnp.float32)
        msk = mask_ref[0, 0, pl.ds(t * tile_k, tile_k)]  # (TK,)
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                        # (TQ, TK)
        k_pos = t * tile_k + jax.lax.iota(jnp.int32, tile_k)
        valid = msk[None, :] > 0
        if causal:
            valid = valid & (q_pos[:, None] >= k_pos[None, :])
        logits = jnp.where(valid, logits, _NEG)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        seen = m_new > _NEG / 2
        alpha = jnp.where(seen, jnp.exp(m - m_new), 0.0)
        p = jnp.exp(logits - m_new[:, None])
        p = jnp.where(valid & seen[:, None], p, 0.0)
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    m0 = jnp.full((tq,), _NEG, dtype=jnp.float32)
    l0 = jnp.zeros((tq,), dtype=jnp.float32)
    a0 = jnp.zeros((tq, d), dtype=jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_kv, body, (m0, l0, a0))
    out = acc / jnp.maximum(l, 1e-20)[:, None]
    out = jnp.where((l > 0)[:, None], out, 0.0)
    o_ref[0] = out.astype(o_ref.dtype)


@partial(jax.jit, static_argnames=("causal", "interpret"))
def _flash_call(q, k, v, kv_mask, causal: bool, interpret: bool):
    B, H, S, D = q.shape
    bh = B * H
    qf = q.reshape(bh, S, D)
    kf = k.reshape(bh, S, D)
    vf = v.reshape(bh, S, D)
    # (bh, 1, S): the singleton keeps the block's trailing dims equal to
    # the array's (TPU lowering requires trailing block dims divisible by
    # (8, 128) or exactly equal)
    maskf = jnp.repeat(kv_mask.astype(jnp.float32), H, axis=0)[:, None, :]
    tile_q = min(_TILE_Q, S)
    tile_k = min(_TILE_K, S)
    grid = (bh, S // tile_q)
    kernel = functools.partial(
        _flash_kernel, causal=causal, seq_len=S, tile_k=tile_k)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, S), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tile_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, S, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, maskf)
    return out.reshape(B, H, S, D)


@functools.cache
def _mode() -> str:
    """'compiled' on a TPU backend, 'interpret' elsewhere, 'off' when
    pallas is unusable."""
    try:
        on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return "off"
    return "compiled" if on_tpu else "interpret"


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    kv_mask: jax.Array | None = None,
    force: bool = False,
) -> jax.Array:
    """Streaming-tile attention for the serving path.

    The pallas kernel runs only with ``force=True`` (see module
    docstring — XLA's fused attention wins at every measured shape);
    otherwise this is exactly ops/attention.full_attention. Forward-only
    — do not call under jax.grad.
    """
    B, H, S, D = q.shape
    if kv_mask is None:
        kv_mask = jnp.ones((B, S), dtype=jnp.float32)
    mode = _mode()
    eligible = (
        mode != "off"
        and force  # auto-dispatch disabled: XLA wins at measured shapes
        and S % min(_TILE_Q, S) == 0
    )
    if not eligible:
        return full_attention(q, k, v, causal=causal, kv_mask=kv_mask)
    try:
        return _flash_call(q, k, v, kv_mask, causal, mode == "interpret")
    except Exception:
        if force:
            raise  # the caller asked for the kernel; surface the failure
        import logging

        logging.getLogger(__name__).warning(
            "pallas flash_attention failed to build; using XLA path",
            exc_info=True,
        )
        return full_attention(q, k, v, causal=causal, kv_mask=kv_mask)
