"""Pallas TPU kernel: fused masked scoring + streaming top-k.

The serving hot path (reference: ALSAlgorithm predict/recommendProducts,
tests/pio_tests/engines/recommendation-engine/src/main/scala/
ALSAlgorithm.scala:90-120) is ``top_k(mask(U @ I^T))``. The XLA
formulation in ops/topk.py materializes the full (B, I) score matrix;
for catalog-scale I (10^5-10^7 items) that round-trips B*I*4 bytes of
HBM per request batch. This kernel streams item tiles HBM→VMEM once,
computes the tile's scores on the MXU, applies the eligibility and
seen-item masks in-register, and folds the tile into a running
per-query top-k carried in the output block across grid steps — the
score matrix never exists in HBM.

Selection is k rounds of (max, argmax, replace-min) per tile on the VPU
(k is small and static: 10-20 in every template), then one final
``jax.lax.top_k`` over (B, k) outside the kernel to order the carry.

Falls back transparently to the XLA path (ops/topk.recommend_topk)
off-TPU or if the kernel fails to build; interpret mode covers CPU
tests.
"""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_TILE_I = 512
#: chunked-vs-flat XLA dispatch thresholds (recommend_topk_fused auto
#: path): the chunked-scan merge wins from ~1M items with batched
#: queries; below, the flat materialize+top_k is faster.
_MIN_ITEMS = 786_432
_MIN_BATCH = 24
#: validity bounds for FORCED pallas-kernel use (use_pallas=True):
_MAX_BATCH = 512   # (B, S) seen arrays + (B, tile) scores must fit VMEM
_MAX_K = 32        # selection loop unrolls k times per tile
#: static menu of seen-pad widths; callers pad to 512, real per-batch
#: seen counts are usually tiny — trimming to the smallest fitting width
#: shrinks the unrolled mask loop by up to 64x at identical results
_SEEN_WIDTHS = (8, 32, 128, 512)


def _topk_kernel(user_ref, item_ref, allow_ref, seen_cols_ref, seen_mask_ref,
                 vals_ref, idx_ref, *, k: int, num_items: int, tile_i: int):
    step = pl.program_id(0)

    neg_inf = jnp.float32(-float("inf"))

    @pl.when(step == 0)
    def _():
        vals_ref[:] = jnp.full_like(vals_ref, neg_inf)
        idx_ref[:] = jnp.zeros_like(idx_ref)

    # (B, TILE_I) tile scores on the MXU
    scores = jax.lax.dot_general(
        user_ref[:], item_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    b, _ = scores.shape
    # global item ids of this tile + validity of the (padded) tail tile
    gid = step * tile_i + jax.lax.broadcasted_iota(jnp.int32, (b, tile_i), 1)
    scores = jnp.where(gid < num_items, scores, neg_inf)
    scores = jnp.where(allow_ref[:] > 0, scores, neg_inf)

    # hide seen items: statically-unrolled loop of (B, TILE_I) compares.
    # Mosaic can't index an arbitrary lane (last dim must be 128-aligned),
    # so each iteration reads the aligned lane-0 column and rolls the
    # seen arrays left by one.
    n_seen = seen_cols_ref.shape[1]
    seen = seen_cols_ref[:]
    smask = seen_mask_ref[:]
    for _ in range(n_seen):
        hit = (seen[:, 0:1] == gid) & (smask[:, 0:1] > 0)
        scores = jnp.where(hit, neg_inf, scores)
        # left-roll by one (pltpu.roll requires a non-negative shift)
        seen = pltpu.roll(seen, n_seen - 1, axis=1)
        smask = pltpu.roll(smask, n_seen - 1, axis=1)

    # fold the tile into the running top-k: k rounds of extract-max /
    # replace-carry-min
    carry_vals = vals_ref[:]
    carry_idx = idx_ref[:]
    k_iota = jax.lax.broadcasted_iota(jnp.int32, (b, k), 1)
    for _ in range(k):
        t_max = jnp.max(scores, axis=1)                      # (B,)
        t_arg = jnp.argmax(scores, axis=1).astype(jnp.int32)  # (B,)
        c_min = jnp.min(carry_vals, axis=1)
        c_arg = jnp.argmin(carry_vals, axis=1).astype(jnp.int32)
        better = t_max > c_min                                # (B,)
        slot = (k_iota == c_arg[:, None]) & better[:, None]   # (B, k) one-hot
        carry_vals = jnp.where(slot, t_max[:, None], carry_vals)
        carry_idx = jnp.where(slot, (step * tile_i + t_arg)[:, None], carry_idx)
        # retire the extracted column from this tile
        taken = (gid == (step * tile_i + t_arg)[:, None])
        scores = jnp.where(taken, neg_inf, scores)
    vals_ref[:] = carry_vals
    idx_ref[:] = carry_idx


@partial(jax.jit, static_argnames=("k", "tile_i", "interpret"))
def _pallas_masked_topk(user_vecs, item_f, seen_cols, seen_mask, allow_row,
                        k: int, tile_i: int, interpret: bool):
    b, _ = user_vecs.shape
    num_items = item_f.shape[0]
    grid = (pl.cdiv(num_items, tile_i),)
    kernel = functools.partial(
        _topk_kernel, k=k, num_items=num_items, tile_i=tile_i)
    vals, idx = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((b, user_vecs.shape[1]), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_i, item_f.shape[1]), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_i), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((b, seen_cols.shape[1]), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((b, seen_mask.shape[1]), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((b, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((b, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b, k), jnp.float32),
            jax.ShapeDtypeStruct((b, k), jnp.int32),
        ),
        interpret=interpret,
    )(user_vecs.astype(jnp.float32), item_f.astype(jnp.float32),
      allow_row, seen_cols.astype(jnp.int32), seen_mask)
    # order the unsorted carry
    svals, pos = jax.lax.top_k(vals, k)
    sidx = jnp.take_along_axis(idx, pos, axis=1)
    return svals, sidx


@functools.cache
def _kernel_mode() -> str | None:
    """'compiled' on a TPU backend, 'interpret' elsewhere (tests), or
    None if the kernel can't run at all in this environment."""
    try:
        on_tpu = jax.default_backend() not in ("cpu",)
        probe = _pallas_masked_topk(
            jnp.ones((8, 8), jnp.float32),
            jnp.ones((256, 8), jnp.float32),
            jnp.zeros((8, 8), jnp.int32),
            jnp.zeros((8, 8), jnp.float32),
            jnp.ones((1, 256), jnp.float32),
            4, 128, not on_tpu,
        )
        jax.block_until_ready(probe)
        return "compiled" if on_tpu else "interpret"
    except Exception:  # pragma: no cover - environment-dependent
        return None


def recommend_topk_fused(
    user_vecs: jax.Array,    # (B, K)
    item_f: jax.Array,       # (I, K)
    seen_cols: jax.Array,    # (B, S) int32, padded
    seen_mask: jax.Array,    # (B, S) 1=real, 0=pad
    allow: jax.Array,        # (I,) eligibility (0/1)
    k: int,
    tile_i: int = _TILE_I,
    use_pallas: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Top-k recommendation, same contract as ops/topk.recommend_topk
    restricted to 1-D ``allow``; dispatches between the streaming pallas
    kernel and the XLA path.

    ``use_pallas=None`` resolves to False: re-measured with chained,
    fully-blocked timing (this chip, f32, K=32, k=10), the pallas kernel
    loses at every point (129 ms vs XLA's 21 ms at I=1M/B=32) — its
    per-tile VPU selection loop can't match XLA's fused paths, so it
    stays available only under ``use_pallas=True`` (exact, bit-identical
    indices; for backends without the XLA fusion). The auto path instead
    picks between two XLA formulations: the flat materialize+top_k
    (ops/topk.recommend_topk, best for small catalogs and B=1 serving)
    and the chunked-scan merge (ops/topk.recommend_topk_chunked,
    O(B x chunk) memory; measured 1.2-1.75x faster from ~1M items with
    batched queries). The envelope constants (_MAX_BATCH/_MAX_K) are the
    validity bounds enforced on forced pallas use. Any failure to
    build/run the kernel falls back to the XLA path."""
    if use_pallas is None:
        use_pallas = False  # measured: XLA wins everywhere (docstring)
    elif use_pallas:
        # forced use must stay inside the kernel's validity bounds —
        # outside them the kernel over-fills VMEM or unrolls pathologically
        if not (user_vecs.shape[0] <= _MAX_BATCH and k <= _MAX_K):
            raise ValueError(
                f"use_pallas=True outside the kernel envelope "
                f"(B={user_vecs.shape[0]} <= {_MAX_BATCH}, k={k} <= {_MAX_K})"
            )
    # probe (a real Mosaic compile) only when the kernel would be used
    if not use_pallas or allow.ndim != 1 or (mode := _kernel_mode()) is None:
        from predictionio_tpu.ops.topk import recommend_topk, recommend_topk_chunked

        if (allow.ndim == 1 and item_f.shape[0] >= _MIN_ITEMS
                and user_vecs.shape[0] >= _MIN_BATCH):
            return recommend_topk_chunked(
                user_vecs, item_f, seen_cols, seen_mask, allow, k)
        return recommend_topk(user_vecs, item_f, seen_cols, seen_mask, allow, k)
    seen_cols, seen_mask = _trim_seen(seen_cols, seen_mask)
    tile_i = min(tile_i, max(128, pl.cdiv(item_f.shape[0], 128) * 128))
    try:
        return _pallas_masked_topk(
            user_vecs, item_f, seen_cols.astype(jnp.int32),
            seen_mask.astype(jnp.float32),
            allow.astype(jnp.float32).reshape(1, -1),
            k, tile_i, mode == "interpret",
        )
    except Exception:
        # e.g. a batch/seen-width combination Mosaic rejects on this
        # generation — serve the request on the XLA path instead
        from predictionio_tpu.ops.topk import recommend_topk

        return recommend_topk(user_vecs, item_f, seen_cols, seen_mask, allow, k)


def _trim_seen(seen_cols: jax.Array, seen_mask: jax.Array):
    """Shrink the seen-item pad to the smallest static width covering the
    batch's real max seen count (concrete arrays only — under a tracer
    the caller's pad stands). The kernel unrolls its mask loop S times,
    so this directly scales its per-tile VPU work."""
    if isinstance(seen_mask, jax.core.Tracer) or seen_mask.ndim != 2:
        return seen_cols, seen_mask
    # bound by the last occupied slot (not the count): entries need not
    # be left-packed
    occupied = jnp.where(
        seen_mask > 0,
        jnp.arange(1, seen_mask.shape[1] + 1)[None, :],
        0,
    )
    real = int(jnp.max(occupied))
    for width in _SEEN_WIDTHS:
        if real <= width < seen_mask.shape[1]:
            return seen_cols[:, :width], seen_mask[:, :width]
    return seen_cols, seen_mask
