"""Canonical event record and validation rules.

Behavioral parity with the reference Event model
(reference: data/src/main/scala/.../data/storage/Event.scala:41-170):
an event has an id, name, entity, optional target entity, a DataMap of
properties, event time, tags, an optional predicted-result id, and a
creation time. Reserved events $set/$unset/$delete mutate entity
properties; names with a ``$``/``pio_`` prefix are otherwise rejected.
"""

from __future__ import annotations

import dataclasses
from datetime import datetime, timezone
from typing import Sequence

from predictionio_tpu.core.datamap import DataMap


def utcnow() -> datetime:
    return datetime.now(timezone.utc)


@dataclasses.dataclass(frozen=True)
class Event:
    """One event in the Event Store. Parity: Event.scala:41-53."""

    event: str
    entity_type: str
    entity_id: str
    target_entity_type: str | None = None
    target_entity_id: str | None = None
    properties: DataMap = dataclasses.field(default_factory=DataMap)
    event_time: datetime = dataclasses.field(default_factory=utcnow)
    tags: Sequence[str] = ()
    pr_id: str | None = None
    creation_time: datetime = dataclasses.field(default_factory=utcnow)
    event_id: str | None = None

    def __post_init__(self):
        # Normalize naive datetimes to UTC (reference default zone:
        # EventValidation.defaultTimeZone = UTC, Event.scala:73).
        for name in ("event_time", "creation_time"):
            t = getattr(self, name)
            if t.tzinfo is None:
                object.__setattr__(self, name, t.replace(tzinfo=timezone.utc))
        # Normalize tags to a tuple so Event stays hashable and round-trips
        # identically through every backend.
        if not isinstance(self.tags, tuple):
            object.__setattr__(self, "tags", tuple(self.tags))

    def with_event_id(self, event_id: str) -> "Event":
        return dataclasses.replace(self, event_id=event_id)

    def __str__(self) -> str:
        return (
            f"Event(id={self.event_id},event={self.event},"
            f"eType={self.entity_type},eId={self.entity_id},"
            f"tType={self.target_entity_type},tId={self.target_entity_id},"
            f"p={self.properties},t={self.event_time},tags={list(self.tags)},"
            f"pKey={self.pr_id},ct={self.creation_time})"
        )


class EventValidationError(ValueError):
    """An event violated the validation rules."""


class EventValidation:
    """Validation rules for events. Parity: Event.scala:66-170."""

    #: Reserved single-entity event names (Event.scala:83).
    SPECIAL_EVENTS = frozenset({"$set", "$unset", "$delete"})
    #: Built-in entity types allowed to use the reserved prefix (Event.scala:147).
    BUILTIN_ENTITY_TYPES = frozenset({"pio_pr"})
    #: Built-in property names allowed to use the reserved prefix (Event.scala:150).
    BUILTIN_PROPERTIES: frozenset[str] = frozenset()

    @classmethod
    def is_reserved_prefix(cls, name: str) -> bool:
        return name.startswith("$") or name.startswith("pio_")

    @classmethod
    def is_special_event(cls, name: str) -> bool:
        return name in cls.SPECIAL_EVENTS

    @classmethod
    def is_builtin_entity_type(cls, name: str) -> bool:
        return name in cls.BUILTIN_ENTITY_TYPES

    @classmethod
    def validate(cls, e: Event) -> None:
        """Raise EventValidationError on any rule violation.

        Rule list mirrors EventValidation.validate (Event.scala:113-143).
        """
        def require(cond: bool, msg: str) -> None:
            if not cond:
                raise EventValidationError(msg)

        require(bool(e.event), "event must not be empty.")
        require(bool(e.entity_type), "entityType must not be empty string.")
        require(bool(e.entity_id), "entityId must not be empty string.")
        require(
            e.target_entity_type is None or bool(e.target_entity_type),
            "targetEntityType must not be empty string",
        )
        require(
            e.target_entity_id is None or bool(e.target_entity_id),
            "targetEntityId must not be empty string.",
        )
        require(
            (e.target_entity_type is None) == (e.target_entity_id is None),
            "targetEntityType and targetEntityId must be specified together.",
        )
        require(
            not (e.event == "$unset" and e.properties.is_empty()),
            "properties cannot be empty for $unset event",
        )
        require(
            not cls.is_reserved_prefix(e.event) or cls.is_special_event(e.event),
            f"{e.event} is not a supported reserved event name.",
        )
        require(
            not cls.is_special_event(e.event)
            or (e.target_entity_type is None and e.target_entity_id is None),
            f"Reserved event {e.event} cannot have targetEntity",
        )
        require(
            not cls.is_reserved_prefix(e.entity_type)
            or cls.is_builtin_entity_type(e.entity_type),
            f"The entityType {e.entity_type} is not allowed. "
            "'pio_' is a reserved name prefix.",
        )
        require(
            e.target_entity_type is None
            or not cls.is_reserved_prefix(e.target_entity_type)
            or cls.is_builtin_entity_type(e.target_entity_type),
            f"The targetEntityType {e.target_entity_type} is not allowed. "
            "'pio_' is a reserved name prefix.",
        )
        cls.validate_properties(e)

    @classmethod
    def validate_properties(cls, e: Event) -> None:
        """Property names must not use the reserved prefix (Event.scala:158-169)."""
        for k in e.properties.key_set:
            if cls.is_reserved_prefix(k) and k not in cls.BUILTIN_PROPERTIES:
                raise EventValidationError(
                    f"The property {k} is not allowed. "
                    "'pio_' is a reserved name prefix."
                )
