"""Folding $set/$unset/$delete event streams into per-entity PropertyMaps.

Two implementations with parity to the reference:

- ``aggregate_properties`` / ``aggregate_properties_single`` — the
  order-based fold used for local reads
  (reference: data/.../storage/LEventAggregator.scala:32-148).
- ``EventOp`` — an **associative monoid** carrying per-field timestamps so
  aggregation can run as a tree reduce over arbitrarily partitioned event
  shards (reference: data/.../storage/PEventAggregator.scala:30-212, where
  it backs Spark ``aggregateByKey``). Here it backs parallel aggregation
  over host shards feeding the TPU data path.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from datetime import datetime
from typing import Iterable, Mapping

from predictionio_tpu.core.datamap import DataMap, JsonValue, PropertyMap
from predictionio_tpu.core.event import Event

#: Event names that control aggregation (LEventAggregator.scala:92).
AGGREGATION_EVENT_NAMES = ("$set", "$unset", "$delete")


# ---------------------------------------------------------------------------
# Order-based local fold (LEventAggregator parity)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Prop:
    dm: dict[str, JsonValue] | None = None
    first_updated: datetime | None = None
    last_updated: datetime | None = None


def _fold_one(p: _Prop, e: Event) -> _Prop:
    """Parity: LEventAggregator.propAggregator (LEventAggregator.scala:117-135)."""
    if e.event not in AGGREGATION_EVENT_NAMES:
        return p
    if e.event == "$set":
        dm = dict(e.properties.fields) if p.dm is None else {**p.dm, **e.properties.fields}
    elif e.event == "$unset":
        dm = None if p.dm is None else {
            k: v for k, v in p.dm.items() if k not in e.properties.key_set
        }
    else:  # $delete
        dm = None
    first = e.event_time if p.first_updated is None else min(p.first_updated, e.event_time)
    last = e.event_time if p.last_updated is None else max(p.last_updated, e.event_time)
    return _Prop(dm=dm, first_updated=first, last_updated=last)


def aggregate_properties_single(events: Iterable[Event]) -> PropertyMap | None:
    """Fold one entity's events (any order; sorted by event time here).

    Parity: LEventAggregator.aggregatePropertiesSingle (:69-89).
    """
    prop = _Prop()
    for e in sorted(events, key=lambda e: e.event_time):
        prop = _fold_one(prop, e)
    if prop.dm is None:
        return None
    assert prop.first_updated is not None and prop.last_updated is not None
    return PropertyMap(prop.dm, prop.first_updated, prop.last_updated)


def aggregate_properties(events: Iterable[Event]) -> dict[str, PropertyMap]:
    """Group by entityId, fold each group. Entities whose fold ends in a
    deleted/never-set state are omitted.

    Parity: LEventAggregator.aggregateProperties (:42-60).
    """
    by_entity: dict[str, list[Event]] = defaultdict(list)
    for e in events:
        by_entity[e.entity_id].append(e)
    out: dict[str, PropertyMap] = {}
    for entity_id, evs in by_entity.items():
        pm = aggregate_properties_single(evs)
        if pm is not None:
            out[entity_id] = pm
    return out


# ---------------------------------------------------------------------------
# Associative monoid (PEventAggregator parity) — safe for tree reduction
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class _PropTime:
    """A value with the time it was set (PEventAggregator.scala:29-30)."""
    value: JsonValue
    t: datetime


@dataclasses.dataclass(frozen=True)
class EventOp:
    """Partial aggregate of one entity's property events.

    ``EventOp(e1) + EventOp(e2) + ...`` is associative and commutative over
    event order because every field carries its own timestamp — the
    property that let the reference run it under Spark ``aggregateByKey``
    and lets us tree-reduce over shards (PEventAggregator.scala:89-152).
    """

    set_fields: Mapping[str, _PropTime] = dataclasses.field(default_factory=dict)
    set_t: datetime | None = None        # latest $set time (may have empty fields)
    unset_fields: Mapping[str, datetime] = dataclasses.field(default_factory=dict)
    delete_t: datetime | None = None     # latest $delete time
    first_updated: datetime | None = None
    last_updated: datetime | None = None

    @staticmethod
    def from_event(e: Event) -> "EventOp":
        """Parity: EventOp.apply (PEventAggregator.scala:155-189)."""
        t = e.event_time
        if e.event == "$set":
            return EventOp(
                set_fields={k: _PropTime(v, t) for k, v in e.properties.fields.items()},
                set_t=t, first_updated=t, last_updated=t,
            )
        if e.event == "$unset":
            return EventOp(
                unset_fields={k: t for k in e.properties.key_set},
                first_updated=t, last_updated=t,
            )
        if e.event == "$delete":
            return EventOp(delete_t=t, first_updated=t, last_updated=t)
        return EventOp()

    def __add__(self, other: "EventOp") -> "EventOp":
        """Parity: EventOp.++ (PEventAggregator.scala:96-111 and the SetProp/
        UnsetProp/DeleteEntity combiners above it)."""
        set_fields = dict(self.set_fields)
        for k, pt in other.set_fields.items():
            cur = set_fields.get(k)
            set_fields[k] = pt if cur is None or pt.t > cur.t else cur
        unset_fields = dict(self.unset_fields)
        for k, t in other.unset_fields.items():
            cur_t = unset_fields.get(k)
            unset_fields[k] = t if cur_t is None or t > cur_t else cur_t

        def _max(a, b):
            return b if a is None else (a if b is None else max(a, b))

        def _min(a, b):
            return b if a is None else (a if b is None else min(a, b))

        return EventOp(
            set_fields=set_fields,
            set_t=_max(self.set_t, other.set_t),
            unset_fields=unset_fields,
            delete_t=_max(self.delete_t, other.delete_t),
            first_updated=_min(self.first_updated, other.first_updated),
            last_updated=_max(self.last_updated, other.last_updated),
        )

    def to_property_map(self) -> PropertyMap | None:
        """Resolve the partial aggregate. Parity: EventOp.toPropertyMap
        (PEventAggregator.scala:115-152): a field survives if it was $set and
        neither a later-or-equal $unset of that field nor a later-or-equal
        $delete of the whole entity occurred."""
        if self.set_t is None:
            return None
        if self.delete_t is not None and self.delete_t >= self.set_t:
            return None
        fields: dict[str, JsonValue] = {}
        for k, pt in self.set_fields.items():
            unset_t = self.unset_fields.get(k)
            if unset_t is not None and unset_t >= pt.t:
                continue
            if self.delete_t is not None and self.delete_t >= pt.t:
                continue
            fields[k] = pt.value
        assert self.first_updated is not None and self.last_updated is not None
        return PropertyMap(fields, self.first_updated, self.last_updated)


def aggregate_properties_parallel(
    event_shards: Iterable[Iterable[Event]],
) -> dict[str, PropertyMap]:
    """Aggregate per-entity properties from arbitrarily partitioned shards
    via the EventOp monoid — the host-parallel analogue of
    PEventAggregator.aggregateProperties (:198-211)."""
    acc: dict[str, EventOp] = {}
    for shard in event_shards:
        for e in shard:
            op = EventOp.from_event(e)
            cur = acc.get(e.entity_id)
            acc[e.entity_id] = op if cur is None else cur + op
    out: dict[str, PropertyMap] = {}
    for entity_id, op in acc.items():
        pm = op.to_property_map()
        if pm is not None:
            out[entity_id] = pm
    return out


def aggregate_properties_by_type(
    events: Iterable[Event],
) -> dict[str, dict[str, PropertyMap]]:
    """entityType -> entityId -> PropertyMap, for multi-type aggregation."""
    by_type: dict[str, list[Event]] = defaultdict(list)
    for e in events:
        by_type[e.entity_type].append(e)
    return {t: aggregate_properties(evs) for t, evs in by_type.items()}
