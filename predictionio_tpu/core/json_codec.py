"""Wire-format JSON codec for events (the Event API contract).

Parity with the reference's json4s serializers
(reference: data/src/main/scala/.../data/storage/EventJson4sSupport.scala,
DateTimeJson4sSupport.scala): field names are camelCase, times are ISO8601
with milliseconds and zone offset, and reads apply EventValidation.

The reference maintained two JSON stacks (json4s + Gson) purely for its
Scala/Java duality (core/.../workflow/JsonExtractor.scala:36-167); this
framework deliberately has exactly one canonical codec.

Serving fast path (beyond reference): the /queries.json envelope used
to run the generic reflective binder (core/wire.from_wire / to_wire)
per request — ``typing.get_type_hints`` + ``dataclasses.fields`` + the
camelCase regex on EVERY query and prediction. :func:`compile_wire_decoder`
/ :func:`compile_wire_encoder` hoist all of that to one compile step
per class (field tables, accepted spellings, nested sub-codecs), so the
per-request cost is a dict walk; :func:`canonical_json` is the
normalized query key the result cache and the batcher's dedup pass
share. Wire behavior is bit-identical to core/wire — the equivalence
is pinned by tests/test_serving_perf.py.
"""

from __future__ import annotations

import dataclasses
import json
import typing
from datetime import datetime, timezone
from typing import Any, Callable, Mapping

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event, EventValidation, EventValidationError
from predictionio_tpu.core.wire import (
    _unwrap_optional,
    camel_to_snake,
    snake_to_camel,
)


def format_datetime(t: datetime) -> str:
    """ISO8601 with milliseconds, e.g. ``2004-12-13T21:39:45.618Z``
    (DateTimeJson4sSupport serializes via Utils.dateTimeToString)."""
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    if t.utcoffset() == timezone.utc.utcoffset(None):
        return t.strftime("%Y-%m-%dT%H:%M:%S.") + f"{t.microsecond // 1000:03d}Z"
    return t.isoformat(timespec="milliseconds")


def parse_datetime(s: str) -> datetime:
    """Accept ISO8601 with 'Z' or explicit offsets; naive times are UTC."""
    t = datetime.fromisoformat(s.replace("Z", "+00:00"))
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    return t


def event_to_json(e: Event) -> dict[str, Any]:
    """Event -> API JSON (EventJson4sSupport.writeToJValue parity)."""
    out: dict[str, Any] = {
        "eventId": e.event_id,
        "event": e.event,
        "entityType": e.entity_type,
        "entityId": e.entity_id,
        "targetEntityType": e.target_entity_type,
        "targetEntityId": e.target_entity_id,
        "properties": e.properties.to_json(),
        "eventTime": format_datetime(e.event_time),
        "tags": list(e.tags),
        "prId": e.pr_id,
        "creationTime": format_datetime(e.creation_time),
    }
    return {k: v for k, v in out.items() if v is not None}


def event_from_json(obj: Mapping[str, Any], validate: bool = True) -> Event:
    """API JSON -> Event (EventJson4sSupport.readFromJValue parity):
    required event/entityType/entityId; eventTime defaults to now;
    validation raises EventValidationError."""
    def _req(name: str) -> str:
        v = obj.get(name)
        if not isinstance(v, str):
            raise EventValidationError(f"field {name} is required and must be a string")
        return v

    def _opt_str(name: str) -> str | None:
        v = obj.get(name)
        if v is None:
            return None
        if not isinstance(v, str):
            raise EventValidationError(f"field {name} must be a string")
        return v

    props = obj.get("properties", {})
    if props is None:
        props = {}
    if not isinstance(props, Mapping):
        raise EventValidationError("field properties must be a JSON object")
    tags = obj.get("tags", [])
    if tags is None:
        tags = []
    if not isinstance(tags, list) or not all(isinstance(t, str) for t in tags):
        raise EventValidationError("field tags must be a list of strings")

    event_time_s = _opt_str("eventTime")
    creation_time_s = _opt_str("creationTime")
    try:
        event_time = parse_datetime(event_time_s) if event_time_s else datetime.now(timezone.utc)
        creation_time = (
            parse_datetime(creation_time_s) if creation_time_s else datetime.now(timezone.utc)
        )
    except ValueError as exc:
        raise EventValidationError(f"invalid time format: {exc}") from exc

    e = Event(
        event=_req("event"),
        entity_type=_req("entityType"),
        entity_id=_req("entityId"),
        target_entity_type=_opt_str("targetEntityType"),
        target_entity_id=_opt_str("targetEntityId"),
        properties=DataMap.from_json(props),
        event_time=event_time,
        tags=tags,
        pr_id=_opt_str("prId"),
        creation_time=creation_time,
        event_id=_opt_str("eventId"),
    )
    if validate:
        EventValidation.validate(e)
    return e


# ---------------------------------------------------------------------------
# serving fast path: precompiled wire codecs + canonical query keys
# ---------------------------------------------------------------------------


def canonical_json(obj: Any) -> str:
    """The canonical spelling of a JSON value: sorted keys, no
    whitespace. Two requests carrying the same query in different key
    orders produce the same string — the result cache's key and the
    batcher's dedup key."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      ensure_ascii=False, default=str)


_DECODERS: dict[Any, Callable[[Any], Any]] = {}


def compile_wire_decoder(cls: Any) -> Callable[[Any], Any]:
    """A JSON→``cls`` binder with the reflection hoisted out: type
    hints, field tables, and accepted key spellings (camelCase AND
    snake_case, exactly core/wire.from_wire's contract, including the
    unknown-key rejection) are resolved once per class; the returned
    callable does only dict walks per request."""
    cls = _unwrap_optional(cls)
    try:
        cached = _DECODERS.get(cls)
        hashable = True
    except TypeError:        # unhashable annotation — compile fresh
        cached, hashable = None, False
    if cached is not None:
        return cached
    decoder = _build_decoder(cls)
    if hashable:
        _DECODERS[cls] = decoder
    return decoder


def _build_decoder(cls: Any) -> Callable[[Any], Any]:
    if isinstance(cls, type) and dataclasses.is_dataclass(cls):
        return _build_dataclass_decoder(cls)
    if cls is tuple:
        # bare `tuple` annotations still coerce JSON lists (frozen
        # Query dataclasses rely on tuple fields for hashability)
        return lambda v: tuple(v) if isinstance(v, list) else v
    origin = typing.get_origin(cls)
    if origin in (list, tuple):
        args = typing.get_args(cls)
        elem = args[0] if args and args[0] is not Ellipsis else Any
        if elem is Any:
            if origin is tuple:
                return lambda v: tuple(v) if isinstance(v, list) else v
            return lambda v: v
        sub = compile_wire_decoder(elem)
        if origin is tuple:
            return lambda v: (tuple(sub(x) for x in v)
                              if isinstance(v, list) else v)
        return lambda v: [sub(x) for x in v] if isinstance(v, list) else v
    return lambda v: v


def _build_dataclass_decoder(cls: type) -> Callable[[Any], Any]:
    # register a forward reference FIRST so self-referential dataclass
    # fields compile instead of recursing forever; `accept` is filled
    # in below and shared by closure
    accept: dict[str, tuple[str, Callable[[Any], Any]]] = {}
    wire_names: list[str] = []

    def decode(obj: Any) -> Any:
        if not isinstance(obj, dict):
            raise ValueError(
                f"expected JSON object for {cls.__name__}, "
                f"got {type(obj).__name__}")
        kwargs: dict[str, Any] = {}
        unknown = []
        for key, value in obj.items():
            entry = accept.get(key) or accept.get(camel_to_snake(key))
            if entry is None:
                unknown.append(key)
                continue
            name, sub = entry
            kwargs[name] = sub(value)
        if unknown:
            raise ValueError(
                f"Unknown field(s) {sorted(unknown)} for {cls.__name__} "
                f"(accepted: {sorted(wire_names)})")
        return cls(**kwargs)

    _DECODERS[cls] = decode
    try:
        hints = typing.get_type_hints(cls)
        for f in dataclasses.fields(cls):
            sub = compile_wire_decoder(hints.get(f.name, Any))
            accept[f.name] = (f.name, sub)
            # exact field-name spellings take precedence over a
            # camelCase collision, matching from_wire's resolution order
            accept.setdefault(snake_to_camel(f.name), (f.name, sub))
            wire_names.append(snake_to_camel(f.name))
    except BaseException:
        # a failed compile (e.g. unresolvable forward-ref annotation)
        # must not leave the half-built decoder cached — a later retry
        # would silently serve its empty accept table
        _DECODERS.pop(cls, None)
        raise
    return decode


#: per-dataclass (attr, wireName) field tables for the fast encoder
_ENCODER_FIELDS: dict[type, tuple[tuple[str, str], ...]] = {}

_SCALARS = (str, int, float, bool, type(None))


def encode_wire(obj: Any) -> Any:
    """Fast ``core/wire.to_wire``: identical output, with per-class
    field tables compiled once instead of ``dataclasses.fields`` + the
    camelCase conversion per call."""
    if isinstance(obj, _SCALARS):
        return obj
    t = type(obj)
    pairs = _ENCODER_FIELDS.get(t)
    if pairs is None and dataclasses.is_dataclass(obj) \
            and not isinstance(obj, type):
        pairs = tuple((f.name, snake_to_camel(f.name))
                      for f in dataclasses.fields(t))
        _ENCODER_FIELDS[t] = pairs
    if pairs is not None:
        return {wire: encode_wire(getattr(obj, name)) for name, wire in pairs}
    if isinstance(obj, (list, tuple)):
        return [encode_wire(v) for v in obj]
    if isinstance(obj, dict):
        return {str(k): encode_wire(v) for k, v in obj.items()}
    if hasattr(obj, "item") and callable(getattr(obj, "item", None)) \
            and hasattr(obj, "dtype"):
        return obj.item()  # numpy/jax scalar, one host fetch at the wire
    return obj


def compile_wire_encoder(cls: type) -> Callable[[Any], Any]:
    """Prime the encoder table for ``cls`` and return the fast encoder
    (callers that know their prediction class ahead of the first
    request avoid even the one lazy-compile dict miss)."""
    if isinstance(cls, type) and dataclasses.is_dataclass(cls):
        _ENCODER_FIELDS.setdefault(
            cls, tuple((f.name, snake_to_camel(f.name))
                       for f in dataclasses.fields(cls)))
    return encode_wire
