"""Wire-format JSON codec for events (the Event API contract).

Parity with the reference's json4s serializers
(reference: data/src/main/scala/.../data/storage/EventJson4sSupport.scala,
DateTimeJson4sSupport.scala): field names are camelCase, times are ISO8601
with milliseconds and zone offset, and reads apply EventValidation.

The reference maintained two JSON stacks (json4s + Gson) purely for its
Scala/Java duality (core/.../workflow/JsonExtractor.scala:36-167); this
framework deliberately has exactly one canonical codec.
"""

from __future__ import annotations

from datetime import datetime, timezone
from typing import Any, Mapping

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event, EventValidation, EventValidationError


def format_datetime(t: datetime) -> str:
    """ISO8601 with milliseconds, e.g. ``2004-12-13T21:39:45.618Z``
    (DateTimeJson4sSupport serializes via Utils.dateTimeToString)."""
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    if t.utcoffset() == timezone.utc.utcoffset(None):
        return t.strftime("%Y-%m-%dT%H:%M:%S.") + f"{t.microsecond // 1000:03d}Z"
    return t.isoformat(timespec="milliseconds")


def parse_datetime(s: str) -> datetime:
    """Accept ISO8601 with 'Z' or explicit offsets; naive times are UTC."""
    t = datetime.fromisoformat(s.replace("Z", "+00:00"))
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    return t


def event_to_json(e: Event) -> dict[str, Any]:
    """Event -> API JSON (EventJson4sSupport.writeToJValue parity)."""
    out: dict[str, Any] = {
        "eventId": e.event_id,
        "event": e.event,
        "entityType": e.entity_type,
        "entityId": e.entity_id,
        "targetEntityType": e.target_entity_type,
        "targetEntityId": e.target_entity_id,
        "properties": e.properties.to_json(),
        "eventTime": format_datetime(e.event_time),
        "tags": list(e.tags),
        "prId": e.pr_id,
        "creationTime": format_datetime(e.creation_time),
    }
    return {k: v for k, v in out.items() if v is not None}


def event_from_json(obj: Mapping[str, Any], validate: bool = True) -> Event:
    """API JSON -> Event (EventJson4sSupport.readFromJValue parity):
    required event/entityType/entityId; eventTime defaults to now;
    validation raises EventValidationError."""
    def _req(name: str) -> str:
        v = obj.get(name)
        if not isinstance(v, str):
            raise EventValidationError(f"field {name} is required and must be a string")
        return v

    def _opt_str(name: str) -> str | None:
        v = obj.get(name)
        if v is None:
            return None
        if not isinstance(v, str):
            raise EventValidationError(f"field {name} must be a string")
        return v

    props = obj.get("properties", {})
    if props is None:
        props = {}
    if not isinstance(props, Mapping):
        raise EventValidationError("field properties must be a JSON object")
    tags = obj.get("tags", [])
    if tags is None:
        tags = []
    if not isinstance(tags, list) or not all(isinstance(t, str) for t in tags):
        raise EventValidationError("field tags must be a list of strings")

    event_time_s = _opt_str("eventTime")
    creation_time_s = _opt_str("creationTime")
    try:
        event_time = parse_datetime(event_time_s) if event_time_s else datetime.now(timezone.utc)
        creation_time = (
            parse_datetime(creation_time_s) if creation_time_s else datetime.now(timezone.utc)
        )
    except ValueError as exc:
        raise EventValidationError(f"invalid time format: {exc}") from exc

    e = Event(
        event=_req("event"),
        entity_type=_req("entityType"),
        entity_id=_req("entityId"),
        target_entity_type=_opt_str("targetEntityType"),
        target_entity_id=_opt_str("targetEntityId"),
        properties=DataMap.from_json(props),
        event_time=event_time,
        tags=tags,
        pr_id=_opt_str("prId"),
        creation_time=creation_time,
        event_id=_opt_str("eventId"),
    )
    if validate:
        EventValidation.validate(e)
    return e
