"""Schemaless property bags attached to events and entities.

Behavioral parity with the reference's DataMap / PropertyMap
(reference: data/src/main/scala/.../data/storage/DataMap.scala:45-245,
PropertyMap.scala:36-99): a JSON object with typed getters, merge (``++``)
and key-removal (``--``) operators, and dataclass extraction. PropertyMap
additionally carries first/last updated times — the result of folding
$set/$unset/$delete event streams (see core/aggregation.py).
"""

from __future__ import annotations

import dataclasses
from datetime import datetime
from typing import Any, Iterable, Iterator, Mapping, Type, TypeVar

T = TypeVar("T")

# JSON value types a DataMap field may hold.
JsonValue = None | bool | int | float | str | list | dict


class DataMapError(KeyError):
    """Raised when a required field is missing or has the wrong type."""


def _convert(value: Any, target: Type[T], field: str) -> T:
    """Coerce a JSON value to the requested Python type, strictly enough to
    mirror the reference's json4s extraction failures (DataMap.scala:96-112)."""
    if target is Any:
        return value
    if target is float and isinstance(value, int) and not isinstance(value, bool):
        return float(value)  # JSON has one number type; int -> float is lossless intent
    if target is datetime:
        if isinstance(value, datetime):
            return value
        if isinstance(value, str):
            return datetime.fromisoformat(value.replace("Z", "+00:00"))
        raise DataMapError(f"field {field!r} is not a datetime: {value!r}")
    if isinstance(target, type) and isinstance(value, target):
        if target is int and isinstance(value, bool):
            raise DataMapError(f"field {field!r} is bool, expected int")
        return value
    raise DataMapError(
        f"field {field!r} has type {type(value).__name__}, expected {getattr(target, '__name__', target)}"
    )


class DataMap(Mapping[str, JsonValue]):
    """Immutable, schemaless JSON property bag with typed getters.

    Parity: DataMap.scala:45-245. ``get`` on a missing/null field raises
    (the reference throws DataMapException); ``get_opt`` returns None.
    """

    __slots__ = ("_fields",)

    def __init__(self, fields: Mapping[str, JsonValue] | None = None):
        # Explicit JSON nulls are KEPT in the field map (key_set/len include
        # them; $unset events carry them as the keys to remove) but the typed
        # getters treat a null field as absent — same as the reference, where
        # json4s JNull stays in the JObject (DataMap.scala:96-129).
        self._fields: dict[str, JsonValue] = dict(fields or {})

    # -- Mapping protocol -------------------------------------------------
    def __getitem__(self, key: str) -> JsonValue:
        return self._fields[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._fields)

    def __len__(self) -> int:
        return len(self._fields)

    def __contains__(self, key: object) -> bool:
        return key in self._fields

    # -- reference API ----------------------------------------------------
    @property
    def fields(self) -> dict[str, JsonValue]:
        return dict(self._fields)

    def require(self, name: str) -> None:
        """Parity: DataMap.require (DataMap.scala:58-63)."""
        if name not in self._fields or self._fields[name] is None:
            raise DataMapError(f"The field {name} is required.")

    def contains(self, name: str) -> bool:
        return name in self._fields and self._fields[name] is not None

    def get(self, name: str, as_type: Type[T] = object) -> T:  # type: ignore[assignment]
        """Typed getter; raises DataMapError if absent or null.

        Parity: DataMap.get[T] (DataMap.scala:96-112).
        """
        self.require(name)
        return _convert(self._fields[name], as_type, name)

    def get_opt(self, name: str, as_type: Type[T] = object) -> T | None:  # type: ignore[assignment]
        """Typed getter returning None when absent or null.

        Parity: DataMap.getOpt[T] (DataMap.scala:119-129).
        """
        if not self.contains(name):
            return None
        return _convert(self._fields[name], as_type, name)

    def get_or_else(self, name: str, default: T) -> T:
        v = self.get_opt(name, type(default))
        return default if v is None else v

    def get_list(self, name: str, element_type: Type[T] = object) -> list[T]:  # type: ignore[assignment]
        raw = self.get(name, list)
        return [_convert(v, element_type, f"{name}[{i}]") for i, v in enumerate(raw)]

    def get_list_opt(self, name: str, element_type: Type[T] = object) -> list[T] | None:  # type: ignore[assignment]
        if not self.contains(name):
            return None
        return self.get_list(name, element_type)

    def extract(self, dataclass_type: Type[T]) -> T:
        """Extract fields into a dataclass; Optional fields may be absent.

        Parity: DataMap.extract[A] via json4s (DataMap.scala:183-194).
        """
        if not dataclasses.is_dataclass(dataclass_type):
            raise TypeError(f"{dataclass_type} is not a dataclass")
        kwargs: dict[str, Any] = {}
        for f in dataclasses.fields(dataclass_type):
            has_default = (
                f.default is not dataclasses.MISSING
                or f.default_factory is not dataclasses.MISSING  # type: ignore[misc]
            )
            if self.contains(f.name):
                target = f.type
                # Resolve "X | None" annotations to X for conversion.
                origin = getattr(target, "__args__", None)
                if origin:
                    non_none = [a for a in origin if a is not type(None)]
                    if len(non_none) == 1:
                        target = non_none[0]
                    else:
                        target = object
                if isinstance(target, str):  # postponed annotation; best-effort
                    target = object
                kwargs[f.name] = _convert(self._fields[f.name], target, f.name)
            elif not has_default:
                raise DataMapError(f"The field {f.name} is required.")
        return dataclass_type(**kwargs)

    # -- operators ---------------------------------------------------------
    def merge(self, other: "DataMap | Mapping[str, JsonValue]") -> "DataMap":
        """Right-biased merge. Parity: DataMap.++ (DataMap.scala:205-210)."""
        merged = dict(self._fields)
        merged.update(other.fields if isinstance(other, DataMap) else dict(other))
        return type(self)._with_fields(self, merged)

    def remove(self, keys: Iterable[str]) -> "DataMap":
        """Remove keys. Parity: DataMap.-- (DataMap.scala:216-221)."""
        drop = set(keys)
        return type(self)._with_fields(
            self, {k: v for k, v in self._fields.items() if k not in drop}
        )

    def __add__(self, other: "DataMap | Mapping[str, JsonValue]") -> "DataMap":
        return self.merge(other)

    def __sub__(self, keys: Iterable[str]) -> "DataMap":
        return self.remove(keys)

    def _with_fields(self, fields: dict[str, JsonValue]) -> "DataMap":
        return DataMap(fields)

    def is_empty(self) -> bool:
        return not self._fields

    @property
    def key_set(self) -> set[str]:
        return set(self._fields)

    def to_json(self) -> dict[str, JsonValue]:
        return dict(self._fields)

    @classmethod
    def from_json(cls, obj: Mapping[str, JsonValue] | None) -> "DataMap":
        return cls(obj or {})

    def __eq__(self, other: object) -> bool:
        if isinstance(other, DataMap):
            return self._fields == other._fields
        return NotImplemented

    def __hash__(self) -> int:
        # Key-only hash: weak but contract-safe — any two maps that compare
        # equal (including int==float values, or PropertyMap vs DataMap with
        # equal fields) hash identically.
        return hash(frozenset(self._fields))

    def __repr__(self) -> str:
        return f"DataMap({self._fields!r})"


class PropertyMap(DataMap):
    """A DataMap produced by aggregating $set/$unset/$delete events, plus the
    first/last times the entity's properties were updated.

    Parity: PropertyMap.scala:36-99.
    """

    __slots__ = ("first_updated", "last_updated")

    def __init__(
        self,
        fields: Mapping[str, JsonValue] | None,
        first_updated: datetime,
        last_updated: datetime,
    ):
        super().__init__(fields)
        self.first_updated = first_updated
        self.last_updated = last_updated

    def _with_fields(self, fields: dict[str, JsonValue]) -> "PropertyMap":
        return PropertyMap(fields, self.first_updated, self.last_updated)

    def __eq__(self, other: object) -> bool:
        # Same cross-type equality shape as the reference (PropertyMap.equals,
        # PropertyMap.scala:58-66): PropertyMap==PropertyMap compares times
        # too, PropertyMap==DataMap compares fields only. Like the reference
        # this is knowingly non-transitive across the two types.
        if isinstance(other, PropertyMap):
            return (
                self._fields == other._fields
                and self.first_updated == other.first_updated
                and self.last_updated == other.last_updated
            )
        if isinstance(other, DataMap):
            return self._fields == other._fields
        return NotImplemented

    # Inherit DataMap's key-only hash so PropertyMap/DataMap pairs that
    # compare equal hash equally (eq/hash contract).
    __hash__ = DataMap.__hash__

    def __repr__(self) -> str:
        return (
            f"PropertyMap({self._fields!r}, first_updated={self.first_updated}, "
            f"last_updated={self.last_updated})"
        )
