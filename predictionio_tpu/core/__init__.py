"""Core event-data model: Event, DataMap, PropertyMap, aggregation."""
