"""EventColumns — struct-of-arrays event batches for the columnar data plane.

The reference amortized training-time event scans across a Spark
cluster (PEvents' RDD reads); this port's equivalent lever is trading
per-event Python objects for numpy columns. ``Events.find_columnar``
(storage/base.py) yields these batches; the train path consumes them
through ``EventStore.scan`` (data/store.py) so events land in the
padded jit-ready arrays without a per-event Python loop.

Layout per batch of ``n`` events:

- ``event_time_us`` — int64 epoch-microseconds (exact: datetime
  resolution is µs, so the int64 column round-trips losslessly);
- ``event``, ``entity_type``, ``entity_id``, ``target_entity_type``,
  ``target_entity_id`` — dictionary-encoded :class:`DictColumn`
  (int32 codes + string vocab; ``None`` is a vocab entry, so optional
  columns need no separate mask);
- ``event_ids`` — plain tuple (ids are unique, dictionary encoding
  would only add indirection);
- everything else (properties, tags, prId, creationTime) — a LAZY
  row-payload column: the backend hands over whatever cheap per-row
  representation it already holds (Event objects for the in-memory
  store, raw JSON strings for SQL rows, framed event-JSON payloads for
  the binary log) and decoding happens only for the rows a consumer
  actually touches. Scans that never read properties never parse them.
"""

from __future__ import annotations

import dataclasses
import json
from datetime import datetime, timedelta, timezone
from typing import Any, Iterable, Sequence

import numpy as np

from predictionio_tpu.core.datamap import DataMap
from predictionio_tpu.core.event import Event

_EPOCH = datetime(1970, 1, 1, tzinfo=timezone.utc)


def datetime_to_us(t: datetime) -> int:
    """Exact microseconds since epoch (same arithmetic as the binevents
    frame format, storage/binevents.py)."""
    delta = t - _EPOCH
    return (delta.days * 86_400 + delta.seconds) * 1_000_000 + delta.microseconds


def us_to_datetime(us: int) -> datetime:
    """Inverse of :func:`datetime_to_us`, exact (no float round-trip)."""
    return _EPOCH + timedelta(microseconds=int(us))


class DictColumn:
    """Dictionary-encoded string column: int32 codes into a small vocab.

    Event-name/entity-type/entity-id columns are low-cardinality, so the
    string work is O(vocab) instead of O(events); ``decode()`` expands
    to an object array for vectorized consumers (numpy fancy-indexing,
    one C loop)."""

    __slots__ = ("codes", "vocab")

    def __init__(self, codes: np.ndarray, vocab: Sequence[str | None]):
        self.codes = np.asarray(codes, dtype=np.int32)
        self.vocab = tuple(vocab)

    def __len__(self) -> int:
        return len(self.codes)

    def decode(self) -> np.ndarray:
        """codes -> object array of strings (or None)."""
        return np.asarray(self.vocab, dtype=object)[self.codes]

    def __getitem__(self, i: int) -> str | None:
        return self.vocab[self.codes[i]]

    def code_of(self, value: str | None) -> int | None:
        """The code for ``value`` in this batch's vocab, or None when the
        value never occurs (lets consumers compare int codes, not strings)."""
        try:
            return self.vocab.index(value)
        except ValueError:
            return None


def encode_column(values: Sequence[str | None]) -> DictColumn:
    """Dictionary-encode one column at C speed: ``dict.fromkeys`` builds
    the order-preserving vocab in a single C call, and the codes come
    from mapping the C-level ``dict.__getitem__`` under ``np.fromiter``
    — no per-value Python frame (a method-per-value encoder measured
    ~3x slower on the sqlite scan)."""
    index = {v: i for i, v in enumerate(dict.fromkeys(values))}
    codes = np.fromiter(map(index.__getitem__, values), dtype=np.int32,
                        count=len(values))
    return DictColumn(codes, list(index))


# ---------------------------------------------------------------------------
# Lazy row payloads: the cold fields, decoded per row on demand
# ---------------------------------------------------------------------------

class _EventRows:
    """Cold fields backed by already-materialized Event objects (the
    in-memory store and the generic rows->columns fallback)."""

    __slots__ = ("events",)

    def __init__(self, events: Sequence[Event]):
        self.events = events

    def properties(self, i: int) -> DataMap:
        return self.events[i].properties

    def properties_raw(self, i: int) -> dict:
        return self.events[i].properties.fields

    def tags(self, i: int) -> tuple[str, ...]:
        return tuple(self.events[i].tags)

    def pr_id(self, i: int) -> str | None:
        return self.events[i].pr_id

    def creation_time(self, i: int) -> datetime:
        return self.events[i].creation_time


class _JsonRows:
    """Cold fields as raw SQL columns (properties/tags as the JSON text
    the row already carries, creationTime as its stored text — all
    parsed only when asked; a scan that never materializes Events never
    pays any of it)."""

    __slots__ = ("props_json", "tags_json", "pr_ids", "creation_raw")

    def __init__(self, props_json: Sequence[str | None],
                 tags_json: Sequence[str | None],
                 pr_ids: Sequence[str | None],
                 creation_raw: Sequence[str]):
        self.props_json = props_json
        self.tags_json = tags_json
        self.pr_ids = pr_ids
        self.creation_raw = creation_raw

    def properties(self, i: int) -> DataMap:
        raw = self.props_json[i]
        return DataMap.from_json(json.loads(raw)) if raw else DataMap()

    def properties_raw(self, i: int) -> dict:
        raw = self.props_json[i]
        return json.loads(raw) if raw else {}

    def tags(self, i: int) -> tuple[str, ...]:
        raw = self.tags_json[i]
        return tuple(json.loads(raw)) if raw else ()

    def pr_id(self, i: int) -> str | None:
        return self.pr_ids[i]

    def creation_time(self, i: int) -> datetime:
        from predictionio_tpu.core.json_codec import parse_datetime

        return parse_datetime(self.creation_raw[i])


class _EventJsonRows:
    """Cold fields inside full event-JSON payloads (the binevents frame
    carries the filterable fields in binary and the rest as one JSON
    blob; a scan that never touches properties never parses it)."""

    __slots__ = ("payloads", "_cache")

    def __init__(self, payloads: Sequence[bytes | str]):
        self.payloads = payloads
        self._cache: dict[int, dict] = {}

    def _doc(self, i: int) -> dict:
        doc = self._cache.get(i)
        if doc is None:
            doc = self._cache[i] = json.loads(self.payloads[i])
        return doc

    def properties(self, i: int) -> DataMap:
        return DataMap.from_json(self._doc(i).get("properties") or {})

    def properties_raw(self, i: int) -> dict:
        return self._doc(i).get("properties") or {}

    def tags(self, i: int) -> tuple[str, ...]:
        return tuple(self._doc(i).get("tags") or ())

    def pr_id(self, i: int) -> str | None:
        return self._doc(i).get("prId")

    def creation_time(self, i: int) -> datetime:
        from predictionio_tpu.core.json_codec import parse_datetime

        raw = self._doc(i).get("creationTime")
        return parse_datetime(raw) if raw else us_to_datetime(0)

    def event_time(self, i: int) -> datetime:
        """Payload eventTime — the wire format truncates to
        milliseconds, and materialized Events must match what the row
        path (``find``) returns bit-for-bit; the µs-exact instant stays
        available in the batch's ``event_time_us`` column."""
        from predictionio_tpu.core.json_codec import parse_datetime

        return parse_datetime(self._doc(i)["eventTime"])


# ---------------------------------------------------------------------------
# The batch type
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class EventColumns:
    """One struct-of-arrays batch of events (module docstring has the
    layout). Row order is the backend's ``find`` order for the same
    filter — the columnar/row conformance suite pins that equivalence
    for every backend (tests/test_storage_conformance.py)."""

    event_time_us: np.ndarray          # int64[n]
    event: DictColumn
    entity_type: DictColumn
    entity_id: DictColumn
    target_entity_type: DictColumn
    target_entity_id: DictColumn
    event_ids: tuple[str | None, ...]
    _rows: Any                         # lazy cold-field provider

    def __len__(self) -> int:
        return len(self.event_time_us)

    # -- vectorized accessors ------------------------------------------------
    def event_times(self) -> np.ndarray:
        """int64 epoch-micros (the canonical time column)."""
        return self.event_time_us

    def properties(self, i: int) -> DataMap:
        """Row ``i``'s properties, decoded on demand."""
        return self._rows.properties(i)

    def properties_raw(self, i: int) -> dict:
        """Row ``i``'s properties as the plain decoded-JSON mapping —
        the hot-path accessor: no DataMap wrapping, no per-value
        conversion pass; use :meth:`properties` when DataMap semantics
        (typed getters, datetime revival) matter."""
        return self._rows.properties_raw(i)

    # -- materialization -----------------------------------------------------
    def to_events(self) -> list[Event]:
        """Materialize Event objects (the row-path escape hatch; batch
        consumers should read the arrays instead)."""
        if isinstance(self._rows, _EventRows):
            # the batch was built FROM these Events — hand them back
            # instead of reconstructing field-identical copies
            return list(self._rows.events)
        ev_names = self.event.decode()
        etypes = self.entity_type.decode()
        eids = self.entity_id.decode()
        tets = self.target_entity_type.decode()
        teis = self.target_entity_id.decode()
        rows = self._rows
        # providers whose row payload carries its own event-time
        # spelling (the binary log's ms-truncated wire JSON) override
        # the column so materialized Events match find() exactly
        row_time = getattr(rows, "event_time", None)
        return [
            Event(
                event=ev_names[i],
                entity_type=etypes[i],
                entity_id=eids[i],
                target_entity_type=tets[i],
                target_entity_id=teis[i],
                properties=rows.properties(i),
                event_time=(row_time(i) if row_time is not None
                            else us_to_datetime(self.event_time_us[i])),
                tags=rows.tags(i),
                pr_id=rows.pr_id(i),
                creation_time=rows.creation_time(i),
                event_id=self.event_ids[i],
            )
            for i in range(len(self))
        ]

    # -- constructors --------------------------------------------------------
    @staticmethod
    def from_events(events: Sequence[Event]) -> "EventColumns":
        """Single-pass rows->columns build (the generic fallback every
        backend inherits, and the in-memory store's native path). One
        list comprehension per column + the C-speed encoder — not one
        Python loop doing six things per event."""
        events = events if isinstance(events, (list, tuple)) else list(events)
        n = len(events)
        times = np.fromiter(
            (datetime_to_us(e.event_time) for e in events),
            dtype=np.int64, count=n)
        return EventColumns(
            event_time_us=times,
            event=encode_column([e.event for e in events]),
            entity_type=encode_column([e.entity_type for e in events]),
            entity_id=encode_column([e.entity_id for e in events]),
            target_entity_type=encode_column(
                [e.target_entity_type for e in events]),
            target_entity_id=encode_column(
                [e.target_entity_id for e in events]),
            event_ids=tuple(e.event_id for e in events),
            _rows=_EventRows(events),
        )

    @staticmethod
    def from_sql_columns(times_us: np.ndarray,
                         event: DictColumn, entity_type: DictColumn,
                         entity_id: DictColumn, target_entity_type: DictColumn,
                         target_entity_id: DictColumn,
                         event_ids: Sequence[str | None],
                         props_json: Sequence[str | None],
                         tags_json: Sequence[str | None],
                         pr_ids: Sequence[str | None],
                         creation_raw: Sequence[str]) -> "EventColumns":
        """SQL rows already split into columns; properties/tags stay the
        raw JSON text of the row (the lazy JSON column) and
        creationTime stays its stored text — only event_time is eager
        (it is the hot column scans sort and range-filter on)."""
        return EventColumns(
            event_time_us=np.asarray(times_us, dtype=np.int64),
            event=event, entity_type=entity_type, entity_id=entity_id,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            event_ids=tuple(event_ids),
            _rows=_JsonRows(props_json, tags_json, pr_ids, creation_raw),
        )

    @staticmethod
    def from_event_json(times_us: np.ndarray,
                        event: DictColumn, entity_type: DictColumn,
                        entity_id: DictColumn, target_entity_type: DictColumn,
                        target_entity_id: DictColumn,
                        event_ids: Sequence[str | None],
                        payloads: Sequence[bytes | str]) -> "EventColumns":
        """Binary-log frames: hot fields decoded straight from the frame
        header, cold fields left inside the event-JSON payload."""
        return EventColumns(
            event_time_us=np.asarray(times_us, dtype=np.int64),
            event=event, entity_type=entity_type, entity_id=entity_id,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            event_ids=tuple(event_ids),
            _rows=_EventJsonRows(payloads),
        )


def check_batch_size(batch_size: int) -> None:
    """Eager validation shared by every find_columnar implementation:
    those are generator functions, so an in-body check would only fire
    at first iteration — far from the misconfigured call site."""
    if batch_size <= 0:
        raise ValueError(f"batch_size must be positive, got {batch_size}")


def iter_batches(events: Iterable[Event], batch_size: int):
    """Chunk an event iterator into EventColumns batches (the generic
    rows->columns fallback; storage/base.py wires it as the default
    ``find_columnar``)."""
    check_batch_size(batch_size)
    return _iter_batches(events, batch_size)


def _iter_batches(events: Iterable[Event], batch_size: int):
    import itertools

    it = iter(events)
    while True:
        chunk = list(itertools.islice(it, batch_size))
        if not chunk:
            return
        yield EventColumns.from_events(chunk)
