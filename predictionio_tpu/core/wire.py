"""Wire-format JSON ↔ dataclass binding for queries and predictions.

The reference serialized Scala case classes (camelCase fields) with
json4s/Gson on the /queries.json path (CreateServer.scala:470-621,
JsonExtractor.scala:60-100). Our component types are snake_case Python
dataclasses; this codec keeps the HTTP wire format reference-compatible:

- output: dataclasses → JSON objects with camelCase keys, tuples → arrays;
- input: JSON objects bind to dataclass fields accepting camelCase or
  snake_case keys, recursing into nested dataclass / tuple-of-dataclass
  fields.
"""

from __future__ import annotations

import dataclasses
import re
import types
import typing
from typing import Any, Type, TypeVar

T = TypeVar("T")

_CAMEL_RE = re.compile(r"([a-z0-9])([A-Z])")


def camel_to_snake(name: str) -> str:
    return _CAMEL_RE.sub(r"\1_\2", name).lower()


def snake_to_camel(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(w.capitalize() for w in rest)


def to_wire(obj: Any) -> Any:
    """Dataclass/tuple/list/dict → plain JSON value with camelCase keys."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            snake_to_camel(f.name): to_wire(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    if isinstance(obj, (list, tuple)):
        return [to_wire(v) for v in obj]
    if isinstance(obj, dict):
        return {str(k): to_wire(v) for k, v in obj.items()}
    if hasattr(obj, "item") and callable(getattr(obj, "item", None)) and hasattr(obj, "dtype"):
        return obj.item()  # numpy/jax scalar
    return obj


def _unwrap_optional(tp: Any) -> Any:
    # both typing.Optional[X] and PEP-604 "X | None"
    if typing.get_origin(tp) in (typing.Union, types.UnionType):
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def from_wire(cls: Type[T], obj: Any) -> T:
    """Bind a JSON value to ``cls``. Dataclass fields accept their
    camelCase or snake_case spelling; unknown keys are rejected (the
    json4s strict-extraction behavior the event API also follows)."""
    cls = _unwrap_optional(cls)
    if not (isinstance(cls, type) and dataclasses.is_dataclass(cls)):
        # bare `tuple` annotations (no type params) still coerce JSON
        # lists — frozen Query dataclasses rely on tuple fields for
        # hashability
        if cls is tuple and isinstance(obj, list):
            return tuple(obj)
        origin = typing.get_origin(cls)
        if origin in (list, tuple) and isinstance(obj, list):
            args = typing.get_args(cls)
            elem = args[0] if args and args[0] is not Ellipsis else Any
            vals = [from_wire(elem, v) if elem is not Any else v for v in obj]
            return tuple(vals) if origin is tuple else vals
        return obj
    if not isinstance(obj, dict):
        raise ValueError(f"expected JSON object for {cls.__name__}, got {type(obj).__name__}")

    hints = typing.get_type_hints(cls)
    fields = {f.name: f for f in dataclasses.fields(cls)}
    by_wire_name = {snake_to_camel(n): n for n in fields}
    kwargs: dict[str, Any] = {}
    unknown = []
    for key, value in obj.items():
        name = key if key in fields else by_wire_name.get(key) or camel_to_snake(key)
        if name not in fields:
            unknown.append(key)
            continue
        kwargs[name] = from_wire(hints.get(name, Any), value)
    if unknown:
        raise ValueError(
            f"Unknown field(s) {sorted(unknown)} for {cls.__name__} "
            f"(accepted: {sorted(by_wire_name)})"
        )
    return cls(**kwargs)
