"""Mesh/sharding utilities — the distribution substrate.

Replaces the role Spark played in the reference (SURVEY.md §2.6): data
parallelism via arrays sharded over the ``data`` mesh axis, model/embedding
sharding over the ``model`` axis, XLA collectives instead of shuffles.
"""

from predictionio_tpu.parallel.mesh import (
    data_sharding,
    model_sharding,
    pad_to_multiple,
    replicated,
    shard_put,
)

__all__ = [
    "data_sharding",
    "model_sharding",
    "pad_to_multiple",
    "replicated",
    "shard_put",
]
