"""Sharding helpers over a 2D ("data", "model") mesh.

Conventions (see workflow/context.EngineContext): batch-like dimensions
shard over ``data``; embedding-table rows shard over ``model``. Ragged
host data is padded to a multiple of the axis size before device_put so
shapes stay static under jit (SURVEY.md §7 hard-parts: recompilation
control lives at this boundary).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharding(mesh: Mesh, ndim: int = 1, axis: int = 0) -> NamedSharding:
    """Shard dimension ``axis`` over the "data" mesh axis."""
    spec = [None] * ndim
    spec[axis] = "data"
    return NamedSharding(mesh, P(*spec))


def model_sharding(mesh: Mesh, ndim: int = 2, axis: int = 0) -> NamedSharding:
    """Shard dimension ``axis`` over the "model" mesh axis (embedding rows)."""
    spec = [None] * ndim
    spec[axis] = "model"
    return NamedSharding(mesh, P(*spec))


def pad_to_multiple(
    array: np.ndarray, multiple: int, axis: int = 0, fill: Any = 0
) -> tuple[np.ndarray, int]:
    """Pad ``array`` along ``axis`` to the next multiple; returns
    (padded, original_length)."""
    n = array.shape[axis]
    target = ((n + multiple - 1) // multiple) * multiple if n else multiple
    if target == n:
        return array, n
    pad_width = [(0, 0)] * array.ndim
    pad_width[axis] = (0, target - n)
    return np.pad(array, pad_width, constant_values=fill), n


def shard_put(
    array: np.ndarray, mesh: Mesh, axis: int = 0, mesh_axis: str = "data"
) -> jax.Array:
    """device_put a host array sharded along one mesh axis (the
    TableInputFormat/JdbcRDD -> executor-partition analogue)."""
    spec = [None] * array.ndim
    spec[axis] = mesh_axis
    return jax.device_put(array, NamedSharding(mesh, P(*spec)))


def shard_batch(
    arrays: Sequence[np.ndarray], mesh: Mesh, fill: Any = 0
) -> tuple[list[jax.Array], int]:
    """Pad a set of equal-length host arrays to the data-axis multiple and
    shard them; returns (device arrays, original length)."""
    axis_size = mesh.shape["data"]
    out = []
    n = arrays[0].shape[0]
    for a in arrays:
        padded, _ = pad_to_multiple(a, axis_size, fill=fill)
        out.append(shard_put(padded, mesh))
    return out, n
