"""Multi-host runtime initialization (DCN).

The reference scaled across machines through Spark's driver/executor
model (spark-submit --master, Runner.scala:185-307); the TPU-native
equivalent is `jax.distributed`: every host runs the same program,
`jax.distributed.initialize` wires them over DCN, and the global mesh
spans all hosts' devices — ICI inside a slice, DCN between slices
(SURVEY.md §2.6 TPU-equivalent note).

Env contract (the spark-submit argument surface collapsed to env vars):

- ``PIO_NUM_HOSTS``            total processes (absent/1 = single host)
- ``PIO_HOST_INDEX``           this process's index [0, n)
- ``PIO_COORDINATOR_ADDRESS``  host:port of process 0

The CLI calls :func:`maybe_initialize_distributed` once at startup; it is
a no-op unless PIO_NUM_HOSTS > 1, so single-host users never notice it.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

_initialized = False


def maybe_initialize_distributed() -> bool:
    """Initialize jax.distributed from PIO_* env vars when configured.
    Returns whether multi-host mode is active. Idempotent."""
    global _initialized
    num_hosts = int(os.environ.get("PIO_NUM_HOSTS", "1"))
    if num_hosts <= 1:
        return False
    if _initialized:
        return True

    coordinator = os.environ.get("PIO_COORDINATOR_ADDRESS")
    host_index = os.environ.get("PIO_HOST_INDEX")
    if coordinator is None or host_index is None:
        raise RuntimeError(
            "PIO_NUM_HOSTS > 1 requires PIO_COORDINATOR_ADDRESS (host:port "
            "of host 0) and PIO_HOST_INDEX (this host's index)"
        )
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_hosts,
        process_id=int(host_index),
    )
    _initialized = True
    logger.info(
        "jax.distributed initialized: host %s of %s (coordinator %s); "
        "%d local / %d global devices",
        host_index, num_hosts, coordinator,
        jax.local_device_count(), jax.device_count(),
    )
    return True
