"""Shared-memory result cache: ONE physical copy of the pool's hot set.

The private :class:`~predictionio_tpu.serving.result_cache.ResultCache`
replicates per worker what `pio deploy --workers N` should share: a key
warmed by worker A cold-starts again on workers B..N, and a `/reload`
re-warms N caches instead of one (ROADMAP item 4). This module keeps the
ResultCache *interface* — ``lookup``/``put``/``invalidate``/
``invalidate_matching``/``snapshot``/``__len__``/``generation`` — but
backs it with one ``multiprocessing.shared_memory`` segment every
worker attaches, so ``engine_server``, the online overlay's per-user
invalidation, and ``/stats.json`` compose unchanged.

Layout (one segment, fixed geometry stamped in the header)::

    [header 4096 B] [user-tag column: nslots x u64] [nslots x slot_bytes]

    header: magic u64 | version u32 | nslots u32 | slot_bytes u32 | pad
            | generation u64 | last_reload u64 | epoch u64
    slot:   seq u64 | gen_stamp u64 | key_hash u64 | inserted_at f64
            | key_len u32 | val_len u32 | crc32 u32 | pad
            | key bytes | pickled value

Concurrency is a per-slot **seqlock**, not a lock: a writer bumps the
slot ``seq`` to odd, writes payload + crc32, then bumps it even; a
reader snapshots ``seq``, copies the payload, re-reads ``seq``, and
retries (bounded, then miss) on odd-or-changed. Readers therefore
NEVER block the writer — there is no cross-process mutex to convoy on,
and a worker killed -9 mid-write leaves exactly one slot odd (a
permanent miss until overwritten), never a wedged pool. Writer-writer
collisions on a slot are *benign*, not prevented: the crc32 over the
payload rejects any interleaved result at read time (slots are
direct-mapped by key hash, so two writers on one slot are already a
cache-collision overwrite).

**Memory-ordering assumption (x86-TSO).** The protocol issues no
explicit fences — CPython has no portable store barrier — and leans on
x86's total-store-order (stores become visible in program order;
loads are not reordered with older loads) plus the crc32 backstop.
What that buys and what it does not:

- Slot payloads can never be *served* torn on any architecture: a
  reordered or interleaved view fails the seq re-read or the crc/key
  check and reads as a miss (the hammer test's zero-torn criterion).
- The epoch fence's post-publish re-check vs ``invalidate_matching``'s
  bump-then-scan is a classic store-buffer litmus (each side stores
  then loads the other's word): on a machine that lets a load hop its
  own earlier store, both sides could read the pre-store value and a
  pre-fold result could theoretically survive per-user invalidation.
  x86-TSO forbids neither side's store-load reordering being hidden
  from the OTHER core's later loads in the order stored, and in
  CPython every one of these accesses brushes the GIL's own seq-cst
  handoffs, so the window is not observable in practice; on weakly
  ordered hosts (aarch64) it is real but bounded — a stale entry
  outlives the fence only until the key's TTL or next overwrite.
  Serializing the epoch word through an OS-level atomic (fcntl byte
  lock) would close it at the cost of a syscall per put; the TTL
  bound is the deliberate trade.

Invalidation is a stamp compare, not a broadcast:

- ``generation`` (header) rides the pool's shared reload sequence. A
  slot is live only while its ``gen_stamp`` equals the header
  generation, so ``invalidate()`` — `/reload` — is ONE u64 bump that
  stales every slot at once, applied once per reload sequence
  (``last_reload`` makes each sibling's sync-loop re-apply a no-op, so
  the worker that re-warms a key right after the handling worker's
  bump leaves it HOT for the whole pool). Once-per-sequence is
  best-effort, not exactly-once: the ``last_reload`` check-then-set is
  guarded only by each process's own ``threading.Lock``, so two
  siblings applying the SAME sequence truly concurrently can both pass
  the check and double-bump — over-invalidation (re-warmed keys stale
  again), never staleness. The guarantee that matters — the common
  sequential re-apply, each sibling's sync loop firing after the
  handling worker's bump, is a no-op — holds regardless.
- ``epoch`` (header) is the put-fence token ``lookup`` hands out and
  ``put`` checks — it bumps on EVERY invalidation event, including the
  per-user kind, so an in-flight computation started before the event
  can never land after it (the private cache's stale-``put`` guard,
  now pool-wide). ``put`` re-checks the epoch AFTER publishing and
  zaps its own slot on a lost race, closing the check-then-write
  window a cross-process cache cannot lock away.
- The epoch alone cannot fence a computation started AFTER a reload
  bump on a worker that has not yet swapped its own model: that
  worker's lookup would hand out a fresh token, and its old-model
  result would publish into the NEW generation and serve pool-wide
  (the private per-worker cache never had this hole — each worker's
  swap cleared exactly its own entries). ``model_generation_fn`` —
  the engine server wires it to its live ``model_generation`` — closes
  it: while the local model trails the segment's ``last_reload``,
  ``lookup`` hands out a poisoned token and ``put`` refuses to
  publish (pre-check AND post-publish re-check, same discipline as
  the epoch fence), so pre-swap results land nowhere and the worker
  resumes publishing the moment its own swap catches it up.
- ``invalidate_matching(fragment)`` — the PR 14 per-user contract —
  reads the contiguous user-tag column (one u64 per slot: the hash of
  the ``"user":...`` fragment extracted from the canonical key at put
  time), zaps only matching slots, and leaves the generation alone:
  every other user's entries keep serving warm.

Values cross process boundaries as pickles. That is a same-host,
same-codebase trust domain (every attacher is a worker of THIS deploy,
spawned from the same binary) — do not point ``PIO_SERVING_SHM_SEGMENT``
at a segment other software writes.

TTL stamps use ``time.monotonic()`` (CLOCK_MONOTONIC), which is
system-wide per boot on Linux, so timestamps written by one worker are
comparable in another. An injected test clock is honored but only
meaningful single-process.

Everything degrades, nothing dies: a host without POSIX shared memory
(or a full /dev/shm) makes :func:`open_shm_cache` warn and return
``None``, and the engine server falls back to its private LRU.
"""

from __future__ import annotations

import hashlib
import logging
import pickle
import struct
import threading
import zlib
from typing import Any

from predictionio_tpu.api.stats import ServingStats
from predictionio_tpu.serving.result_cache import _MISS, user_fragment_of
from predictionio_tpu.utils.resilience import SYSTEM_CLOCK, Clock

logger = logging.getLogger(__name__)

_MAGIC = 0x50494F5348_4D0001          # "PIOSHM" + layout version tag
_VERSION = 1
_HEADER_SIZE = 4096

#: header field offsets (u64 unless noted)
_OFF_MAGIC = 0
_OFF_VERSION = 8                      # u32
_OFF_NSLOTS = 12                      # u32
_OFF_SLOT_BYTES = 16                  # u32
_OFF_GENERATION = 24
_OFF_LAST_RELOAD = 32
_OFF_EPOCH = 40

#: slot header: seq, gen_stamp, key_hash, inserted_at, key_len,
#: val_len, crc32 (+4 pad so payload starts 8-aligned)
_SLOT_HDR = struct.Struct("<QQQdIII4x")
SLOT_OVERHEAD = _SLOT_HDR.size

#: bounded seqlock read retries before declaring a miss — the reader
#: never waits on the writer, it just stops trying
_READ_RETRIES = 3

#: the poisoned epoch token ``lookup`` hands out while this worker's
#: model trails the pool's reload sequence: the header epoch is a u64,
#: so -1 can never equal it and the eventual ``put`` is always fenced
_STALE_TOKEN = -1


def _hash64(data: bytes) -> int:
    """Stable 64-bit key/tag hash — processes must agree, so the
    PYTHONHASHSEED-salted builtin is out."""
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=8).digest(), "little") or 1


class ShmResultCache:
    """ResultCache-compatible cache over one shared-memory segment.

    ``create='auto'`` attaches the named segment if it exists and
    creates it otherwise (two workers racing the creation resolve
    through FileExistsError -> attach); ``'create'``/``'attach'`` force
    one behavior. The creator owns the segment name: ``close()``
    unlinks only when ``owner`` (or when told explicitly), so pool
    workers detaching never destroy their siblings' cache.
    """

    def __init__(self, segment: str, nslots: int = 4096,
                 slot_bytes: int = 4096, ttl_s: float = 30.0,
                 stats: ServingStats | None = None,
                 clock: Clock = SYSTEM_CLOCK,
                 create: str = "auto"):
        from multiprocessing import shared_memory

        self.segment = segment
        self.ttl_s = ttl_s
        self.stats = stats or ServingStats()
        self._clock = clock
        #: the pool-reload put fence (module docstring): the engine
        #: server points this at its live ``model_generation`` so a
        #: worker that has not yet swapped after a sibling's /reload
        #: cannot publish old-model results into the new generation.
        #: None (bare handles, tests, single-process deploys where
        #: ``last_reload`` never moves) means no fence.
        self.model_generation_fn = None
        # serializes THIS process's threads; cross-process coordination
        # is the seqlock protocol itself (module docstring)
        self._lock = threading.Lock()
        nslots = max(8, int(nslots))
        slot_bytes = max(SLOT_OVERHEAD + 64, int(slot_bytes))
        size = _HEADER_SIZE + nslots * 8 + nslots * slot_bytes
        self.owner = False
        if create == "create":
            shm = shared_memory.SharedMemory(segment, create=True,
                                             size=size)
            self.owner = True
        elif create == "attach":
            shm = shared_memory.SharedMemory(segment)
        else:
            try:
                shm = shared_memory.SharedMemory(segment)
            except FileNotFoundError:
                try:
                    shm = shared_memory.SharedMemory(segment, create=True,
                                                     size=size)
                    self.owner = True
                except FileExistsError:   # lost the creation race
                    shm = shared_memory.SharedMemory(segment)
        self._shm = shm
        self._buf = shm.buf
        if self.owner:
            struct.pack_into("<QIII", self._buf, 0, _MAGIC, _VERSION,
                             nslots, slot_bytes)
            self.nslots, self.slot_bytes = nslots, slot_bytes
        else:
            # Python <3.13 registers ATTACHED segments with the
            # resource tracker too, which unlinks them when this
            # process exits — that would tear the pool's cache down
            # with the first worker to stop. De-register; the creator
            # (or the deploy CLI) owns cleanup.
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(shm._name, "shared_memory")
            except Exception:             # tracker drift across versions
                pass
            magic, version, got_nslots, got_slot_bytes = struct.unpack_from(
                "<QIII", self._buf, 0)
            if magic != _MAGIC or version != _VERSION:
                shm.close()
                raise ValueError(
                    f"segment {segment!r} is not a pio shm cache "
                    f"(magic {magic:#x}, version {version})")
            self.nslots, self.slot_bytes = got_nslots, got_slot_bytes
        self._tags_off = _HEADER_SIZE
        self._slots_off = _HEADER_SIZE + self.nslots * 8
        self.max_entries = self.nslots   # interface parity (snapshot)

    # ---- header words ---------------------------------------------------

    def _u64(self, off: int) -> int:
        return struct.unpack_from("<Q", self._buf, off)[0]

    def _set_u64(self, off: int, value: int) -> None:
        struct.pack_into("<Q", self._buf, off, value & (2**64 - 1))

    @property
    def generation(self) -> int:
        return self._u64(_OFF_GENERATION)

    @property
    def last_reload(self) -> int:
        """The highest pool reload sequence applied to the segment."""
        return self._u64(_OFF_LAST_RELOAD)

    def _worker_lags(self) -> bool:
        """True while THIS worker's model trails the pool's applied
        reload sequence — the window between a sibling's /reload bump
        and this worker's own model swap, when local computations are
        old-model results that must not publish (module docstring)."""
        fn = self.model_generation_fn
        return fn is not None and fn() < self._u64(_OFF_LAST_RELOAD)

    # ---- slot helpers ---------------------------------------------------

    def _slot_off(self, idx: int) -> int:
        return self._slots_off + idx * self.slot_bytes

    def _tag_off(self, idx: int) -> int:
        return self._tags_off + idx * 8

    def _zap(self, idx: int) -> None:
        """Kill one slot: bump its seq to odd (readers see
        write-in-progress forever) and clear its tag. The next put on
        the slot resumes the even/odd protocol from the bumped value."""
        off = self._slot_off(idx)
        seq = self._u64(off)
        self._set_u64(off, (seq + 1) | 1)
        self._set_u64(self._tag_off(idx), 0)

    # ---- ResultCache interface ------------------------------------------

    def get(self, key: str) -> Any:
        return self.lookup(key)[1]

    def lookup(self, key: str) -> tuple[bool, Any, int]:
        """(hit, value_or_MISS, epoch_token) — the token is the shared
        put-fence epoch, not the reload generation: callers thread it
        into :meth:`put` exactly like the private cache's triple."""
        key_b = key.encode("utf-8")
        key_hash = _hash64(key_b)
        idx = key_hash % self.nslots
        off = self._slot_off(idx)
        now = self._clock.monotonic()
        # the token must be read BEFORE the slot so it is conservative:
        # an invalidation between here and the payload copy makes the
        # eventual put stale, never fresh. A worker whose model trails
        # the pool's reload sequence gets a POISONED token: the miss it
        # is about to take would be recomputed with the OLD model, and
        # that result must never publish into the new generation (hits
        # are still served — live slots were stamped by caught-up
        # workers, so their values are new-model results)
        token = (_STALE_TOKEN if self._worker_lags()
                 else self._u64(_OFF_EPOCH))
        for _ in range(_READ_RETRIES):
            seq0 = self._u64(off)
            if seq0 & 1 or seq0 == 0:
                break                      # mid-write or never written
            (_, gen_stamp, slot_hash, inserted, key_len, val_len,
             crc) = _SLOT_HDR.unpack_from(self._buf, off)[0:7]
            if slot_hash != key_hash:
                break
            payload = bytes(self._buf[off + SLOT_OVERHEAD:
                                      off + SLOT_OVERHEAD + key_len
                                      + val_len])
            if self._u64(off) != seq0:
                continue                   # torn by a concurrent write
            if gen_stamp != self._u64(_OFF_GENERATION):
                break                      # staled by a /reload bump
            if self.ttl_s > 0 and now - inserted >= self.ttl_s:
                self.stats.bump("cache_expirations")
                break
            if zlib.crc32(payload) != crc or payload[:key_len] != key_b:
                break                      # torn write or hash collision
            try:
                value = pickle.loads(payload[key_len:])
            except Exception:
                break                      # truncated by a dying writer
            self.stats.bump("cache_hits")
            return True, value, token
        self.stats.bump("cache_misses")
        return False, _MISS, token

    def put(self, key: str, value: Any,
            generation: int | None = None) -> bool:
        """Publish; returns False (caching nothing) when the epoch
        token is stale, the value does not pickle, or the entry
        outsizes a slot."""
        key_b = key.encode("utf-8")
        try:
            val_b = pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:
            return False                   # unpicklable -> just uncached
        if SLOT_OVERHEAD + len(key_b) + len(val_b) > self.slot_bytes:
            return False                   # oversized entry: not shareable
        key_hash = _hash64(key_b)
        idx = key_hash % self.nslots
        off = self._slot_off(idx)
        tag = user_fragment_of(key)
        tag_hash = _hash64(tag.encode("utf-8")) if tag else 0
        payload = key_b + val_b
        crc = zlib.crc32(payload)
        with self._lock:
            if (generation is not None
                    and generation != self._u64(_OFF_EPOCH)):
                return False               # computed before an invalidation
            if self._worker_lags():
                # this worker's model trails the pool's reload
                # sequence: the value was computed with the OLD model
                # (also catches direct puts that never took a token)
                return False
            seq0 = self._u64(off)
            if seq0 and not seq0 & 1:
                old_hash = _SLOT_HDR.unpack_from(self._buf, off)[2]
                if old_hash != key_hash:
                    self.stats.bump("cache_evictions")
            gen_stamp = self._u64(_OFF_GENERATION)
            # seqlock publish: odd -> payload -> even. No fsync, no
            # barrier calls: x86-TSO store order plus the crc make a
            # torn read detectable, never servable.
            self._set_u64(off, (seq0 + 1) | 1)
            _SLOT_HDR.pack_into(self._buf, off, (seq0 + 1) | 1,
                                gen_stamp, key_hash,
                                self._clock.monotonic(),
                                len(key_b), len(val_b), crc)
            self._buf[off + SLOT_OVERHEAD:
                      off + SLOT_OVERHEAD + len(payload)] = payload
            self._set_u64(self._tag_off(idx), tag_hash)
            self._set_u64(off, ((seq0 + 1) | 1) + 1)
            if ((generation is not None
                    and generation != self._u64(_OFF_EPOCH))
                    or self._worker_lags()):
                # an invalidation (or a sibling's reload bump this
                # worker has not swapped for) landed between the
                # pre-check and the publish: un-publish rather than
                # serve a fenced result
                self._zap(idx)
                return False
            return True

    def invalidate(self, generation: int | None = None) -> None:
        """One header bump stales every slot (stamp compare — no
        broadcast, no slot walk). With ``generation`` (the pool's
        shared reload sequence) the bump applies ONCE per sequence:
        the segment is shared, so the handling worker's bump already
        invalidated for every sibling, and each sibling's sync-loop
        re-apply must not re-stale the keys the pool just re-warmed.
        Once is best-effort across processes — ``self._lock`` only
        serializes this process's threads, so two siblings applying
        the same sequence truly concurrently can both pass the
        ``last_reload`` check and double-bump. That over-invalidates
        (keys warmed between the bumps stale again — the safe
        direction, never staleness), and the case the no-op exists
        for — each sibling's sync loop re-applying AFTER the handling
        worker's bump — is sequential and stays a no-op. Without
        ``generation`` (single-process ``/reload``, retrieval
        reconfig) every call is its own event."""
        with self._lock:
            if generation is not None:
                if generation <= self._u64(_OFF_LAST_RELOAD):
                    return                 # this reload already applied
                self._set_u64(_OFF_LAST_RELOAD, generation)
            self._set_u64(_OFF_GENERATION, self._u64(_OFF_GENERATION) + 1)
            self._set_u64(_OFF_EPOCH, self._u64(_OFF_EPOCH) + 1)
            self.stats.bump("cache_invalidations")

    def invalidate_matching(self, fragment: str) -> int:
        """Drop the slots tagged with ``fragment``'s user tag — the
        online plane's per-fold invalidation, proportional to one
        contiguous u64 column scan + the user's own slots, pool-wide.
        The epoch bumps FIRST so a racing put either sees the bump
        (pre-check / post-publish re-check) or publishes its tag in
        time for this scan to zap it — either way the pre-fold result
        dies. Non-user fragments fall back to a full key scan (the
        generic substring contract)."""
        import numpy as np

        with self._lock:
            self._set_u64(_OFF_EPOCH, self._u64(_OFF_EPOCH) + 1)
            doomed = 0
            if fragment.startswith('"user":'):
                tag_hash = _hash64(fragment.encode("utf-8"))
                tags = np.frombuffer(
                    bytes(self._buf[self._tags_off:self._slots_off]),
                    dtype="<u8")
                for idx in np.flatnonzero(tags == tag_hash):
                    if fragment in (self._slot_key(int(idx)) or ""):
                        self._zap(int(idx))
                        doomed += 1
            else:
                for idx in range(self.nslots):
                    key = self._slot_key(idx)
                    if key is not None and fragment in key:
                        self._zap(idx)
                        doomed += 1
            if doomed:
                self.stats.bump("cache_user_invalidations", doomed)
        return doomed

    def _slot_key(self, idx: int) -> str | None:
        """The canonical key a live slot holds (crc-checked), else
        None."""
        off = self._slot_off(idx)
        seq0 = self._u64(off)
        if seq0 == 0 or seq0 & 1:
            return None
        key_len, val_len, crc = _SLOT_HDR.unpack_from(self._buf, off)[4:7]
        payload = bytes(self._buf[off + SLOT_OVERHEAD:
                                  off + SLOT_OVERHEAD + key_len + val_len])
        if self._u64(off) != seq0 or zlib.crc32(payload) != crc:
            return None
        try:
            return payload[:key_len].decode("utf-8")
        except UnicodeDecodeError:
            return None

    def __len__(self) -> int:
        now = self._clock.monotonic()
        gen = self._u64(_OFF_GENERATION)
        live = 0
        for idx in range(self.nslots):
            off = self._slot_off(idx)
            seq = self._u64(off)
            if seq == 0 or seq & 1:
                continue
            gen_stamp, _, inserted = _SLOT_HDR.unpack_from(
                self._buf, off)[1:4]
            if gen_stamp != gen:
                continue
            if self.ttl_s > 0 and now - inserted >= self.ttl_s:
                continue
            live += 1
        return live

    def snapshot(self) -> dict:
        return {
            "size": len(self),
            "maxEntries": self.nslots,
            "ttlS": self.ttl_s,
            "generation": self.generation,
            "backend": "shm",
            "segment": self.segment,
            "slotBytes": self.slot_bytes,
        }

    # ---- lifecycle -------------------------------------------------------

    def close(self, unlink: bool | None = None) -> None:
        """Detach; unlink iff this handle created the segment (or the
        caller says so — the deploy CLI owns the pool's segment)."""
        do_unlink = self.owner if unlink is None else unlink
        try:
            self._buf.release()
        except Exception:
            pass
        try:
            self._shm.close()
        except Exception:
            pass
        if do_unlink:
            try:
                # an attach handle in THIS process (the deploy parent
                # is both segment owner and worker 0) already
                # de-registered the name; re-register so unlink()'s
                # own de-registration balances instead of KeyError-ing
                # in the tracker process
                from multiprocessing import resource_tracker

                resource_tracker.register(self._shm._name,
                                          "shared_memory")
            except Exception:
                pass
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass                       # a sibling already unlinked
            except Exception:
                logger.warning("shm segment %s unlink failed",
                               self.segment, exc_info=True)


def open_shm_cache(config: Any,
                   stats: ServingStats | None = None
                   ) -> ShmResultCache | None:
    """The engine server's entry: an attached/created cache per the
    ``PIO_SERVING_SHM_*`` config, or ``None`` with a warning when the
    platform can't (no /dev/shm, exhausted shm, bad segment) — the
    caller falls back to the private LRU, degrade-don't-die."""
    import os

    segment = config.shm_segment or f"pio-shm-{os.getpid()}"
    try:
        return ShmResultCache(
            segment, nslots=config.shm_slots,
            slot_bytes=config.shm_slot_bytes,
            ttl_s=config.cache_ttl_s, stats=stats)
    except Exception as exc:
        logger.warning(
            "shared-memory result cache unavailable (%s: %s); "
            "falling back to the private in-process LRU",
            type(exc).__name__, exc)
        return None
