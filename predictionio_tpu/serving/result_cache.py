"""Device-result cache for the serving query path.

An LRU + TTL map from the **canonical query JSON** (core/json_codec.
canonical_json over the BOUND query's wire form — key order,
whitespace, and camelCase/snake_case spellings all normalized, so two
clients spelling the same query differently share an entry) to the
served prediction. A hit answers without touching the device at all; misses
flow through the batcher, whose per-batch dedup pass covers the
concurrent-identical case the cache can't (both in flight at once).

Invalidation is generational: ``invalidate()`` (called by a successful
``/reload`` after the model swap) clears the map AND bumps a generation
counter; ``put()`` carries the generation its caller observed before
computing, so a prediction computed against the old model can never be
cached into the new model's generation — the check and insert are one
atomic step under the cache lock. A FAILED reload calls nothing: the
last-known-good model keeps its warm cache (operations-resilience
semantics).

Counters live in :class:`~predictionio_tpu.api.stats.ServingStats`
(hit/miss/eviction/expiration/invalidation) for ``GET /stats.json``.
The clock is injectable for TTL tests on virtual time.
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict
from typing import Any

from predictionio_tpu.api.stats import ServingStats
from predictionio_tpu.utils.resilience import SYSTEM_CLOCK, Clock

#: sentinel distinguishing "miss" from a cached None prediction
_MISS = object()


def user_fragment_of(key: str) -> str | None:
    """The ``"user":...`` canonical fragment a cache key carries, or
    None for keys without a top-level user (non-JSON test keys, engines
    whose queries aren't user-addressed). Derived through
    ``canonical_json`` itself — the same construction as
    ``online/service.user_key_fragment`` — so the index below and the
    online plane's invalidation fragments can never drift apart."""
    try:
        doc = json.loads(key)
    except ValueError:
        return None
    if not isinstance(doc, dict) or "user" not in doc:
        return None
    from predictionio_tpu.core.json_codec import canonical_json

    return canonical_json({"user": doc["user"]})[1:-1]


class ResultCache:
    """Thread-safe LRU+TTL keyed by canonical query JSON."""

    def __init__(self, max_entries: int = 4096, ttl_s: float = 30.0,
                 stats: ServingStats | None = None,
                 clock: Clock = SYSTEM_CLOCK):
        self.max_entries = max(1, int(max_entries))
        self.ttl_s = ttl_s
        self.stats = stats or ServingStats()
        self._clock = clock
        self._lock = threading.Lock()
        #: key -> (inserted_at, value); insertion/access order = LRU
        self._entries: "OrderedDict[str, tuple[float, Any]]" = OrderedDict()
        self._generation = 0
        #: user-fragment -> keys index so the online plane's per-fold
        #: ``invalidate_matching`` costs the USER's entries, not a full
        #: key scan (the shm cache keeps the same index as a tag
        #: column); ``_key_tag`` is the reverse map the deletion paths
        #: (evict/expire/invalidate) use to keep the index exact
        self._tag_keys: dict[str, set[str]] = {}
        self._key_tag: dict[str, str] = {}

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def get(self, key: str) -> Any:
        """The cached value, or the module sentinel ``_MISS``. Use
        :meth:`lookup` for a (hit, value, generation) triple."""
        return self.lookup(key)[1]

    def lookup(self, key: str) -> tuple[bool, Any, int]:
        """(hit, value_or_MISS, generation_observed) — callers thread the
        generation into :meth:`put` so a result computed before a reload
        cannot poison the post-reload cache."""
        now = self._clock.monotonic()
        with self._lock:
            gen = self._generation
            entry = self._entries.get(key)
            if entry is None:
                self.stats.bump("cache_misses")
                return False, _MISS, gen
            inserted, value = entry
            if self.ttl_s > 0 and now - inserted >= self.ttl_s:
                del self._entries[key]
                self._forget(key)
                self.stats.bump("cache_expirations")
                self.stats.bump("cache_misses")
                return False, _MISS, gen
            self._entries.move_to_end(key)
            self.stats.bump("cache_hits")
            return True, value, gen

    def put(self, key: str, value: Any, generation: int | None = None) -> bool:
        """Insert; returns False (and caches nothing) when ``generation``
        is stale — the computation started before an invalidation."""
        now = self._clock.monotonic()
        with self._lock:
            if generation is not None and generation != self._generation:
                return False
            self._entries[key] = (now, value)
            self._entries.move_to_end(key)
            if key not in self._key_tag:
                tag = user_fragment_of(key)
                if tag is not None:
                    self._key_tag[key] = tag
                    self._tag_keys.setdefault(tag, set()).add(key)
            while len(self._entries) > self.max_entries:
                evicted, _ = self._entries.popitem(last=False)
                self._forget(evicted)
                self.stats.bump("cache_evictions")
            return True

    def _forget(self, key: str) -> None:
        """Drop ``key`` from the user index (caller already removed the
        entry, under the cache lock)."""
        tag = self._key_tag.pop(key, None)
        if tag is not None:
            keys = self._tag_keys.get(tag)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self._tag_keys[tag]

    def invalidate(self, generation: int | None = None) -> None:
        """Atomically drop everything and start a new generation.

        ``generation`` pins the NEW generation number explicitly — the
        ``pio deploy --workers N`` coherence path passes the fleet's
        shared reload sequence so every sibling's private cache lands
        on the SAME generation after a ``/reload``, making the
        per-worker generations comparable across the pool
        (docs/serving-performance.md "Multi-process serving"). It only
        ever moves the counter FORWARD: a lagging sibling applying an
        old document cannot rewind a newer local generation (the stale
        ``put()`` guard depends on generations never repeating)."""
        with self._lock:
            self._entries.clear()
            self._tag_keys.clear()
            self._key_tag.clear()
            if generation is not None:
                self._generation = max(self._generation + 1, generation)
            else:
                self._generation += 1
            self.stats.bump("cache_invalidations")

    def invalidate_matching(self, fragment: str) -> int:
        """Drop only the entries whose canonical key contains
        ``fragment`` — the online freshness plane's TARGETED
        invalidation (online/service.user_key_fragment): when one
        user's vector is re-folded, that user's cached predictions die
        and everyone else's stay warm (entries are NOT cleared
        pool-wide the way a ``/reload`` clears them). The generation
        still advances: a query for the SAME user already in flight
        when the fold landed would otherwise ``put()`` its pre-fold
        result right back (the stale-generation guard protects only
        puts, so every OTHER user's existing entries keep serving —
        the in-flight computations across the bump merely become
        uncacheable, the small price of correctness).

        User fragments (``"user":...`` — the only kind the online
        plane sends) resolve through the put-time user index, so the
        cost is proportional to THAT user's entries instead of an
        O(entries) key scan; any other fragment keeps the generic
        full-scan substring contract. (A user fragment cannot hide
        inside a string value — canonical JSON escapes the quotes — so
        for the flat wire queries the templates serve, index equality
        and substring match select the same keys.)"""
        with self._lock:
            if fragment.startswith('"user":'):
                doomed = list(self._tag_keys.get(fragment, ()))
            else:
                doomed = [k for k in self._entries if fragment in k]
            for k in doomed:
                del self._entries[k]
                self._forget(k)
            # unconditional: the racing in-flight query may not have an
            # entry to doom YET — its put is the thing being fenced
            self._generation += 1
            if doomed:
                self.stats.bump("cache_user_invalidations", len(doomed))
        return len(doomed)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> dict:
        with self._lock:
            size, gen = len(self._entries), self._generation
        return {
            "size": size,
            "maxEntries": self.max_entries,
            "ttlS": self.ttl_s,
            "generation": gen,
        }
