"""Cross-worker coherence for the prefork engine-serving pool
(``pio deploy --workers N``; docs/serving-performance.md
"Multi-process serving").

N worker processes share one SO_REUSEPORT listen port, each with its
own model replica, batcher, cache, and metric registry. The kernel
spreads connections across them, which makes every *admin* request a
1/N lottery: a ``POST /reload`` lands on ONE worker and the other N-1
keep serving the old model — and the old cache generation — forever.

This module rides the PR 7/9 worker-spool machinery
(:class:`~predictionio_tpu.fleet.workers.WorkerHub`) to make admin
state **eventually coherent across the pool** without a coordinator:

- the spool's ``admin.state`` document holds a CUMULATIVE state, not
  an action log: ``{"seq": N, "reloadSeq": R, "draining": bool,
  "retrieval": {...}|null}``. Cumulative means a respawned worker
  adopts the WHOLE current state from one read at init — it does not
  need to replay a history it never saw;
- a mutation (``/reload`` succeeded, ``/drain`` latched, retrieval
  reconfigured) merges its change into the current document and
  publishes with the next sequence number (atomic ``os.replace``
  through the hub);
- every sibling's sync loop applies documents with ``seq`` greater
  than what it last applied, by DELTA against its last-applied state:
  ``reloadSeq`` advanced → reload (adopting the sequence number as the
  result-cache generation, so all private caches land on the SAME
  generation — coherence is generational, the caches themselves stay
  per-worker); ``draining`` flipped → flip the local latch;
  ``retrieval`` changed → reconfigure the local models.

Concurrent publishers race last-writer-wins on the ``os.replace``
(admin mutations are rare, human-speed events — the WorkerHub
contract); the merge-before-publish read preserves a sibling's earlier
mutation in the published document, and :meth:`WorkerCoherence.publish`
fires the apply callback for any sibling delta it carried forward, so
a pending sibling change is never silently marked applied.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable

from predictionio_tpu.fleet.workers import WorkerHub

logger = logging.getLogger(__name__)

#: the cumulative admin-state schema (module docstring); ``seq`` and
#: ``publishedBy`` are stamped by WorkerHub.publish_admin
DEFAULT_STATE = {"reloadSeq": 0, "draining": False, "retrieval": None}


def _normalize(doc: dict | None) -> dict:
    """The cumulative state fields of ``doc`` with schema defaults for
    anything missing/malformed — a junk document degrades to defaults
    instead of wedging the sync loop."""
    out = dict(DEFAULT_STATE)
    if not isinstance(doc, dict):
        return out
    if isinstance(doc.get("reloadSeq"), int) and doc["reloadSeq"] >= 0:
        out["reloadSeq"] = doc["reloadSeq"]
    if isinstance(doc.get("draining"), bool):
        out["draining"] = doc["draining"]
    if isinstance(doc.get("retrieval"), dict) or doc.get("retrieval") is None:
        out["retrieval"] = doc.get("retrieval")
    return out


class WorkerCoherence:
    """One worker's view of the shared admin state: publish mutations,
    apply siblings' (module docstring).

    ``on_state(new, prev)`` is the apply callback — the engine service
    compares the two cumulative states and performs whatever changed
    (reload / drain latch / retrieval reconfig). It runs on the sync
    thread or the publishing handler thread, never under this object's
    lock, and must tolerate being called concurrently with overlapping
    deltas (the service's reload path already does — concurrent HTTP
    ``/reload`` calls were always possible)."""

    def __init__(self, hub: WorkerHub,
                 on_state: Callable[[dict, dict], None],
                 interval_s: float = 0.5):
        self.hub = hub
        self._on_state = on_state
        self._interval_s = interval_s
        self._lock = threading.Lock()
        self._seq = 0
        self._state = dict(DEFAULT_STATE)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- adoption at (re)spawn ------------------------------------------------
    def adopt(self) -> dict:
        """Read the current document and mark it applied WITHOUT firing
        the callback — the caller decides what a fresh boot needs (a
        respawned worker already loaded the latest completed instance,
        so it adopts ``reloadSeq`` as history rather than reloading;
        the drain latch and retrieval config it applies itself).
        Returns the adopted cumulative state."""
        doc = self.hub.read_admin()
        with self._lock:
            if doc is not None:
                self._seq = doc["seq"]
                self._state = _normalize(doc)
            return dict(self._state)

    def state(self) -> dict:
        with self._lock:
            return dict(self._state)

    def next_reload_seq(self) -> int:
        """The reload sequence a /reload happening NOW should commit
        as: one past the latest the spool or this worker has seen (the
        spool may be ahead of the local state when a sibling's publish
        has not been synced yet)."""
        doc = _normalize(self.hub.read_admin())
        with self._lock:
            return max(doc["reloadSeq"], self._state["reloadSeq"]) + 1

    # -- publish --------------------------------------------------------------
    def publish(self, **changes) -> dict:
        """Merge ``changes`` into the current spool document, publish
        with the next sequence number, and mark the result applied.
        The published document may carry a sibling mutation this worker
        has not applied yet (its sync loop simply had not run); those
        deltas fire the apply callback here so carrying them forward
        never swallows them. The caller has already performed its OWN
        change before publishing — a failed local mutation must not be
        announced to the pool."""
        with self._lock:
            current = _normalize(self.hub.read_admin())
            prev = self._state
            merged = {**current, **changes}
            try:
                seq = self.hub.publish_admin(merged)
            except OSError:
                logger.exception("publishing serving admin state failed")
                return dict(prev)
            self._seq = max(self._seq, seq)
            self._state = merged
            # sibling deltas the merge carried forward: everything that
            # differs between our last-applied state and the published
            # document EXCEPT the change we just made ourselves
            already = {**prev, **changes}
        if merged != already:
            self._on_state(dict(merged), dict(already))
        return dict(merged)

    # -- sync -----------------------------------------------------------------
    def sync_once(self) -> bool:
        """Apply the spool document when its sequence advanced past
        what this worker last applied; returns True when a delta was
        handed to the callback."""
        doc = self.hub.read_admin()
        if doc is None:
            return False
        with self._lock:
            if doc["seq"] <= self._seq:
                return False
            self._seq = doc["seq"]
            prev = self._state
            self._state = _normalize(doc)
            new = self._state
        self._on_state(dict(new), dict(prev))
        return True

    def _run(self) -> None:
        # Event.wait doubles as interval sleep and prompt stop — the
        # membership-loop idiom, never a bare time.sleep
        while not self._stop.wait(self._interval_s):
            try:
                self.sync_once()
            except Exception:  # noqa: BLE001 — a torn read is the next pass's problem
                logger.exception("serving admin-state sync failed")

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="pio-serving-admin-sync", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
