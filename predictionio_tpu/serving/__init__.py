"""The serving hot-path subsystem: micro-batching policy, query
batcher, and the device-result cache.

Grown out of the single ``QueryBatcher`` class that used to live in
``workflow/deploy.py`` (PR 1's fixed 5 ms window): the batcher now
composes a load-aware :mod:`batch_policy`, a per-batch dedup pass, and
an optional :mod:`result_cache`, with its counters surfaced through
``api/stats.py`` on the engine server's ``GET /stats.json``.
"""

from predictionio_tpu.serving.batch_policy import (  # noqa: F401
    AdaptiveBatchPolicy,
    BatchPolicy,
    FixedBatchPolicy,
    make_batch_policy,
)
from predictionio_tpu.serving.batcher import (  # noqa: F401
    QueryBatcher,
    QueryDeadlineExceeded,
)
from predictionio_tpu.serving.result_cache import ResultCache  # noqa: F401
