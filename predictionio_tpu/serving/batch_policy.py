"""Load-aware micro-batching policy for the serving query batcher.

The batcher's original fixed ``batch_wait_ms`` window (PR 1) charged
every lone query the full wait for nothing and still under-coalesced
under load. The adaptive policy replaces the constant with a decision
per batch, driven by an EWMA of query inter-arrival time:

- **idle** (arrivals further apart than the max wait): waiting would
  buy no companions — dispatch immediately, near-zero added latency;
- **loaded** (arrivals dense): wait just long enough for the expected
  arrivals to fill the target batch, capped at ``max_wait_ms``.

Target batch sizes snap to the power-of-two jit-signature menu shared
with the templates' ``batch_predict`` padding (``ops/topk.BATCH_WIDTHS``)
so an adaptive target can never mint a batch shape outside the
compiled-program cache — adaptivity must not cause retraces.

The clock is injectable (:class:`~predictionio_tpu.utils.resilience.Clock`,
the same pattern as ``CircuitBreaker``) so the policy unit-tests run on
virtual time.
"""

from __future__ import annotations

import threading

from predictionio_tpu.ops.topk import BATCH_WIDTHS, serving_batch
from predictionio_tpu.utils.resilience import SYSTEM_CLOCK, Clock


class BatchPolicy:
    """One decision point per batch: how long to wait, how many to take.

    ``observe_arrival()`` is called by every handler thread at submit
    time; ``plan()`` is called by the dispatcher after it pops a
    batch's first query. Both are lock-guarded — arrivals come from
    many handler threads concurrently.
    """

    def __init__(self, batch_max: int = 64, clock: Clock = SYSTEM_CLOCK,
                 ewma_alpha: float = 0.2):
        # same clamp as the batcher: the templates' batch menu tops out
        # at BATCH_WIDTHS[-1]; beyond it every size is a fresh signature
        self.batch_max = max(1, min(int(batch_max), BATCH_WIDTHS[-1]))
        self._clock = clock
        self._alpha = min(max(ewma_alpha, 0.01), 1.0)
        self._lock = threading.Lock()
        self._last_arrival: float | None = None
        self._ewma_s: float | None = None
        self._last_wait_s = 0.0
        self._last_target = self.batch_max

    def observe_arrival(self) -> None:
        now = self._clock.monotonic()
        with self._lock:
            if self._last_arrival is not None:
                dt = max(0.0, now - self._last_arrival)
                self._ewma_s = (dt if self._ewma_s is None
                                else (1 - self._alpha) * self._ewma_s
                                + self._alpha * dt)
            self._last_arrival = now

    def ewma_interarrival_s(self) -> float | None:
        with self._lock:
            return self._ewma_s

    def plan(self, inflight: int | None = None) -> tuple[float, int]:
        """(wait_seconds, target_batch_size) for the batch being formed.

        ``inflight`` is the number of callers currently blocked in
        ``submit`` (None = unknown): with one in-flight caller no
        companion can possibly arrive during a wait — every other
        client is either absent or already queued — so an adaptive
        policy must not hold the door."""
        raise NotImplementedError

    def _record_plan(self, wait_s: float, target: int) -> None:
        with self._lock:
            self._last_wait_s = wait_s
            self._last_target = target

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "policy": type(self).__name__,
                "batchMax": self.batch_max,
                "ewmaInterarrivalMs": (
                    round(self._ewma_s * 1e3, 4)
                    if self._ewma_s is not None else None),
                "lastWaitMs": round(self._last_wait_s * 1e3, 4),
                "lastTargetBatch": self._last_target,
            }


class FixedBatchPolicy(BatchPolicy):
    """The legacy behavior: a constant wait window, always aiming for a
    full batch. Selected with ``ServerConfig.batch_policy="fixed"``;
    ``batch_max=1`` degenerates to strict per-query dispatch (the
    reference's one-predict-per-request model, used as the benchmark
    baseline)."""

    def __init__(self, batch_max: int = 64, wait_ms: float = 5.0,
                 clock: Clock = SYSTEM_CLOCK):
        super().__init__(batch_max=batch_max, clock=clock)
        self._wait_s = max(0.0, wait_ms) / 1e3

    def plan(self, inflight: int | None = None) -> tuple[float, int]:
        self._record_plan(self._wait_s, self.batch_max)
        return self._wait_s, self.batch_max


class AdaptiveBatchPolicy(BatchPolicy):
    """EWMA-driven wait: expect ``max_wait / ewma`` arrivals in the
    window, target the smallest menu size covering them, and wait only
    as long as filling that target should take.

    With no arrival history (cold start) or a stale/slow EWMA the
    policy chooses zero wait — a lone query after an idle stretch pays
    (near) nothing. ``min_wait_ms`` exists for deployments whose
    arrivals are bursty beyond what the EWMA can see (default 0)."""

    def __init__(self, batch_max: int = 64, max_wait_ms: float = 5.0,
                 min_wait_ms: float = 0.0, clock: Clock = SYSTEM_CLOCK,
                 ewma_alpha: float = 0.2):
        super().__init__(batch_max=batch_max, clock=clock,
                         ewma_alpha=ewma_alpha)
        self._max_wait_s = max(0.0, max_wait_ms) / 1e3
        self._min_wait_s = min(max(0.0, min_wait_ms) / 1e3, self._max_wait_s)

    def plan(self, inflight: int | None = None) -> tuple[float, int]:
        if inflight is not None and inflight <= 1:
            # a lone in-flight caller (single closed-loop client): no
            # companion can arrive while it blocks — the EWMA may look
            # "loaded" (its own steady spacing) but waiting would
            # charge that one client the window for nothing
            self._record_plan(self._min_wait_s, 1)
            return self._min_wait_s, 1
        with self._lock:
            ewma = self._ewma_s
        if ewma is None or self._max_wait_s <= 0.0:
            # cold start: no evidence any companion is coming
            self._record_plan(self._min_wait_s, self.batch_max)
            return self._min_wait_s, self.batch_max
        if ewma >= self._max_wait_s:
            # idle: the next arrival is (in expectation) beyond the
            # longest wait we may charge — dispatch now
            self._record_plan(self._min_wait_s, 1)
            return self._min_wait_s, 1
        # loaded: arrivals expected inside the window (incl. the one
        # already in hand), snapped UP to the jit-signature menu so the
        # dispatched size is one batch_predict already compiled for
        expected = 1 + int(self._max_wait_s / max(ewma, 1e-9))
        target = min(serving_batch(expected), self.batch_max)
        wait = min(max(ewma * (target - 1), self._min_wait_s),
                   self._max_wait_s)
        self._record_plan(wait, target)
        return wait, target


def make_batch_policy(name: str, batch_max: int, wait_ms: float,
                      clock: Clock = SYSTEM_CLOCK) -> BatchPolicy:
    """Policy factory for ``ServerConfig.batch_policy``: "adaptive"
    (wait_ms is the cap) or "fixed" (wait_ms is the constant window)."""
    if name == "fixed":
        return FixedBatchPolicy(batch_max=batch_max, wait_ms=wait_ms,
                                clock=clock)
    if name == "adaptive":
        return AdaptiveBatchPolicy(batch_max=batch_max, max_wait_ms=wait_ms,
                                   clock=clock)
    raise ValueError(
        f"unknown batch_policy {name!r} (expected 'adaptive' or 'fixed')")
