"""The query micro-batcher: coalesces concurrent queries into one
device dispatch — the TPU-first serving feature a per-query dispatch
model can't offer (beyond reference; the reference's spray actor served
queries strictly one predict per request, CreateServer.scala:495-497).

Handler threads ``submit()`` and block on a future; one dispatcher
thread drains the queue. After a batch's first query arrives the
configured :class:`~predictionio_tpu.serving.batch_policy.BatchPolicy`
decides how long to wait for companions and how many to take (the
adaptive policy waits near-zero when idle, coalesces under load; the
fixed policy is the legacy constant window), then the whole batch runs
through ``DeployedEngine.query_batch``.

Hot-path guarantees, each carried by a counter in
:class:`~predictionio_tpu.api.stats.ServingStats`:

- queries whose resilience deadline already expired are FAILED at
  dequeue time (``QueryDeadlineExceeded`` → the server's 503) instead
  of being scored and discarded — a timed-out client must not consume
  a device slot;
- identical concurrent queries (same canonical-JSON key) dedup to ONE
  slot in the dispatched batch, every waiter sharing the result;
- a failing batch is retried query-by-query so one poisoned query 500s
  alone, skipping entries whose deadline expired during the batch
  attempt.

``get_deployed`` is read fresh per batch, so /reload hot-swaps apply
from the next batch on.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Any, NamedTuple

from predictionio_tpu.api.stats import ServingStats
from predictionio_tpu.serving.batch_policy import BatchPolicy, FixedBatchPolicy
from predictionio_tpu.utils.resilience import (
    deadline_scope,
    record_fallback,
    remaining_deadline,
)

logger = logging.getLogger(__name__)


class QueryDeadlineExceeded(RuntimeError):
    """A query's time budget expired while WAITING for its result — as
    distinct from the work itself raising TimeoutError (which, on
    Python 3.11+, is the same class as concurrent.futures.TimeoutError
    and must not be misreported as a blown deadline)."""

    def __init__(self, budget: float):
        super().__init__(f"query deadline exceeded ({budget:.3f}s budget)")
        self.budget = budget


class _Pending(NamedTuple):
    query: Any
    fut: Future
    #: absolute monotonic deadline (None = unbounded)
    deadline: float | None
    #: the budget that produced the deadline, for error messages
    budget: float | None
    #: canonical dedup key (None = never deduped)
    key: str | None
    #: perf_counter at enqueue — the queue-wait component of serving
    #: latency is measured from here to the dispatch (obs histograms)
    t_enq: float
    #: the caller's trace, carried EXPLICITLY across the thread handoff
    #: (contextvars do not follow queue entries); None when tracing is
    #: off — the dispatcher's whole tracing cost is this None check
    trace: Any = None


class QueryBatcher:
    """Policy-driven coalescing dispatcher (module docstring)."""

    def __init__(self, get_deployed, policy: BatchPolicy | None = None,
                 stats: ServingStats | None = None, batch_max: int = 64,
                 batch_wait_ms: float = 5.0):
        import queue as _queue

        self._get_deployed = get_deployed
        # legacy ctor shape (batch_max/batch_wait_ms) builds the fixed
        # policy PR 1 shipped with
        self._policy = policy or FixedBatchPolicy(
            batch_max=batch_max, wait_ms=batch_wait_ms)
        self.stats = stats or ServingStats()
        self._queue: "_queue.Queue" = _queue.Queue()
        self._stopped = False
        # callers currently blocked in submit — the closed-loop load
        # signal the policy uses to avoid holding the door for
        # companions that cannot exist (BatchPolicy.plan docstring)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._run, name="pio-query-batcher", daemon=True)
        self._thread.start()

    @property
    def policy(self) -> BatchPolicy:
        return self._policy

    # counters kept as read-only views for the status page (the writers
    # live in ServingStats, lock-guarded at both ends)
    @property
    def batches(self) -> int:
        return self.stats.count("dispatches")

    @property
    def batched_queries(self) -> int:
        return self.stats.count("batched_queries")

    def submit(self, query: Any, timeout: float = 300.0,
               key: str | None = None, trace: Any = None) -> Any:
        """Enqueue and wait; raises whatever the predict path raised.

        The caller's ambient resilience deadline (deadline_scope) rides
        along into the dispatcher thread — contextvars do not cross
        threads, so the remaining budget is captured here and re-entered
        around the batch dispatch and any per-query fallbacks. A budget
        that is ALREADY exhausted fails here, before the queue. The
        caller's ``trace`` (obs/trace.py) rides the queue entry the
        same way: the dispatcher records this query's queue-wait and
        device-dispatch spans onto it."""
        if self._stopped:
            raise RuntimeError("query batcher is stopped")
        rem = remaining_deadline()
        if rem is not None and rem <= 0:
            self.stats.bump("expired")
            raise QueryDeadlineExceeded(max(rem, 0.0))
        with self._inflight_lock:
            self._inflight += 1
        try:
            self._policy.observe_arrival()
            deadline = time.monotonic() + rem if rem is not None else None
            fut: Future = Future()
            self._queue.put(_Pending(query, fut, deadline, rem, key,
                                     time.perf_counter(), trace))
            if self._stopped and not fut.done():
                # close() raced the enqueue: the dispatcher (or close's
                # drain) may never see this entry — fail fast instead of
                # letting the handler hang out the timeout (done() guards
                # the benign double-completion race)
                try:
                    fut.set_exception(
                        RuntimeError("query batcher is stopped"))
                except Exception:
                    pass
            try:
                return fut.result(timeout=timeout)
            except FuturesTimeoutError:
                if not fut.done():
                    # the WAIT expired (a blown budget) — not an
                    # exception from the predict path, which fut.done()
                    # distinguishes even on 3.11 where the two classes
                    # are aliased
                    raise QueryDeadlineExceeded(timeout) from None
                raise
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    def close(self) -> None:
        self._stopped = True
        self._queue.put(None)
        self._thread.join(timeout=5)
        self._fail_pending()

    def _fail_pending(self) -> None:
        """Fail anything still queued after the dispatcher exited —
        a blocked submit must get its 500 now, not at timeout."""
        import queue as _queue

        while True:
            try:
                item = self._queue.get_nowait()
            except _queue.Empty:
                return
            if item is None:
                continue
            if not item.fut.done():
                try:
                    item.fut.set_exception(
                        RuntimeError("query batcher is stopped"))
                except Exception:
                    pass

    # -- dispatcher ---------------------------------------------------------
    def _run(self) -> None:
        import queue as _queue

        while True:
            item = self._queue.get()
            if item is None:
                return
            batch = [item]
            # the policy decides how long to hold the door for FUTURE
            # arrivals and how many to wait for (snapped to the
            # jit-signature menu); queries that ALREADY queued while
            # the previous batch dispatched always ride along for free
            # (up to the menu cap) — under closed-loop load the queue
            # depth, not the inter-arrival EWMA, carries the signal
            # (blocked clients space their arrivals out exactly when
            # coalescing pays most)
            with self._inflight_lock:
                inflight = self._inflight
            wait_s, target = self._policy.plan(inflight=inflight)
            stop = False
            while len(batch) < self._policy.batch_max:
                try:
                    nxt = self._queue.get_nowait()
                except _queue.Empty:
                    break
                if nxt is None:
                    stop = True
                    break
                batch.append(nxt)
            if not stop:
                deadline = time.perf_counter() + wait_s
                while len(batch) < target:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    try:
                        nxt = self._queue.get(timeout=remaining)
                    except _queue.Empty:
                        break
                    if nxt is None:
                        stop = True
                        break
                    batch.append(nxt)
            self._finish(batch)
            if stop:
                return

    @staticmethod
    def _scope(deadline_abs: float | None):
        """Re-enter a caller's deadline (absolute monotonic) on the
        dispatcher thread; nested scopes only ever shrink."""
        if deadline_abs is None:
            return contextlib.nullcontext()
        return deadline_scope(max(0.0, deadline_abs - time.monotonic()))

    def _expire(self, entry: _Pending) -> None:
        self.stats.bump("expired")
        if not entry.fut.done():
            try:
                entry.fut.set_exception(QueryDeadlineExceeded(
                    entry.budget if entry.budget is not None else 0.0))
            except Exception:
                pass

    def _finish(self, batch: list[_Pending]) -> None:
        # 1. fail anything already past its deadline — dispatching it
        # would burn a device slot on a client that stopped waiting
        now = time.monotonic()
        live: list[_Pending] = []
        for entry in batch:
            if entry.deadline is not None and now >= entry.deadline:
                self._expire(entry)
            else:
                live.append(entry)
        if not live:
            return
        # 2. dedup identical concurrent queries (same canonical key):
        # one device slot, every waiter shares the result
        groups: list[list[_Pending]] = []
        by_key: dict[str, int] = {}
        for entry in live:
            if entry.key is not None and entry.key in by_key:
                groups[by_key[entry.key]].append(entry)
            else:
                if entry.key is not None:
                    by_key[entry.key] = len(groups)
                groups.append([entry])
        deployed = self._get_deployed()
        deadlines = [e.deadline for e in live if e.deadline is not None]
        try:
            # the batch shares one dispatch: honor its tightest deadline
            t0 = time.perf_counter()
            # queue-wait attribution (enqueue -> dispatch start): one
            # lock acquisition for the whole batch's samples, plus the
            # per-entry trace spans when tracing rode along
            self.stats.observe_queue_waits([t0 - e.t_enq for e in live])
            for e in live:
                if e.trace is not None:
                    e.trace.add_span("batcher.queue_wait", e.t_enq, t0)
            with self._scope(min(deadlines) if deadlines else None):
                results = deployed.query_batch([g[0].query for g in groups])
            dt = time.perf_counter() - t0
            self.stats.observe_device_time(dt)
            for e in live:
                if e.trace is not None:
                    e.trace.add_span("batcher.device_dispatch", t0, t0 + dt)
            # query_batch records request bookkeeping only for the
            # group leaders it saw; the deduped waiters were answered
            # by the same dispatch and must count as served requests
            # too (same invariant the server applies to cache hits)
            for _ in range(len(live) - len(groups)):
                deployed.record_served(dt)
            for group, served in zip(groups, results):
                for entry in group:
                    if not entry.fut.done():
                        try:
                            entry.fut.set_result(served)
                        except Exception:
                            pass
            self.stats.record_batch(len(groups), len(live))
        except Exception:
            logger.exception(
                "batched predict failed; retrying %d quer(ies) individually",
                len(groups))
            record_fallback("serving/query-batcher")
            for group in groups:
                self._fallback_group(group)

    _UNSET = object()

    def _fallback_group(self, group: list[_Pending]) -> None:
        """Per-query retry of one dedup group after a failed batch: one
        predict shared by the group's waiters; entries whose deadline
        expired during the batch attempt are failed, not retried."""
        outcome: Any = self._UNSET
        err: Exception | None = None
        for entry in group:
            if entry.fut.done():
                continue
            if entry.deadline is not None and time.monotonic() >= entry.deadline:
                self._expire(entry)
                continue
            if outcome is self._UNSET and err is None:
                t0 = time.perf_counter()
                try:
                    # re-resolve per query: a /reload mid-batch must not
                    # pin the whole fallback pass to the dead instance
                    # the batch dispatch captured
                    with self._scope(entry.deadline):
                        outcome = self._get_deployed().query(entry.query)
                except Exception as e:          # noqa: BLE001
                    err = e
                if entry.trace is not None:
                    entry.trace.add_span("batcher.fallback_predict", t0,
                                         time.perf_counter())
            try:
                if err is not None:
                    entry.fut.set_exception(err)
                else:
                    entry.fut.set_result(outcome)
            except Exception:
                pass
