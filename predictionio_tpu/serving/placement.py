"""Best-effort NUMA/CPU-affinity worker placement for the prefork pool.

`pio deploy --workers N` leaves the kernel free to bounce N engine
processes across cores; on big hosts that costs cache locality (each
worker's model pages, batcher state, and shm-cache slots keep migrating
between L2/LLC domains) and, on multi-socket machines, cross-NUMA
traffic against the mmap'd factor tables. Pinning each worker to a
contiguous stripe of the allowed CPU list keeps a worker's working set
on one cache/NUMA domain — contiguous CPU ids are the portable proxy
for "same socket" without parsing sysfs topology.

Everything here is best-effort by contract: a 1-core container, a
host with fewer allowed CPUs than workers, a platform without
``sched_setaffinity`` (macOS), or a denied syscall all return ``None``
and change nothing — placement is an optimization, never a boot
requirement (degrade-don't-die, the knob discipline every serving
feature follows).
"""

from __future__ import annotations

import logging
import os
from collections.abc import Iterable

logger = logging.getLogger(__name__)


def assign_worker_cpus(index: int, total: int,
                       cpus: Iterable[int]) -> frozenset[int] | None:
    """The contiguous CPU stripe worker ``index`` of ``total`` should
    pin to, carved from the ALLOWED cpu list (so an outer cgroup/taskset
    restriction is respected, never widened). None when placement can't
    help: a single worker (nothing to separate) or fewer CPUs than
    workers (pinning would serialize siblings a free scheduler could
    still interleave)."""
    cpu_list = sorted(set(cpus))
    if total <= 1 or index < 0 or index >= total:
        return None
    if len(cpu_list) < total:
        return None
    per, extra = divmod(len(cpu_list), total)
    start = index * per + min(index, extra)
    size = per + (1 if index < extra else 0)
    return frozenset(cpu_list[start:start + size])


def apply_worker_affinity(index: int, total: int,
                          cpus: Iterable[int] | None = None
                          ) -> frozenset[int] | None:
    """Pin THIS process to its stripe; returns the applied CPU set, or
    None when the platform/topology says don't (logged at debug — this
    is the expected outcome on 1-core CI hosts, not an error).

    ``cpus`` is the pool-wide allowed set to carve stripes from. The
    deploy CLI captures it ONCE, before the parent pins itself, and
    threads it to every worker spawn: a worker respawned by the fleet
    supervisor inherits the (already-pinned) parent's affinity mask,
    so reading ``sched_getaffinity`` in the child would see only the
    parent's stripe and either refuse placement or pile every respawn
    onto worker 0's cores. ``None`` falls back to this process's own
    inherited mask (the pre-pin spawn path and standalone use)."""
    getter = getattr(os, "sched_getaffinity", None)
    setter = getattr(os, "sched_setaffinity", None)
    if setter is None:
        return None
    if cpus is not None:
        allowed = set(cpus)
    else:
        if getter is None:
            return None
        try:
            allowed = getter(0)
        except OSError:
            return None
    stripe = assign_worker_cpus(index, total, allowed)
    if stripe is None:
        logger.debug(
            "worker %d/%d: no affinity stripe (%d allowed cpus) — "
            "leaving scheduling to the kernel", index, total, len(allowed))
        return None
    try:
        setter(0, stripe)
    except OSError as exc:                 # containers may deny the call
        logger.debug("worker %d/%d: sched_setaffinity(%s) denied: %s",
                     index, total, sorted(stripe), exc)
        return None
    logger.info("worker %d/%d pinned to cpus %s", index, total,
                sorted(stripe))
    return stripe
