"""The real-time freshness plane: online ALS fold-in between retrains.

The reference is a Lambda architecture — model freshness is bounded by
the ``pio train`` cadence, so a user's session-start events cannot
influence recommendations until the next full retrain. This package is
the speed layer that closes the loop WITHOUT a retrain
(docs/freshness.md):

- :mod:`~predictionio_tpu.online.follower` — tails the event store
  through ``Events.find_columnar`` from a durable ``(eventTime, id)``
  cursor, exactly-once across batch boundaries (the ordering the PR 4
  conformance suite pins on every backend);
- :mod:`~predictionio_tpu.online.foldin` — recomputes an affected ALS
  user vector with the closed-form rank x rank normal-equation solve
  over the user's FULL interaction set (idempotent by construction:
  re-folding a user is a recomputation, not an accumulation), and gives
  brand-new items a popularity/content prior vector;
- :mod:`~predictionio_tpu.online.overlay` — the bounded LRU delta table
  the serving path consults per query, generation-FENCED against the
  deployed base model: a delta computed against model generation G is
  discarded, never applied, once ``/reload`` lands G+1;
- :mod:`~predictionio_tpu.online.service` — the per-server loop wiring
  the three together (``pio deploy --online``), with worker-pool
  propagation over the PR 10 spool plane and per-user result-cache
  invalidation instead of pool-wide generation bumps.
"""

from predictionio_tpu.online.follower import (  # noqa: F401
    CursorStore,
    EventTailFollower,
    TailCursor,
    resume_columnar,
)
from predictionio_tpu.online.foldin import (  # noqa: F401
    popularity_prior,
    solve_item,
    solve_user,
)
from predictionio_tpu.online.overlay import (  # noqa: F401
    ItemDelta,
    OnlineOverlay,
    UserDelta,
)
