"""Event-store tail follower: resumable ``find_columnar`` reads from a
durable ``(eventTime, id)`` cursor.

Every backend's ``find``/``find_columnar`` yields one deterministic
total order — ascending ``(eventTime, id)`` with the id tiebreak PR 4
pinned (plan-independent tie order) — so a consumer that remembers the
LAST row it consumed can resume exactly after it: re-read from the
cursor's event time (inclusive) and drop rows whose order key is not
strictly greater than the cursor's. ``Events.CURSOR_TIME_RESOLUTION_US``
names the granularity each backend ORDERS at (µs for the SQL/memory
stores, ms for the binary log whose payload order is the ms-truncated
wire spelling), so the comparison mirrors the backend's own sort key
instead of inventing a finer one that would mis-split ties.

Exactly-once is pinned per backend (including chaos fault injection) by
``tests/test_storage_conformance.py::TestColumnarCursorResume`` — the
correctness contract the fold-in loop stands on: no skipped event (a
rating that never reaches the model) and no duplicate (harmless here —
fold-in recomputes from the full history — but a violated contract
nonetheless).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Any, Iterator

import numpy as np

from predictionio_tpu.core.columns import us_to_datetime
from predictionio_tpu.storage.base import EventFilter, Events

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class TailCursor:
    """The last-consumed row's position in the backend's
    ``(eventTime, id)`` total order: µs-exact event time + event id."""

    time_us: int
    event_id: str

    def key(self, resolution_us: int = 1) -> tuple[int, str]:
        """The comparison key at the backend's ordering granularity."""
        return (self.time_us // max(1, resolution_us), self.event_id)

    def to_doc(self) -> list:
        return [int(self.time_us), self.event_id]

    @staticmethod
    def from_doc(doc: Any) -> "TailCursor | None":
        """A cursor from its JSON spelling; None for junk (a torn or
        hand-edited file degrades to "no cursor", never a crash)."""
        if (isinstance(doc, (list, tuple)) and len(doc) == 2
                and isinstance(doc[0], int) and isinstance(doc[1], str)):
            return TailCursor(time_us=doc[0], event_id=doc[1])
        return None


class CursorStore:
    """Durable cursor persistence: one JSON file, committed with the
    tmp+fsync+``os.replace`` discipline (the WAL cursor's idiom) so a
    crash never leaves a torn cursor. ``path=None`` keeps the cursor
    in memory only — a restart re-tails from its initial position,
    which is CORRECT (fold-in is idempotent) just wasteful."""

    def __init__(self, path: str | None):
        self.path = path
        self._memory: TailCursor | None = None

    def load(self) -> TailCursor | None:
        if self.path is None:
            return self._memory
        try:
            with open(self.path) as f:
                return TailCursor.from_doc(json.load(f))
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            return None

    def save(self, cursor: TailCursor) -> None:
        self._memory = cursor
        if self.path is None:
            return
        tmp = f"{self.path}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(cursor.to_doc(), f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError:
            # a read-only/full state dir degrades durability, not
            # correctness: the in-memory cursor keeps this process
            # exactly-once; only a restart re-reads the tail
            logger.warning("could not persist tail cursor to %s",
                           self.path, exc_info=True)


def cursor_resolution_us(events: Any) -> int:
    """The granularity ``events`` orders ties at (class attribute on
    the DAO; proxied backends — chaos — pass it through)."""
    return int(getattr(events, "CURSOR_TIME_RESOLUTION_US", 1))


def resume_columnar(
    events: Any,
    app_id: int,
    channel_id: int | None = None,
    filter: EventFilter = EventFilter(),
    cursor: TailCursor | None = None,
    batch_size: int = Events.COLUMNAR_BATCH_SIZE,
) -> Iterator[tuple[Any, np.ndarray]]:
    """``find_columnar`` resumed strictly after ``cursor``: yields
    ``(EventColumns, surviving_row_indices)`` pairs. Concatenating the
    surviving rows reproduces exactly the suffix of the full ``find``
    sequence that follows the cursor row — no skip, no duplicate
    (module docstring; conformance-pinned per backend).

    The resume is defined only for the forward unlimited scan the tail
    consumes: ``reversed`` or ``limit`` filters raise (a limited or
    descending read has no meaningful "after the cursor" suffix)."""
    if filter.reversed or filter.limit is not None:
        raise ValueError(
            "cursor resume is defined for forward unlimited scans only")
    if cursor is None:
        for cols in events.find_columnar(app_id, channel_id, filter,
                                         batch_size=batch_size):
            yield cols, np.arange(len(cols))
        return
    res = cursor_resolution_us(events)
    cursor_t, cursor_id = cursor.key(res)
    # re-read from the cursor's ORDER-KEY time (inclusive: equal-time
    # rows with a greater id are still pending) and drop everything at
    # or before the cursor key
    floor = us_to_datetime(cursor_t * res)
    start = (max(filter.start_time, floor)
             if filter.start_time is not None else floor)
    flt = dataclasses.replace(filter, start_time=start)
    for cols in events.find_columnar(app_id, channel_id, flt,
                                     batch_size=batch_size):
        t = cols.event_time_us // res
        after = t > cursor_t
        tied = t == cursor_t
        if tied.any():
            ids_after = np.fromiter(
                ((eid or "") > cursor_id for eid in cols.event_ids),
                dtype=bool, count=len(cols))
            after = after | (tied & ids_after)
        idx = np.nonzero(after)[0]
        if len(idx):
            yield cols, idx


@dataclasses.dataclass(frozen=True)
class TailRow:
    """One tailed event, flattened to what the fold-in consumes."""

    event: str
    entity_id: str
    target_entity_id: str | None
    time_us: int
    event_id: str
    properties: dict


class EventTailFollower:
    """A stateful tail over one app's event stream.

    ``poll_once()`` reads everything past the current cursor and
    returns ``(rows, new_cursor)`` WITHOUT advancing — the caller
    commits via :meth:`commit` only after the rows were applied
    downstream, so a crash between read and apply replays (at-least-
    once into an idempotent fold — the WAL replay discipline)."""

    def __init__(self, events: Any, app_id: int,
                 channel_id: int | None = None,
                 filter: EventFilter = EventFilter(),
                 store: CursorStore | None = None,
                 batch_size: int = Events.COLUMNAR_BATCH_SIZE,
                 max_rows: int = 20_000):
        self.events = events
        self.app_id = app_id
        self.channel_id = channel_id
        self.filter = filter
        self.store = store or CursorStore(None)
        self.batch_size = batch_size
        #: per-poll backlog cap: a leader resuming a durable cursor
        #: after a long stop must not materialize the whole backlog in
        #: one pass — the poll stops at the cap, the cursor lands on
        #: the last row CONSUMED, and the next cycle continues exactly
        #: where this one stopped (still exactly-once, just paged)
        self.max_rows = max(1, int(max_rows))
        self.cursor = self.store.load()

    def poll_once(self) -> tuple[list[TailRow], TailCursor | None]:
        rows: list[TailRow] = []
        last: TailCursor | None = None
        for cols, idx in resume_columnar(
                self.events, self.app_id, self.channel_id, self.filter,
                cursor=self.cursor, batch_size=self.batch_size):
            if len(rows) + len(idx) > self.max_rows:
                idx = idx[: self.max_rows - len(rows)]
            names = cols.event.decode()
            eids = cols.entity_id.decode()
            targets = cols.target_entity_id.decode()
            for i in idx:
                i = int(i)
                rows.append(TailRow(
                    event=names[i],
                    entity_id=eids[i],
                    target_entity_id=targets[i],
                    time_us=int(cols.event_time_us[i]),
                    event_id=cols.event_ids[i] or "",
                    properties=cols.properties_raw(i),
                ))
            if len(idx):
                tail = int(idx[-1])
                last = TailCursor(int(cols.event_time_us[tail]),
                                  cols.event_ids[tail] or "")
            if len(rows) >= self.max_rows:
                break
        return rows, (last or self.cursor)

    def commit(self, cursor: TailCursor | None) -> None:
        """Advance + persist — call only after the polled rows were
        applied (at-least-once contract in the class docstring)."""
        if cursor is None:
            return
        # pio: lint-ignore[shared-state-race]: cursor is an immutable TailCursor swapped by reference on the fold thread; the status-doc read tolerates staleness
        self.cursor = cursor
        self.store.save(cursor)
