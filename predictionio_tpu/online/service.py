"""The online fold-in service: tail → solve → publish, on a loop.

One :class:`OnlineFoldIn` runs inside each engine server deployed with
``pio deploy --online`` (docs/freshness.md). Per cycle (paced by
``Event.wait`` on the configured interval — the membership-loop idiom,
never a bare sleep):

1. **tail** — read everything past the durable ``(eventTime, id)``
   cursor through :mod:`~predictionio_tpu.online.follower`;
2. **solve** — give brand-new items a popularity-prior / symmetric-
   solve vector, then recompute every touched user's vector with the
   closed-form rank x rank solve over their FULL interaction set
   (:mod:`~predictionio_tpu.online.foldin` — idempotent, so the
   at-least-once tail commit is safe);
3. **publish** — install the deltas into the serving overlay
   (generation-FENCED: a fold computed against model generation G is
   discarded once ``/reload`` lands G+1), invalidate exactly the
   touched users' result-cache entries (not the whole pool's
   generation), commit the cursor, and — under ``--workers N`` —
   publish the overlay snapshot to the PR 10 spool plane so every
   sibling worker converges.

Worker-pool shape: ONE worker holds the tail lease (an ``O_EXCL`` claim
file beside the admin spool, pid-liveness-reaped like worker entries)
and folds; the siblings apply the seq'd ``online.state`` snapshot the
leader publishes — the same cumulative-document discipline as
``serving/workers.WorkerCoherence``. A dead leader's lease is reclaimed
by whichever sibling's next cycle notices, and the new leader adopts
the published cursor, so fold-in survives worker death with at most a
few intervals of added lag.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Mapping

import numpy as np

from predictionio_tpu.online.follower import (
    CursorStore,
    EventTailFollower,
    TailCursor,
    TailRow,
)
from predictionio_tpu.online.foldin import (
    item_gramian,
    popularity_prior,
    solve_item,
    solve_user,
)
from predictionio_tpu.online.overlay import ItemDelta, OnlineOverlay, UserDelta
from predictionio_tpu.storage.base import EventFilter

logger = logging.getLogger(__name__)

#: the leader's published overlay snapshot in the worker spool
#: (cumulative seq'd document, the WorkerCoherence discipline)
ONLINE_STATE_FILE = "online.state"
#: the tail-lease claim file (one folding leader per pool)
ONLINE_LEASE_FILE = "online.lease"


def user_key_fragment(user_id: str) -> str:
    """The canonical-JSON fragment a recommendation-family query for
    ``user_id`` carries in its result-cache key — derived through
    ``canonical_json`` itself so the spelling can never drift from the
    cache's key construction."""
    from predictionio_tpu.core.json_codec import canonical_json

    return canonical_json({"user": user_id})[1:-1]


@dataclasses.dataclass
class OnlineBinding:
    """Everything the fold-in needs, resolved from a deployment: the
    event stream coordinates, the rating rule, and the ALS model +
    hyperparameters the closed-form solve must mirror."""

    events: Any
    app_id: int
    channel_id: int | None
    entity_type: str
    target_entity_type: str
    event_names: tuple[str, ...] | None
    buy_rating: float | None
    model: Any                      # ALSModel (the fold-in target)
    lam: float
    implicit: bool
    alpha: float

    def rating_of(self, event: str, props: Mapping[str, Any]) -> float | None:
        """The template family's rating rule (recommendation's
        ratings_from_columns, generalized): ``rate`` events carry their
        rating property (malformed → dropped, the row-path rule);
        anything else is an implicit signal worth ``buy_rating`` when
        the template defines one (recommendation's buy=4.0), else 1.0
        (the view-event templates)."""
        if event == "rate":
            try:
                return float(props["rating"])
            except (KeyError, TypeError, ValueError):
                return None
        if self.buy_rating is not None:
            return float(self.buy_rating)
        return 1.0

    def tail_filter(self) -> EventFilter:
        return EventFilter(
            entity_type=self.entity_type,
            event_names=(list(self.event_names)
                         if self.event_names else None),
        )


def resolve_online_binding(deployed: Any, storage: Any) -> OnlineBinding | None:
    """Resolve the fold-in binding from a deployed engine, or None when
    the deployment has no ALS-family model / no resolvable app (the
    service then stays inert with a warning — ``--online`` on a
    classification engine must not kill the deploy)."""
    from predictionio_tpu.workflow.deploy import retrieval_targets

    try:
        instance = deployed.instance
        params = deployed.engine.params_from_instance_json(
            instance.data_source_params, instance.preparator_params,
            instance.algorithms_params, instance.serving_params)
    except Exception:
        logger.warning("online fold-in: engine params unresolvable",
                       exc_info=True)
        return None
    ds = params.data_source_params[1]
    app_name = getattr(ds, "app_name", "")
    if not app_name:
        logger.warning("online fold-in: data source names no app")
        return None
    app = storage.get_meta_data_apps().get_by_name(app_name)
    if app is None:
        logger.warning("online fold-in: app %r not found", app_name)
        return None
    model = None
    algo_params = None
    algo = None
    for (name, ap), a, m in zip(params.algorithm_params_list,
                                deployed.algorithms, deployed.models):
        targets = list(retrieval_targets([m]))
        if targets:
            model, algo_params, algo = targets[0], ap, a
            break
    if model is None:
        logger.warning(
            "online fold-in: no ALS-family model in this deployment")
        return None
    implicit = bool(getattr(algo_params, "implicit_prefs",
                            getattr(algo, "implicit_prefs", False)))
    return OnlineBinding(
        events=storage.get_events(),
        app_id=app.id,
        channel_id=None,
        entity_type=getattr(ds, "entity_type", "user"),
        target_entity_type=getattr(ds, "target_entity_type", "item"),
        event_names=(tuple(getattr(ds, "event_names", ()) or ()) or None),
        buy_rating=getattr(ds, "buy_rating", None),
        model=model,
        lam=float(getattr(algo_params, "lambda_", 0.01)),
        implicit=implicit,
        alpha=float(getattr(algo_params, "alpha", 1.0)),
    )


class TailLease:
    """One folding leader per worker pool: an ``O_EXCL`` claim file in
    the spool directory, identified by worker id and liveness-checked
    by pid (dead leaders are reaped, same discipline as the worker
    spool entries)."""

    def __init__(self, spool_dir: str, owner: str):
        self.path = os.path.join(spool_dir, ONLINE_LEASE_FILE)
        self.owner = owner

    def _holder(self) -> dict | None:
        try:
            with open(self.path) as f:
                doc = json.load(f)
            return doc if isinstance(doc, dict) else None
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            return None

    def try_hold(self) -> bool:
        """True when this worker holds (or just claimed) the lease."""
        holder = self._holder()
        if holder is not None:
            if holder.get("worker") == self.owner:
                return True
            try:
                os.kill(int(holder.get("pid", -1)), 0)
                return False            # live leader elsewhere
            except (ProcessLookupError, ValueError):
                try:
                    os.unlink(self.path)   # dead leader: reap
                except OSError:
                    return False
            except PermissionError:
                return False            # alive, different uid
        try:
            fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_EXCL)
        except FileExistsError:
            return False                # lost the claim race
        except OSError:
            return False
        with os.fdopen(fd, "w") as f:
            json.dump({"worker": self.owner, "pid": os.getpid()}, f)
        logger.info("online tail lease claimed by %s", self.owner)
        return True

    def release(self) -> None:
        holder = self._holder()
        if holder is not None and holder.get("worker") == self.owner:
            try:
                os.unlink(self.path)
            except OSError:
                pass


class OnlineFoldIn:
    """The per-server fold-in loop (module docstring)."""

    def __init__(
        self,
        *,
        storage: Any,
        deployed_fn: Callable[[], Any],
        generation_fn: Callable[[], int],
        interval_s: float = 1.0,
        overlay_max: int = 4096,
        state_dir: str | None = None,
        tail_batch: int = 4096,
        invalidate_user: Callable[[str], None] | None = None,
        trace_log: Any = None,
        tracing: bool = False,
        worker_hub: Any = None,
        initial_cursor: TailCursor | None = None,
    ):
        self.storage = storage
        self._deployed_fn = deployed_fn
        self._generation_fn = generation_fn
        self.interval_s = max(0.05, float(interval_s))
        self._tail_batch = tail_batch
        self._invalidate_user = invalidate_user
        self._trace_log = trace_log
        self._tracing = tracing
        self._hub = worker_hub
        self._state_dir = state_dir
        self._initial_cursor = initial_cursor
        self.overlay = OnlineOverlay(
            max_users=overlay_max,
            max_items=max(64, overlay_max // 4),
            generation=generation_fn())
        self.enabled = False
        self._binding: OnlineBinding | None = None
        self._follower: EventTailFollower | None = None
        self._lease: TailLease | None = None
        self._is_leader = False
        self._adopted_leader_state = False
        self._applied_seq = 0
        #: (mtime_ns, size) of the last pool snapshot this sibling
        #: fully processed — the cheap skip-the-parse guard
        self._doc_stamp: tuple | None = None
        #: users to re-solve against a freshly reloaded model (the
        #: overlay cleared at the generation fence; refolding closes
        #: the window where their post-training events would be
        #: invisible until their next event)
        self._pending_refold: set[str] = set()
        #: per-generation solve constants (implicit gramian, item
        #: prior) — one full-table host read per model generation
        self._gram: tuple[int, np.ndarray] | None = None
        self._prior: tuple[int, np.ndarray] | None = None
        self._lock = threading.Lock()
        self._stats = {
            "foldedEvents": 0, "foldCycles": 0, "usersFolded": 0,
            "itemsAdded": 0, "errors": 0, "lagSeconds": None,
        }
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._rebind()
        if self._binding is None:
            logger.warning(
                "--online requested but this deployment cannot fold in "
                "(no ALS model / unresolvable app); the freshness plane "
                "stays inert")
            return
        cursor_path = (os.path.join(self._state_dir, "online.cursor")
                       if self._state_dir else None)
        if cursor_path:
            os.makedirs(self._state_dir, exist_ok=True)
        store = CursorStore(cursor_path)
        self._follower = EventTailFollower(
            self._binding.events, self._binding.app_id,
            self._binding.channel_id, self._binding.tail_filter(),
            store=store, batch_size=self._tail_batch)
        if self._follower.cursor is None:
            # tail from NOW: history up to deploy time is the batch
            # layer's job (the trained model already has it); events
            # explicitly back-dated past this instant wait for the next
            # retrain (docs/freshness.md)
            self._follower.cursor = (
                self._initial_cursor
                or TailCursor(int(time.time() * 1_000_000), ""))
        if self._hub is not None:
            self._lease = TailLease(self._hub.spool_dir,
                                    self._hub.worker_id)
        self.enabled = True
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="pio-online-foldin", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            was_leader = self._is_leader
        if self._lease is not None and was_leader:
            self._lease.release()

    def _run(self) -> None:
        # Event.wait doubles as pacing and prompt stop — never a bare
        # time.sleep (the banned_sleep_paths lint invariant)
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — a failed cycle is the next one's problem
                with self._lock:
                    self._stats["errors"] += 1
                logger.exception("online fold-in cycle failed")

    # -- model-swap hook (EngineService.reload) -----------------------------
    def on_model_swapped(self, generation: int) -> None:
        """A ``/reload`` landed: fence the overlay (deltas computed
        against the old model are discarded, never applied), rebind to
        the fresh model objects, and queue every previously-folded user
        for a refold against the new base — their post-training events
        may postdate the new model's training read too."""
        # under _lock: the fold thread swaps this set out concurrently
        # (_fold_once), and |= is a read-modify-write — an unlocked
        # interleave would silently drop queued refold users
        with self._lock:
            self._pending_refold |= set(self.overlay.touched_users())
        self.overlay.advance_generation(generation)
        # racy clears are deliberate: both caches key on the generation
        # captured at cycle start, so a fold cycle that repopulates them
        # after this clear self-heals on its next gen check; the tuple
        # swap itself is atomic under the GIL
        self._gram = None
        self._prior = None
        self._rebind()
        if self._follower is not None and self._binding is not None:
            self._follower.events = self._binding.events

    def _rebind(self) -> None:
        self._binding = resolve_online_binding(
            self._deployed_fn(), self.storage)
        if self._binding is not None:
            self._install_overlay()

    def _install_overlay(self) -> None:
        from predictionio_tpu.workflow.deploy import retrieval_targets

        for target in retrieval_targets(
                getattr(self._deployed_fn(), "models", ())):
            if hasattr(target, "set_online_overlay"):
                target.set_online_overlay(self.overlay)

    # -- one cycle ---------------------------------------------------------
    def tick(self) -> int:
        """One loop pass: fold when this process is the (sole or
        lease-holding) tailer, otherwise sync the leader's published
        snapshot. Returns the number of events folded/applied."""
        if not self.enabled:
            return 0
        if self._lease is None or self._lease.try_hold():
            if self._lease is not None and not self._adopted_leader_state:
                self._adopt_leader_state()
            with self._lock:
                self._is_leader = True
            return self._fold_once()
        with self._lock:
            self._is_leader = False
        self._adopted_leader_state = False
        return self._sync_once()

    def _adopt_leader_state(self) -> None:
        """A freshly promoted leader resumes from the PUBLISHED cursor
        (the previous leader's progress), not its own stale one."""
        doc = self._read_pool_doc()
        if doc is not None:
            cursor = TailCursor.from_doc(doc.get("cursor"))
            if cursor is not None:
                self._follower.commit(cursor)
            with self._lock:
                self._applied_seq = int(doc.get("seq", 0))
        self._adopted_leader_state = True

    def _fold_once(self) -> int:
        # generation FIRST, then the binding: the tail poll below can
        # take a while, and a /reload completing anywhere after this
        # line leaves `generation` stale — which is exactly what the
        # overlay fence rejects at publish (a gen captured after the
        # swap but paired with the pre-swap binding would slip vectors
        # solved against the OLD factor tables onto the new model)
        generation = self._generation_fn()
        binding = self._binding
        trace = None
        if self._tracing and self._trace_log is not None:
            from predictionio_tpu.obs.trace import start_trace

            trace = start_trace("online.foldin", service="engine")
        t0 = time.perf_counter()
        rows, new_cursor = self._follower.poll_once()
        t_tail = time.perf_counter()
        with self._lock:
            refold, self._pending_refold = self._pending_refold, set()
        if not rows and not refold:
            return 0
        try:
            return self._solve_and_publish(
                binding, generation, rows, new_cursor, refold, trace,
                t0, t_tail)
        except Exception:
            # the solve/publish phase is fallible (storage outage on a
            # history read): the cursor was not committed, so the
            # tailed rows replay — but the refold queue was already
            # swapped out and its users' events are BEHIND the cursor;
            # restore it or a single failed cycle silently drops the
            # refold-after-reload guarantee (under _lock: a /reload's
            # own |= may interleave with this restore)
            with self._lock:
                self._pending_refold |= refold
            raise

    def _solve_and_publish(self, binding: OnlineBinding, generation: int,
                           rows: list[TailRow],
                           new_cursor: TailCursor | None,
                           refold: set[str], trace: Any,
                           t0: float, t_tail: float) -> int:
        by_user: dict[str, list[TailRow]] = {}
        by_item: dict[str, list[TailRow]] = {}
        for row in rows:
            if row.target_entity_id is None:
                continue
            by_user.setdefault(row.entity_id, []).append(row)
            by_item.setdefault(row.target_entity_id, []).append(row)
        model = binding.model
        new_items = {
            iid: ItemDelta(vector=self._solve_new_item(binding, evs,
                                                       generation))
            for iid, evs in by_item.items()
            if model.item_ids.get(iid) is None
        }
        deltas: dict[str, UserDelta] = {}
        for uid in set(by_user) | refold:
            delta = self._fold_user(binding, uid, by_user.get(uid, ()),
                                    new_items, generation)
            if delta is not None:
                deltas[uid] = delta
        t_solve = time.perf_counter()
        applied = 0
        fenced = False
        for iid, delta in new_items.items():
            if not self.overlay.put_item(iid, delta,
                                         generation=generation):
                fenced = True
        for uid, delta in deltas.items():
            if self.overlay.put_user(uid, delta, generation=generation):
                applied += 1
                if self._invalidate_user is not None:
                    self._invalidate_user(uid)
            else:
                fenced = True
        if fenced:
            # a /reload raced this cycle (the generation fence fired):
            # do NOT advance the cursor — the next cycle re-reads these
            # events and re-solves against the NEW model (fold-in is a
            # recomputation, so the replay is exact, not additive)
            with self._lock:
                self._pending_refold |= set(deltas)
        else:
            self._follower.commit(new_cursor)
        now = time.time()
        lag = (now - min(r.time_us for r in rows) / 1e6) if rows else None
        with self._lock:
            self._stats["foldCycles"] += 1
            if not fenced:
                # a fenced cycle applied NOTHING and left the cursor in
                # place — counting its rows would double them when the
                # next cycle re-reads, and its lag is the lag of work
                # that never reached serving
                self._stats["foldedEvents"] += len(rows)
                self._stats["usersFolded"] += applied
                self._stats["itemsAdded"] += len(new_items)
                if lag is not None:
                    self._stats["lagSeconds"] = lag
        if self._hub is not None and not fenced and (applied or new_items):
            self._publish_pool_doc(generation, new_cursor,
                                   sorted(deltas))
        t_publish = time.perf_counter()
        if trace is not None:
            trace.add_span("tail", t0, t_tail)
            trace.add_span("solve", t_tail, t_solve)
            trace.add_span("publish", t_solve, t_publish)
            trace.finish(events=len(rows), users=applied,
                         items=len(new_items), generation=generation)
            self._trace_log.record(trace)
        return len(rows)

    # -- solves ------------------------------------------------------------
    def _item_prior(self, model: Any, gen: int) -> np.ndarray:
        # keyed on the generation CAPTURED at cycle start, not the
        # overlay's live one: a /reload mid-cycle must not cache the
        # old model's centroid under the new generation
        if self._prior is None or self._prior[0] != gen:
            # one full-table host read per model generation, on the
            # background fold thread
            # pio: lint-ignore[host-sync-in-hot-path]: fold-in runs on the background tail thread, never under a request
            table = np.asarray(model.item_factors)
            # pio: lint-ignore[shared-state-race]: gen-keyed cache — a racy clear from on_model_swapped is healed by the gen check above; the tuple swap is atomic under the GIL
            self._prior = (gen, popularity_prior(table))
        return self._prior[1]

    def _gramian(self, factors: Any, gen: int) -> np.ndarray:
        # same captured-generation keying as _item_prior
        if self._gram is None or self._gram[0] != gen:
            # pio: lint-ignore[host-sync-in-hot-path]: per-generation constant, computed off the request path
            # pio: lint-ignore[shared-state-race]: gen-keyed cache — a racy clear from on_model_swapped is healed by the gen check above; the tuple swap is atomic under the GIL
            self._gram = (gen, item_gramian(np.asarray(factors)))
        return self._gram[1]

    def _gather_rows(self, factors: Any, ixs: list[int]) -> np.ndarray:
        import jax.numpy as jnp

        # device gather + small transfer: never the whole table per user
        # pio: lint-ignore[host-sync-in-hot-path]: background fold thread, bounded by the user's history length
        return np.asarray(
            factors[jnp.asarray(np.asarray(ixs, dtype=np.int32))])

    def _solve_new_item(self, binding: OnlineBinding,
                        events: list[TailRow],
                        generation: int) -> np.ndarray:
        """A vector for an item outside the base catalog: the symmetric
        closed-form solve over its known raters when any exist, else
        the popularity prior (foldin module docstring)."""
        model = binding.model
        uixs: list[int] = []
        ratings: list[float] = []
        for row in events:
            uix = model.user_ids.get(row.entity_id)
            rating = binding.rating_of(row.event, row.properties)
            if uix is not None and rating is not None:
                uixs.append(uix)
                ratings.append(rating)
        if uixs:
            vec = solve_item(
                self._gather_rows(model.user_factors, uixs),
                np.asarray(ratings, dtype=np.float32),
                lam=binding.lam, implicit=binding.implicit,
                alpha=binding.alpha,
                gram=(self._gramian(model.user_factors, generation)
                      if binding.implicit else None))
            if vec is not None:
                return vec
        return self._item_prior(model, generation)

    def _fold_user(self, binding: OnlineBinding, uid: str,
                   tail_rows: list[TailRow] | tuple,
                   new_items: Mapping[str, ItemDelta],
                   generation: int) -> UserDelta | None:
        """Recompute one user's vector over their FULL interaction set
        (base history + everything since — read back from the event
        store, so the solve is a recomputation, not an accumulation)."""
        model = binding.model
        history = binding.events.find(
            binding.app_id, binding.channel_id,
            EventFilter(
                entity_type=binding.entity_type, entity_id=uid,
                event_names=(list(binding.event_names)
                             if binding.event_names else None)))
        base_ixs: list[int] = []
        base_ratings: list[float] = []
        delta_vecs: list[np.ndarray] = []
        delta_ratings: list[float] = []
        delta_seen: list[str] = []
        for event in history:
            tid = event.target_entity_id
            if tid is None:
                continue
            rating = binding.rating_of(event.event,
                                       event.properties.fields)
            if rating is None:
                continue
            ix = model.item_ids.get(tid)
            if ix is not None:
                base_ixs.append(ix)
                base_ratings.append(rating)
                continue
            delta = new_items.get(tid) or self.overlay.item(tid)
            if delta is not None:
                delta_vecs.append(delta.vector)
                delta_ratings.append(rating)
                if tid not in delta_seen:
                    delta_seen.append(tid)
        if not base_ixs and not delta_vecs:
            return None
        parts = []
        if base_ixs:
            parts.append(self._gather_rows(model.item_factors, base_ixs))
        if delta_vecs:
            parts.append(np.stack(delta_vecs))
        vecs = np.concatenate(parts) if len(parts) > 1 else parts[0]
        ratings = np.asarray(base_ratings + delta_ratings,
                             dtype=np.float32)
        vector = solve_user(
            vecs, ratings, lam=binding.lam, implicit=binding.implicit,
            alpha=binding.alpha,
            gram=(self._gramian(model.item_factors, generation)
                  if binding.implicit else None))
        if vector is None:
            return None
        times = [r.time_us for r in tail_rows]
        return UserDelta(
            vector=vector,
            extra_seen=tuple(sorted(set(base_ixs))),
            delta_seen=tuple(delta_seen),
            folded_events=len(tail_rows),
            event_time_us=max(times) if times else 0,
        )

    # -- worker-pool propagation (PR 10 spool plane) ------------------------
    def _pool_doc_path(self) -> str:
        return os.path.join(self._hub.spool_dir, ONLINE_STATE_FILE)

    def _read_pool_doc(self) -> dict | None:
        try:
            with open(self._pool_doc_path()) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            return None
        if not isinstance(doc, dict) or not isinstance(doc.get("seq"), int):
            return None
        return doc

    def _publish_pool_doc(self, generation: int,
                          cursor: TailCursor | None,
                          touched: list[str]) -> None:
        """The leader's cumulative overlay snapshot, seq'd and committed
        with atomic ``os.replace`` (the WorkerHub admin discipline):
        a respawned or lagging sibling adopts the WHOLE state from one
        read — no history to replay."""
        users, items = self.overlay.snapshot_entries()
        # the leader is the sole writer and tracks its own sequence
        # (_adopt_leader_state seeds it from the document on
        # promotion) — re-reading the multi-MB snapshot every publish
        # just to recover a number this process wrote is waste
        with self._lock:
            seq = self._applied_seq + 1
            folded = self._stats["foldedEvents"]
            lag = self._stats["lagSeconds"]
        doc = {
            "seq": seq,
            "generation": generation,
            "cursor": cursor.to_doc() if cursor is not None else None,
            "touched": touched,
            "users": {
                uid: {"v": d.vector.tolist(),
                      "seen": [int(x) for x in d.extra_seen],
                      "deltaSeen": list(d.delta_seen),
                      "n": d.folded_events, "t": d.event_time_us}
                for uid, d in users.items()
            },
            "items": {iid: d.vector.tolist() for iid, d in items.items()},
            "foldedTotal": folded,
            "lagSeconds": lag,
            "publishedBy": self._hub.worker_id,
        }
        path = self._pool_doc_path()
        tmp = f"{path}.{self._hub.worker_id}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
            with self._lock:
                self._applied_seq = seq
        except OSError:
            logger.exception("publishing online overlay snapshot failed")

    def _sync_once(self) -> int:
        """A non-leader worker applies the leader's latest snapshot —
        fenced by generation exactly like a local fold (a snapshot
        computed against a model this worker has not reloaded onto yet
        waits; the sequence is retried every cycle until generations
        agree)."""
        # stat before parse: the cumulative snapshot scales to MBs at a
        # warm overlay, and N-1 request-serving siblings re-reading it
        # every interval just to learn "seq unchanged" is pure waste —
        # os.replace always moves mtime/size, so an unchanged stat
        # means an unchanged document
        try:
            st = os.stat(self._pool_doc_path())
            stamp = (st.st_mtime_ns, st.st_size)
        except OSError:
            return 0
        if stamp == self._doc_stamp:
            return 0
        doc = self._read_pool_doc()
        with self._lock:
            applied_seq = self._applied_seq
        if doc is None or doc["seq"] <= applied_seq:
            self._doc_stamp = stamp
            return 0
        generation = self._generation_fn()
        if doc.get("generation") != generation:
            # do NOT latch the stamp: this document must be retried
            # every cycle until this worker's own reload catches up
            # (the generation-fence retry contract)
            return 0
        try:
            users = {
                uid: UserDelta(
                    vector=np.asarray(u["v"], dtype=np.float32),
                    extra_seen=tuple(int(x) for x in u.get("seen", ())),
                    delta_seen=tuple(u.get("deltaSeen", ())),
                    folded_events=int(u.get("n", 0)),
                    event_time_us=int(u.get("t", 0)))
                for uid, u in doc.get("users", {}).items()
            }
            items = {
                iid: ItemDelta(vector=np.asarray(v, dtype=np.float32))
                for iid, v in doc.get("items", {}).items()
            }
        except (TypeError, ValueError):
            logger.warning("malformed online snapshot seq=%s skipped",
                           doc.get("seq"))
            with self._lock:
                self._applied_seq = doc["seq"]
            self._doc_stamp = stamp
            return 0
        # invalidate by DIFF against this worker's current overlay, not
        # by the document's `touched` list: the snapshot is cumulative
        # and this sibling may have skipped intermediate publishes (a
        # slow cycle, the generation-fence retry wait) — `touched` only
        # names the LAST publish's users, and trusting it would leave
        # earlier-folded users' stale cache entries serving until TTL
        prior_users, _ = self.overlay.snapshot_entries()
        changed = [
            uid for uid, delta in users.items()
            if (prev := prior_users.get(uid)) is None
            or not np.array_equal(prev.vector, delta.vector)
        ]
        if not self.overlay.load_snapshot(users, items,
                                          generation=generation):
            return 0
        self._doc_stamp = stamp
        with self._lock:
            self._applied_seq = doc["seq"]
        for uid in changed:
            if self._invalidate_user is not None:
                self._invalidate_user(uid)
        applied = len(changed)
        with self._lock:
            if doc.get("lagSeconds") is not None:
                self._stats["lagSeconds"] = doc["lagSeconds"]
        return applied

    # -- observability ------------------------------------------------------
    def metrics(self) -> dict:
        """The duck-typed read the registry adapter and ``/stats.json``
        share (obs/registry.online_collector)."""
        counters = self.overlay.counters()
        with self._lock:
            stats = dict(self._stats)
            leader = self._is_leader
            applied_seq = self._applied_seq
        return {
            "enabled": self.enabled,
            "leader": leader or self._lease is None,
            "generation": counters["generation"],
            "overlayUsers": counters["users"],
            "overlayItems": counters["items"],
            "overlaySize": counters["users"] + counters["items"],
            "evictions": counters["evictions"],
            "fenced": counters["fenced"],
            "foldedEventsTotal": stats["foldedEvents"],
            "foldCycles": stats["foldCycles"],
            "usersFoldedTotal": stats["usersFolded"],
            "itemsAddedTotal": stats["itemsAdded"],
            "errorsTotal": stats["errors"],
            "lagSeconds": stats["lagSeconds"],
            "appliedSeq": applied_seq,
        }

    def stats_doc(self) -> dict:
        """The ``/stats.json`` ``online`` section."""
        doc = self.metrics()
        doc["intervalS"] = self.interval_s
        cursor = (self._follower.cursor
                  if self._follower is not None else None)
        doc["cursor"] = cursor.to_doc() if cursor is not None else None
        return doc
