"""The online delta overlay: what the serving path reads per query.

A bounded LRU table of fold-in results, installed on the deployed
ALS-family model (``ALSModel.set_online_overlay``). Two kinds of delta:

- **user deltas** — a recomputed user vector plus the item indices the
  user has touched since training (so a just-rated item is excluded
  from their recommendations immediately, not at the next retrain);
  new users get an entry too: cold-start-to-served;
- **item deltas** — vectors for items the base model has never seen
  (popularity prior, refined by the symmetric solve once raters
  exist). They are NOT inserted into the catalog tables or the IVF
  index: the serving path brute-scores the (small) delta matrix on
  the host and merges it into the device top-k, so the ANN index is
  never rebuilt online and retrieval for unchanged items is
  bit-identical (the recall-neutrality pin in tests/test_ann.py).

**Generation fencing** — every write carries the base-model generation
it was computed against; a write whose generation does not match the
overlay's current one is DISCARDED (returns False), and ``/reload``
advances the overlay generation (clearing it) before the new model
serves. A fold computed against model G can therefore never leak onto
model G+1 — pinned e2e in tests/test_online_freshness.py.

Bounded on purpose: the overlay is a freshness WINDOW, not a second
model. Evictions (counted; ``pio_online_overlay_evictions_total``)
drop the least-recently-FOLDED user back to their base vector — stale
by at most the retrain cadence, exactly the pre-online behavior.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict

import numpy as np


@dataclasses.dataclass(frozen=True)
class UserDelta:
    """One folded user: the recomputed vector + post-training seen
    state (base-catalog indices and overlay item ids)."""

    vector: np.ndarray                    # (K,) float32
    extra_seen: tuple[int, ...] = ()      # base-catalog item indices
    delta_seen: tuple[str, ...] = ()      # overlay item ids touched
    folded_events: int = 0
    event_time_us: int = 0                # newest event folded in


@dataclasses.dataclass(frozen=True)
class ItemDelta:
    """One overlay item: a vector for an id outside the base catalog."""

    vector: np.ndarray                    # (K,) float32


class OnlineOverlay:
    """Thread-safe bounded delta table (module docstring). Readers are
    request-handler threads (one dict get under the lock per query);
    the writer is the fold-in loop."""

    def __init__(self, max_users: int = 4096, max_items: int = 1024,
                 generation: int = 0):
        self.max_users = max(1, int(max_users))
        self.max_items = max(1, int(max_items))
        self._lock = threading.Lock()
        self._users: "OrderedDict[str, UserDelta]" = OrderedDict()
        self._items: "OrderedDict[str, ItemDelta]" = OrderedDict()
        self._generation = int(generation)
        self._evictions = 0
        self._fenced = 0
        #: delta-matrix snapshot cache (rebuilt when items change)
        self._matrix: tuple[tuple[str, ...], np.ndarray] | None = None

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    # -- writes (the fold-in publisher) -----------------------------------
    def put_user(self, user_id: str, delta: UserDelta,
                 generation: int) -> bool:
        """Install/replace one user delta; False (nothing written) when
        ``generation`` is not the overlay's current one — the fencing
        contract (module docstring)."""
        with self._lock:
            if generation != self._generation:
                self._fenced += 1
                return False
            self._users[user_id] = delta
            self._users.move_to_end(user_id)
            while len(self._users) > self.max_users:
                self._users.popitem(last=False)
                self._evictions += 1
            return True

    def put_item(self, item_id: str, delta: ItemDelta,
                 generation: int) -> bool:
        with self._lock:
            if generation != self._generation:
                self._fenced += 1
                return False
            self._items[item_id] = delta
            self._items.move_to_end(item_id)
            while len(self._items) > self.max_items:
                self._items.popitem(last=False)
                self._evictions += 1
            self._matrix = None
            return True

    def advance_generation(self, generation: int) -> None:
        """``/reload`` landed: clear everything and fence out any fold
        still in flight against the old model. Forward-only, like the
        result cache's generations."""
        with self._lock:
            self._users.clear()
            self._items.clear()
            self._matrix = None
            self._generation = max(self._generation + 1, int(generation))

    def load_snapshot(self, users: dict, items: dict,
                      generation: int) -> bool:
        """Replace the whole table from a published snapshot (the
        worker-pool sync path): refused — False — when ``generation``
        does not match this worker's overlay generation, the sibling-
        side half of the fencing contract."""
        with self._lock:
            if generation != self._generation:
                self._fenced += 1
                return False
            self._users = OrderedDict(users)
            self._items = OrderedDict(items)
            self._matrix = None
            return True

    # -- reads (the serving path) -----------------------------------------
    def user(self, user_id: str) -> UserDelta | None:
        with self._lock:
            return self._users.get(user_id)

    def item(self, item_id: str) -> ItemDelta | None:
        with self._lock:
            return self._items.get(item_id)

    def has_items(self) -> bool:
        with self._lock:
            return bool(self._items)

    def delta_matrix(self) -> tuple[tuple[str, ...], np.ndarray] | None:
        """``(item_ids, (m, K) matrix)`` of every overlay item, cached
        until the item set changes — the per-query read is one lock
        acquisition and (on the hit path) zero allocation."""
        with self._lock:
            if not self._items:
                return None
            if self._matrix is None:
                ids = tuple(self._items)
                self._matrix = (ids, np.stack(
                    [self._items[i].vector for i in ids]).astype(np.float32))
            return self._matrix

    def touched_users(self) -> list[str]:
        with self._lock:
            return list(self._users)

    # -- introspection -----------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._users) + len(self._items)

    def counters(self) -> dict:
        with self._lock:
            return {
                "users": len(self._users),
                "items": len(self._items),
                "evictions": self._evictions,
                "fenced": self._fenced,
                "generation": self._generation,
            }

    def snapshot_entries(self) -> tuple[dict, dict]:
        """Shallow copies of both tables (publishing a pool snapshot)."""
        with self._lock:
            return dict(self._users), dict(self._items)
