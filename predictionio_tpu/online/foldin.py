"""Closed-form ALS fold-in: the rank x rank normal-equation solves.

The batch trainer alternates whole-table half-steps on the device
(ops/als). Folding ONE user between retrains needs only that user's row
of the same normal equations — a rank x rank solve over the handful of
item vectors the user touched, microseconds of host NumPy — so the
speed layer never dispatches to the device or recompiles anything.

The math mirrors ``ops/als._normal_eq_solve`` exactly (same model, the
e2e freshness pin asserts the folded vector matches a from-scratch
reference within tolerance):

- explicit ALS-WR:  ``A = Σ y yᵀ + λ n_u I``, ``b = Σ r y``
- implicit Hu-Koren (MLlib trainImplicit semantics): confidence
  ``c = 1 + α|r|``, preference ``p = [r > 0]`` —
  ``A = YᵀY + Σ α|r| y yᵀ + λ I``, ``b = Σ (1 + α r)·[r>0] y``,
  where ``YᵀY`` is the gramian of the FULL item table (supplied by the
  caller, computed once per model generation).

Solving over the user's FULL interaction set (not a delta update) makes
fold-in IDEMPOTENT: re-folding after a replayed tail read, a leader
failover, or a model reload recomputes the same vector instead of
double-counting events — the property the at-least-once follower and
the generation-fencing publisher both stand on.

New items have no raters worth trusting yet: :func:`popularity_prior`
hands them the interaction-weighted centroid of the catalog (the
"popular taste" direction), and :func:`solve_item` refines with the
symmetric closed-form solve once known users have rated them.
"""

from __future__ import annotations

import numpy as np


def _solve(A: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Solve the (K, K) normal system, falling back to least squares
    when the ridge was too weak to regularize a degenerate system."""
    try:
        return np.linalg.solve(A, b).astype(np.float32)
    except np.linalg.LinAlgError:
        return np.linalg.lstsq(A, b, rcond=None)[0].astype(np.float32)


def solve_user(item_vecs: np.ndarray, ratings: np.ndarray, lam: float,
               implicit: bool = False, alpha: float = 1.0,
               gram: np.ndarray | None = None) -> np.ndarray | None:
    """One user's closed-form factor vector from the item vectors of
    their full interaction set (module docstring has the model).

    ``item_vecs`` is (n, K) float32, ``ratings`` (n,); ``gram`` is the
    full-table ``YᵀY`` required in implicit mode. Returns (K,) float32,
    or None for an empty interaction set (nothing to say about this
    user — the caller keeps whatever vector the base model has)."""
    item_vecs = np.asarray(item_vecs, dtype=np.float32)
    ratings = np.asarray(ratings, dtype=np.float32)
    n = len(ratings)
    if n == 0:
        return None
    k = item_vecs.shape[1]
    eye = np.eye(k, dtype=np.float32)
    if implicit:
        if gram is None:
            raise ValueError("implicit fold-in needs the item gramian")
        w = alpha * np.abs(ratings)                       # (c - 1)
        A = gram + (item_vecs * w[:, None]).T @ item_vecs + lam * eye
        cp = np.where(ratings > 0, 1.0 + alpha * ratings, 0.0)
        b = cp @ item_vecs                                # Σ c p y
    else:
        A = item_vecs.T @ item_vecs + (lam * n) * eye
        b = ratings @ item_vecs
    return _solve(A, b.astype(np.float32))


def solve_item(user_vecs: np.ndarray, ratings: np.ndarray, lam: float,
               implicit: bool = False, alpha: float = 1.0,
               gram: np.ndarray | None = None) -> np.ndarray | None:
    """The symmetric solve: one ITEM's vector from the vectors of the
    users who rated it (ALS is symmetric in the two factor tables;
    ``gram`` is the full USER-table gramian in implicit mode)."""
    return solve_user(user_vecs, ratings, lam, implicit=implicit,
                      alpha=alpha, gram=gram)


def popularity_prior(item_factors: np.ndarray,
                     weights: np.ndarray | None = None) -> np.ndarray:
    """A cold-start vector for an item nobody known has rated yet: the
    (optionally popularity-weighted) centroid of the existing catalog —
    it scores every user by their affinity for the popular taste
    direction, which beats the all-zeros vector (never recommended)
    and any random direction (noise). Replaced by :func:`solve_item`
    as soon as real raters exist, and by the real trained vector at
    the next retrain."""
    table = np.asarray(item_factors, dtype=np.float32)
    if table.size == 0:
        return np.zeros((table.shape[-1] if table.ndim == 2 else 0,),
                        dtype=np.float32)
    if weights is not None:
        w = np.asarray(weights, dtype=np.float32)
        total = float(w.sum())
        if total > 0:
            return (table * (w / total)[:, None]).sum(axis=0)
    return table.mean(axis=0)


def item_gramian(factors: np.ndarray) -> np.ndarray:
    """``FᵀF`` of a factor table as float32 — the implicit-mode
    constant, computed once per model generation and cached by the
    service."""
    f = np.asarray(factors, dtype=np.float32)
    return f.T @ f
