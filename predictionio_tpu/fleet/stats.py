"""Router hot-path counters and latency attribution.

Same discipline as :class:`~predictionio_tpu.api.stats.ServingStats`:
one lock guards every counter at writers AND readers (handler threads
bump, ``/metrics`` and ``/fleet`` snapshot), the latency histograms
(obs/histogram.py) each own their own lock, and the registry adapter
below runs only at scrape time.
"""

from __future__ import annotations

import threading
from typing import Any

from predictionio_tpu.obs.histogram import LatencyHistogram
from predictionio_tpu.obs.registry import Metric


class RouterStats:
    """Counters for the fleet router's forward path."""

    COUNTER_FIELDS = (
        # admission + outcomes; quota_throttled = per-engine token
        # bucket said no (429; fleet/gateway.py) — distinct from sheds,
        # which is the GLOBAL-pressure 503
        "requests", "sheds", "quota_throttled", "expired", "no_backend",
        # resilience events
        "retries", "upstream_errors",
        # hedging
        "hedges", "hedge_wins",
        # canary bookkeeping
        "canary_requests", "stable_requests", "canary_aborts",
        # degraded-but-correct: the picked group had no healthy replica
        # and the OTHER group answered
        "group_spills",
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = dict.fromkeys(self.COUNTER_FIELDS, 0)
        #: end-to-end upstream exchange time per replica group
        self.upstream_latency = {
            "stable": LatencyHistogram(),
            "canary": LatencyHistogram(),
        }

    def bump(self, field: str, n: int = 1) -> None:
        with self._lock:
            self._counts[field] += n

    def bump_request(self, group: str) -> None:
        """The admission-path double count (requests + per-group) under
        ONE lock acquisition — this runs on every routed query."""
        with self._lock:
            self._counts["requests"] += 1
            self._counts[f"{group}_requests"] += 1

    def bump_throttled(self) -> None:
        """A quota-throttled request (429, fleet/gateway.py): counted
        as a request AND a throttle under one lock acquisition — it
        never reaches the per-group admission path."""
        with self._lock:
            self._counts["requests"] += 1
            self._counts["quota_throttled"] += 1

    def count(self, field: str) -> int:
        with self._lock:
            return self._counts[field]

    def observe_upstream(self, group: str, seconds: float) -> None:
        self.upstream_latency.get(group, self.upstream_latency["stable"]) \
            .observe(seconds)

    def raw_counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def snapshot(self) -> dict[str, Any]:
        from predictionio_tpu.core.wire import snake_to_camel

        with self._lock:
            counts = dict(self._counts)
        return {
            **{snake_to_camel(k): v for k, v in counts.items()},
            "upstreamLatency": {
                group: hist.snapshot().summary_ms()
                for group, hist in self.upstream_latency.items()
            },
        }


def router_collector(stats: RouterStats, membership: Any,
                     canary: Any) -> Any:
    """Registry adapter (obs/registry.py): router counters, per-backend
    membership state gauge, canary weight/abort gauges, and the
    upstream latency histograms by replica group."""

    def collect() -> list[Metric]:
        out = [
            Metric(
                name=f"pio_router_{field}_total", kind="counter",
                help=f"RouterStats counter {field!r} (fleet/stats.py)",
                samples=[({}, float(value))],
            )
            for field, value in stats.raw_counts().items()
        ]
        state = Metric(
            name="pio_router_backend_up", kind="gauge",
            help="Fleet membership state per backend: 1 up, 0 down")
        inflight = Metric(
            name="pio_router_backend_inflight", kind="gauge",
            help="Requests currently forwarded to this backend")
        starved = Metric(
            name="pio_router_probe_starved_total", kind="counter",
            help="Probe timeouts ignored because the backend's data "
                 "path was demonstrably healthy (breaker closed, "
                 "recent forwarded success) — the 1s-probe-under-"
                 "saturation pitfall; see docs/fleet.md \"Healthy "
                 "fleet marked down under load\"")
        for doc in membership.snapshot():
            labels = {"backend": doc["id"], "group": doc["group"]}
            state.samples.append(
                (labels, 1.0 if doc["state"] == "up" else 0.0))
            inflight.samples.append((labels, float(doc["inflight"])))
            starved.samples.append(
                (labels, float(doc.get("probeStarved", 0))))
        out.append(state)
        out.append(inflight)
        out.append(starved)
        cs = canary.snapshot()
        out.append(Metric(
            name="pio_router_canary_weight_pct", kind="gauge",
            help="Share of traffic routed to the canary replica group",
            samples=[({}, float(cs["weightPct"]))]))
        out.append(Metric(
            name="pio_router_canary_aborted", kind="gauge",
            help="1 while the canary is guardrail-aborted, else 0",
            samples=[({}, 1.0 if cs["aborted"] else 0.0)]))
        out.append(Metric(
            name="pio_router_upstream_seconds", kind="histogram",
            help="Upstream request walltime by replica group "
                 "(connect+send+receive, retries excluded)",
            histograms=[
                ({"group": group}, hist.snapshot())
                for group, hist in stats.upstream_latency.items()
            ]))
        return out

    return collect
