"""Health-driven fleet membership with mark-down/mark-up hysteresis.

Every backend's ``/healthz`` AND ``/readyz`` are probed on a background
loop (PR 1 gave every server both surfaces; PR 6's engine server
additionally reports not-ready while a ``/reload`` is in flight, so a
replica mid-model-swap drains here automatically). Hysteresis keeps a
flapping replica from oscillating the routing table: ``down_after``
consecutive probe failures mark a backend DOWN, ``up_after``
consecutive successes mark it UP again. A DOWN backend stops receiving
routed traffic but keeps being probed — mark-up is automatic.

The probe clock is injectable (:class:`~predictionio_tpu.utils.
resilience.Clock`) and the loop can be driven synchronously
(:meth:`FleetMembership.probe_once`) so hysteresis transitions are
deterministic in tests without wall-time sleeps.

**Probe-starvation guard** (the 1s-probe-under-GIL-saturation pitfall,
measured in BENCH_router_r01 and written up in the docs/fleet.md
"Healthy fleet marked down under load" runbook): a probe that TIMES OUT
against a replica whose data path is demonstrably fine — breaker
closed, a successful forwarded exchange within the grace window — is
probe starvation, not replica death. The guard counts it
(``pio_router_probe_starved_total``), logs a pointed warning, and does
NOT advance the failure streak, so a saturated-but-serving fleet never
talks itself into a mark-down spiral. Hard probe failures (refused,
reset, non-200) and timeouts without recent data-path proof still
count against the streak exactly as before.

Concurrency: per-:class:`Backend` mutable state (probe streaks, state,
in-flight count) sits under the backend's own lock; the backend LIST is
lock-guarded too — the scale controller adds and removes replicas at
runtime (fleet/controller.py), so every view takes a snapshot copy.
Handler threads read state through the locked accessors.
"""

from __future__ import annotations

import dataclasses
import logging
import socket
import threading
from typing import Sequence

from predictionio_tpu.fleet.transport import BackendTransport, fan_out
from predictionio_tpu.utils.resilience import (
    SYSTEM_CLOCK,
    CircuitBreaker,
    Clock,
    Resilience,
    RetryPolicy,
)

logger = logging.getLogger(__name__)

UP, DOWN = "up", "down"

STABLE, CANARY = "stable", "canary"


@dataclasses.dataclass(frozen=True)
class BackendSpec:
    """One replica's address and rollout group, parsed from
    ``host:port`` (stable) / ``pio router --canary-backend`` (canary).
    Behind a multi-engine gateway (fleet/gateway.py) the spec also
    names the ENGINE whose group this replica belongs to, so flattened
    fleet snapshots and metric labels attribute every replica to its
    tenant ("" for the classic single-engine router)."""

    host: str
    port: int
    group: str = STABLE
    id: str = ""
    engine: str = ""

    def __post_init__(self):
        if not self.id:
            object.__setattr__(self, "id", f"{self.host}:{self.port}")

    @classmethod
    def parse(cls, addr: str, group: str = STABLE,
              engine: str = "") -> "BackendSpec":
        host, sep, port = addr.rpartition(":")
        if not sep or not port.isdigit():
            raise ValueError(f"backend address {addr!r} is not host:port")
        return cls(host=host or "127.0.0.1", port=int(port), group=group,
                   engine=engine)


class Backend:
    """One replica: transport pool, resilience policy (breaker), and
    lock-guarded membership state."""

    def __init__(self, spec: BackendSpec,
                 breaker_threshold: int = 3,
                 breaker_reset_s: float = 5.0,
                 clock: Clock = SYSTEM_CLOCK):
        self.spec = spec
        self.transport = BackendTransport(spec.host, spec.port)
        #: max_attempts=1 — the ROUTER owns retries (on a different
        #: replica, never this one); the policy contributes breaker
        #: accounting and failure classification per attempt
        self.resilience = Resilience(
            f"router/{spec.id}",
            policy=RetryPolicy(max_attempts=1),
            breaker=CircuitBreaker(
                f"router/{spec.id}",
                failure_threshold=breaker_threshold,
                reset_timeout=breaker_reset_s,
                clock=clock),
            clock=clock,
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._state = UP
        self._ok_streak = 0
        self._fail_streak = 0
        self._last_error: str | None = None
        self._inflight = 0
        self._transitions = 0
        self._last_data_ok: float | None = None
        self._probe_starved = 0

    # -- membership state (locked at writers and readers) -------------------
    @property
    def id(self) -> str:
        return self.spec.id

    @property
    def group(self) -> str:
        return self.spec.group

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def is_routable(self) -> bool:
        """UP and not breaker-open. A half-open breaker stays routable:
        its single admitted probe is exactly how the breaker re-learns
        the replica's health."""
        with self._lock:
            if self._state != UP:
                return False
        breaker = self.resilience.breaker
        return breaker is None or breaker.state != "open"

    def begin(self) -> None:
        with self._lock:
            self._inflight += 1

    def done(self) -> None:
        with self._lock:
            self._inflight -= 1

    # -- probe-starvation guard (module docstring) ---------------------------
    def record_data_ok(self) -> None:
        """A forwarded exchange succeeded — the data-path proof the
        starvation guard checks before trusting a probe timeout."""
        with self._lock:
            self._last_data_ok = self._clock.monotonic()

    def data_ok_within(self, grace_s: float) -> bool:
        with self._lock:
            last = self._last_data_ok
        return (last is not None
                and self._clock.monotonic() - last <= grace_s)

    def record_probe_starved(self) -> None:
        with self._lock:
            self._probe_starved += 1

    @property
    def probe_starved(self) -> int:
        with self._lock:
            return self._probe_starved

    def record_probe(self, ok: bool, error: str | None,
                     down_after: int, up_after: int) -> str | None:
        """Fold one probe result into the hysteresis streaks. Returns
        the new state when a transition happened, else None."""
        with self._lock:
            if ok:
                self._ok_streak += 1
                self._fail_streak = 0
                self._last_error = None
                if self._state == DOWN and self._ok_streak >= up_after:
                    self._state = UP
                    self._transitions += 1
                    return UP
            else:
                self._fail_streak += 1
                self._ok_streak = 0
                self._last_error = error
                if self._state == UP and self._fail_streak >= down_after:
                    self._state = DOWN
                    self._transitions += 1
                    return DOWN
        return None

    def mark_down(self, error: str) -> bool:
        """Immediate mark-down from the DATA path (a forward failed
        hard) — the probe loop will mark it back up. Returns True on an
        actual transition."""
        with self._lock:
            self._ok_streak = 0
            self._last_error = error
            if self._state == UP:
                self._state = DOWN
                self._transitions += 1
                return True
        return False

    def snapshot(self) -> dict:
        with self._lock:
            doc = {
                "id": self.spec.id,
                "group": self.spec.group,
                # the single-engine router's snapshot shape is pinned
                # by the pre-gateway suite: the engine key appears only
                # when a gateway stamped one
                **({"engine": self.spec.engine} if self.spec.engine
                   else {}),
                "state": self._state,
                "inflight": self._inflight,
                "okStreak": self._ok_streak,
                "failStreak": self._fail_streak,
                "transitions": self._transitions,
                "probeStarved": self._probe_starved,
                **({"lastError": self._last_error}
                   if self._last_error else {}),
            }
        breaker = self.resilience.breaker
        if breaker is not None:
            doc["breaker"] = {"state": breaker.state, "opens": breaker.opens}
        return doc

    def close(self) -> None:
        self.transport.close()


class FleetMembership:
    """The probe loop + routable-backend views (module docstring)."""

    def __init__(self, backends: Sequence[Backend],
                 probe_interval_s: float = 1.0,
                 probe_timeout_s: float = 1.0,
                 down_after: int = 2,
                 up_after: int = 2,
                 starvation_grace_s: float = 10.0):
        self._backends = list(backends)
        self._backends_lock = threading.Lock()
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.down_after = max(1, down_after)
        self.up_after = max(1, up_after)
        #: how recent a data-path success must be for a probe TIMEOUT
        #: to count as starvation rather than death (module docstring)
        self.starvation_grace_s = starvation_grace_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # guards the start/stop lifecycle (NOT the probe cycle):
        # start() is reachable from the router's admin-sync thread via
        # gateway registration, so the check-then-spawn must not race a
        # concurrent start()/stop()
        self._lifecycle = threading.Lock()

    # -- views --------------------------------------------------------------
    @property
    def backends(self) -> list[Backend]:
        """Snapshot copy — the list mutates at runtime (scale events)."""
        with self._backends_lock:
            return list(self._backends)

    def routable(self, group: str | None = None,
                 exclude: frozenset[str] | tuple = ()) -> list[Backend]:
        return [
            b for b in self.backends
            if (group is None or b.group == group)
            and b.id not in exclude
            and b.is_routable()
        ]

    def by_id(self, backend_id: str) -> Backend | None:
        return next((b for b in self.backends if b.id == backend_id), None)

    def snapshot(self) -> list[dict]:
        return [b.snapshot() for b in self.backends]

    def probe_starved_total(self) -> int:
        return sum(b.probe_starved for b in self.backends)

    # -- runtime scale events (fleet/controller.py) --------------------------
    def add(self, backend: Backend) -> None:
        """Join a replica at runtime — the probe loop picks it up on
        its next pass; join it DOWN (``backend.mark_down``) when the
        process behind it is still starting."""
        with self._backends_lock:
            if any(b.id == backend.id for b in self._backends):
                raise ValueError(f"backend {backend.id!r} already joined")
            self._backends.append(backend)
        logger.info("fleet backend %s joined membership", backend.id)

    def remove(self, backend_id: str) -> Backend | None:
        """Detach a replica: it stops being routable/probed NOW. The
        caller owns the drain story (the supervisor drains via
        /readyz before SIGTERM — fleet/supervisor.py)."""
        with self._backends_lock:
            backend = next((b for b in self._backends
                            if b.id == backend_id), None)
            if backend is not None:
                self._backends.remove(backend)
        if backend is not None:
            backend.close()
            logger.info("fleet backend %s left membership", backend_id)
        return backend

    # -- probing ------------------------------------------------------------
    def probe_backend(self, backend: Backend) \
            -> tuple[bool, str | None, bool]:
        """One health probe: ``/healthz`` then ``/readyz``, both must
        answer 200 inside ``probe_timeout_s`` each. Returns
        ``(ok, error, timed_out)`` — the timeout flag feeds the
        starvation guard, which must distinguish "slow to answer" from
        "refused/reset/unready" (only the former is starvation)."""
        for path in ("/healthz", "/readyz"):
            try:
                response = backend.transport.request(
                    "GET", path, timeout=self.probe_timeout_s)
            except (TimeoutError, socket.timeout) as exc:
                return False, f"{path}: {exc}", True
            except Exception as exc:  # transport/protocol failures
                return False, f"{path}: {exc}", False
            if response.status != 200:
                return False, f"{path}: HTTP {response.status}", False
        return True, None, False

    def _probe_and_record(self, backend: Backend) -> None:
        ok, error, timed_out = self.probe_backend(backend)
        if not ok and timed_out and self._starved(backend):
            # probe starvation, not replica death (module docstring):
            # the data path is succeeding, so the timeout says the
            # PROBE lost a scheduling race, and marking the replica
            # down would concentrate load on the survivors — the
            # mark-down spiral the runbook describes
            backend.record_probe_starved()
            logger.warning(
                "fleet backend %s probe timed out while its data path "
                "is healthy (breaker closed, success within %.0fs) — "
                "counting pio_router_probe_starved_total, NOT marking "
                "down. Size PIO_ROUTER_PROBE_TIMEOUT_S for the "
                "replica's p99 under load (docs/fleet.md, \"Healthy "
                "fleet marked down under load\")",
                backend.id, self.starvation_grace_s)
            return
        transition = backend.record_probe(
            ok, error, self.down_after, self.up_after)
        if transition is not None:
            log = logger.warning if transition == DOWN else logger.info
            log("fleet backend %s marked %s%s", backend.id, transition,
                f" ({error})" if error else "")

    def _starved(self, backend: Backend) -> bool:
        breaker = backend.resilience.breaker
        return ((breaker is None or breaker.state == "closed")
                and backend.data_ok_within(self.starvation_grace_s))

    def probe_once(self) -> None:
        """One synchronous probe pass over every backend — the loop
        body, also the deterministic test hook. Backends are probed
        CONCURRENTLY: a black-holed replica eats its own probe timeout,
        not everyone else's — sequential probing made one partitioned
        backend stretch every pass by its timeout, delaying mark-down
        and mark-up of healthy-streak transitions fleet-wide."""
        fan_out(self.backends, self._probe_and_record)

    def _run(self) -> None:
        while not self._stop.is_set():
            self.probe_once()
            # Event.wait doubles as the interval sleep AND the prompt
            # stop signal (a bare sleep would hold stop() for a full
            # interval)
            self._stop.wait(self.probe_interval_s)

    def start(self) -> None:
        with self._lifecycle:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="pio-fleet-probe", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._lifecycle:
            thread, self._thread = self._thread, None
        if thread is not None:
            # join OUTSIDE the lifecycle lock: a probe pass can run up
            # to the probe timeout, and holding the lock here would
            # stall a concurrent start() for that long
            thread.join(timeout=5)
        for backend in self.backends:
            backend.close()
