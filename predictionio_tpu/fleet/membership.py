"""Health-driven fleet membership with mark-down/mark-up hysteresis.

Every backend's ``/healthz`` AND ``/readyz`` are probed on a background
loop (PR 1 gave every server both surfaces; PR 6's engine server
additionally reports not-ready while a ``/reload`` is in flight, so a
replica mid-model-swap drains here automatically). Hysteresis keeps a
flapping replica from oscillating the routing table: ``down_after``
consecutive probe failures mark a backend DOWN, ``up_after``
consecutive successes mark it UP again. A DOWN backend stops receiving
routed traffic but keeps being probed — mark-up is automatic.

The probe clock is injectable (:class:`~predictionio_tpu.utils.
resilience.Clock`) and the loop can be driven synchronously
(:meth:`FleetMembership.probe_once`) so hysteresis transitions are
deterministic in tests without wall-time sleeps.

Concurrency: per-:class:`Backend` mutable state (probe streaks, state,
in-flight count) sits under the backend's own lock; the membership
object itself is immutable after construction apart from the loop
thread handle. Handler threads read state through the locked accessors.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Sequence

from predictionio_tpu.fleet.transport import BackendTransport, fan_out
from predictionio_tpu.utils.resilience import (
    SYSTEM_CLOCK,
    CircuitBreaker,
    Clock,
    Resilience,
    RetryPolicy,
)

logger = logging.getLogger(__name__)

UP, DOWN = "up", "down"

STABLE, CANARY = "stable", "canary"


@dataclasses.dataclass(frozen=True)
class BackendSpec:
    """One replica's address and rollout group, parsed from
    ``host:port`` (stable) / ``pio router --canary-backend`` (canary)."""

    host: str
    port: int
    group: str = STABLE
    id: str = ""

    def __post_init__(self):
        if not self.id:
            object.__setattr__(self, "id", f"{self.host}:{self.port}")

    @classmethod
    def parse(cls, addr: str, group: str = STABLE) -> "BackendSpec":
        host, sep, port = addr.rpartition(":")
        if not sep or not port.isdigit():
            raise ValueError(f"backend address {addr!r} is not host:port")
        return cls(host=host or "127.0.0.1", port=int(port), group=group)


class Backend:
    """One replica: transport pool, resilience policy (breaker), and
    lock-guarded membership state."""

    def __init__(self, spec: BackendSpec,
                 breaker_threshold: int = 3,
                 breaker_reset_s: float = 5.0,
                 clock: Clock = SYSTEM_CLOCK):
        self.spec = spec
        self.transport = BackendTransport(spec.host, spec.port)
        #: max_attempts=1 — the ROUTER owns retries (on a different
        #: replica, never this one); the policy contributes breaker
        #: accounting and failure classification per attempt
        self.resilience = Resilience(
            f"router/{spec.id}",
            policy=RetryPolicy(max_attempts=1),
            breaker=CircuitBreaker(
                f"router/{spec.id}",
                failure_threshold=breaker_threshold,
                reset_timeout=breaker_reset_s,
                clock=clock),
            clock=clock,
        )
        self._lock = threading.Lock()
        self._state = UP
        self._ok_streak = 0
        self._fail_streak = 0
        self._last_error: str | None = None
        self._inflight = 0
        self._transitions = 0

    # -- membership state (locked at writers and readers) -------------------
    @property
    def id(self) -> str:
        return self.spec.id

    @property
    def group(self) -> str:
        return self.spec.group

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def is_routable(self) -> bool:
        """UP and not breaker-open. A half-open breaker stays routable:
        its single admitted probe is exactly how the breaker re-learns
        the replica's health."""
        with self._lock:
            if self._state != UP:
                return False
        breaker = self.resilience.breaker
        return breaker is None or breaker.state != "open"

    def begin(self) -> None:
        with self._lock:
            self._inflight += 1

    def done(self) -> None:
        with self._lock:
            self._inflight -= 1

    def record_probe(self, ok: bool, error: str | None,
                     down_after: int, up_after: int) -> str | None:
        """Fold one probe result into the hysteresis streaks. Returns
        the new state when a transition happened, else None."""
        with self._lock:
            if ok:
                self._ok_streak += 1
                self._fail_streak = 0
                self._last_error = None
                if self._state == DOWN and self._ok_streak >= up_after:
                    self._state = UP
                    self._transitions += 1
                    return UP
            else:
                self._fail_streak += 1
                self._ok_streak = 0
                self._last_error = error
                if self._state == UP and self._fail_streak >= down_after:
                    self._state = DOWN
                    self._transitions += 1
                    return DOWN
        return None

    def mark_down(self, error: str) -> bool:
        """Immediate mark-down from the DATA path (a forward failed
        hard) — the probe loop will mark it back up. Returns True on an
        actual transition."""
        with self._lock:
            self._ok_streak = 0
            self._last_error = error
            if self._state == UP:
                self._state = DOWN
                self._transitions += 1
                return True
        return False

    def snapshot(self) -> dict:
        with self._lock:
            doc = {
                "id": self.spec.id,
                "group": self.spec.group,
                "state": self._state,
                "inflight": self._inflight,
                "okStreak": self._ok_streak,
                "failStreak": self._fail_streak,
                "transitions": self._transitions,
                **({"lastError": self._last_error}
                   if self._last_error else {}),
            }
        breaker = self.resilience.breaker
        if breaker is not None:
            doc["breaker"] = {"state": breaker.state, "opens": breaker.opens}
        return doc

    def close(self) -> None:
        self.transport.close()


class FleetMembership:
    """The probe loop + routable-backend views (module docstring)."""

    def __init__(self, backends: Sequence[Backend],
                 probe_interval_s: float = 1.0,
                 probe_timeout_s: float = 1.0,
                 down_after: int = 2,
                 up_after: int = 2):
        self.backends = list(backends)
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.down_after = max(1, down_after)
        self.up_after = max(1, up_after)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- views --------------------------------------------------------------
    def routable(self, group: str | None = None,
                 exclude: frozenset[str] | tuple = ()) -> list[Backend]:
        return [
            b for b in self.backends
            if (group is None or b.group == group)
            and b.id not in exclude
            and b.is_routable()
        ]

    def by_id(self, backend_id: str) -> Backend | None:
        return next((b for b in self.backends if b.id == backend_id), None)

    def snapshot(self) -> list[dict]:
        return [b.snapshot() for b in self.backends]

    # -- probing ------------------------------------------------------------
    def probe_backend(self, backend: Backend) -> tuple[bool, str | None]:
        """One health probe: ``/healthz`` then ``/readyz``, both must
        answer 200 inside ``probe_timeout_s`` each."""
        for path in ("/healthz", "/readyz"):
            try:
                response = backend.transport.request(
                    "GET", path, timeout=self.probe_timeout_s)
            except Exception as exc:  # transport/protocol failures
                return False, f"{path}: {exc}"
            if response.status != 200:
                return False, f"{path}: HTTP {response.status}"
        return True, None

    def _probe_and_record(self, backend: Backend) -> None:
        ok, error = self.probe_backend(backend)
        transition = backend.record_probe(
            ok, error, self.down_after, self.up_after)
        if transition is not None:
            log = logger.warning if transition == DOWN else logger.info
            log("fleet backend %s marked %s%s", backend.id, transition,
                f" ({error})" if error else "")

    def probe_once(self) -> None:
        """One synchronous probe pass over every backend — the loop
        body, also the deterministic test hook. Backends are probed
        CONCURRENTLY: a black-holed replica eats its own probe timeout,
        not everyone else's — sequential probing made one partitioned
        backend stretch every pass by its timeout, delaying mark-down
        and mark-up of healthy-streak transitions fleet-wide."""
        fan_out(self.backends, self._probe_and_record)

    def _run(self) -> None:
        while not self._stop.is_set():
            self.probe_once()
            # Event.wait doubles as the interval sleep AND the prompt
            # stop signal (a bare sleep would hold stop() for a full
            # interval)
            self._stop.wait(self.probe_interval_s)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="pio-fleet-probe", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        for backend in self.backends:
            backend.close()
