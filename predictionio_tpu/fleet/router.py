"""The fleet routing core: pick, forward, retry, hedge, degrade.

One request's life through :meth:`FleetRouter.route`:

1. **admission** — bounded in-flight slots; a saturated fleet sheds
   with 503 + Retry-After instead of queueing into collapse;
2. **deadline** — ``X-PIO-Deadline-Ms`` (tightened by the router's own
   ``request_deadline_ms``) becomes an absolute deadline; an already
   -dead request is never forwarded, and every forward carries the
   REMAINING budget so the backend's own expiry machinery (PR 1/PR 3)
   sees the end-to-end number;
3. **pick** — the canary controller splits traffic stable/canary by
   weight; within the group the least-loaded routable replica wins
   (UP per membership, breaker not open). A group with no routable
   replica spills to the other group (degraded-but-correct, counted)
   rather than failing the request;
4. **forward** — the exchange runs under the backend's per-replica
   :class:`~predictionio_tpu.utils.resilience.Resilience` (breaker
   accounting, transient classification via the shared
   ``is_transient_http_status`` contract);
5. **hedge** (opt-in) — when the primary has not answered after a
   p99-derived delay and a second routable replica exists, a hedge
   fires there and the first answer wins (tail-latency insurance, The
   Tail at Scale);
6. **retry** — a failed or breaker-open replica gets ONE transparent
   retry on a DIFFERENT routable replica, never the same one;
7. **outcome** — canary guardrails fold the result in (5xx/transport
   failures count against the canary; client-side 4xx do not) and may
   auto-abort the rollout.

A request only surfaces 5xx to the client when every routable replica
failed it — "zero 5xx while a healthy replica exists" is the chaos
suite's pinned invariant (tests/test_fleet_router.py).
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import threading
import time
from concurrent.futures import FIRST_COMPLETED, Future, ThreadPoolExecutor, wait
from typing import Callable, Mapping

from predictionio_tpu.api.http_base import (
    parse_deadline_budget,
    retry_after_header,
)
from predictionio_tpu.fleet.canary import CanaryController, GuardrailConfig
from predictionio_tpu.fleet.membership import (
    CANARY,
    Backend,
    BackendSpec,
    FleetMembership,
)
from predictionio_tpu.fleet.stats import RouterStats
from predictionio_tpu.fleet.transport import UpstreamResponse
from predictionio_tpu.obs.histogram import LatencyHistogram
from predictionio_tpu.obs.trace import (
    PARENT_SPAN_HEADER,
    TRACE_ID_HEADER,
    Trace,
    active_trace,
)
from predictionio_tpu.utils.resilience import (
    SYSTEM_CLOCK,
    Clock,
    StorageUnavailableError,
    TransientError,
    is_transient_http_status,
    resilient,
)

logger = logging.getLogger(__name__)

#: request headers the router forwards verbatim to the backend (plus
#: the recomputed deadline and the correlation id); the experiment
#: attribution pair is how an assigned variant id reaches the engine
#: server's response stamp + feedback event (experiment/controller.py)
_FORWARD_HEADERS = ("content-type", "accept",
                    "x-pio-experiment", "x-pio-variant")


class UpstreamStatusError(TransientError):
    """The upstream ANSWERED with a transient status (5xx/429) — a
    health signal for the breaker, but the response itself survives on
    the exception so the router can still return it when no other
    replica is available."""

    def __init__(self, backend_id: str, response: UpstreamResponse):
        super().__init__(f"upstream {backend_id} answered "
                         f"HTTP {response.status}")
        self.response = response


@dataclasses.dataclass
class RouterResponse:
    """What the HTTP layer writes back: status, raw body bytes (passed
    through, never re-encoded), content type, extra headers — plus the
    routing metadata the access log and traces report (which replica
    answered, how many attempts it took, whether the hedge/retry
    machinery fired, and — behind a multi-engine gateway — WHICH
    deployment the request resolved to: before the ``engine`` field the
    access log and root trace spans had nowhere to record that)."""

    status: int
    body: bytes
    content_type: str = "application/json; charset=UTF-8"
    headers: dict[str, str] = dataclasses.field(default_factory=dict)
    #: routing metadata (None/0/False on non-routed responses)
    backend_id: str | None = None
    group: str | None = None
    attempts: int = 0
    hedged: bool = False
    retried: bool = False
    #: the engine (tenant) this request resolved to (fleet/gateway.py);
    #: None on non-routed responses and on pre-resolution rejects
    engine: str | None = None

    @classmethod
    def error(cls, status: int, message: str,
              headers: dict[str, str] | None = None) -> "RouterResponse":
        import json

        return cls(status, json.dumps({"message": message}).encode(),
                   headers=headers or {})


class AdmissionGate:
    """The bounded-admission in-flight counter, factored out of
    :class:`FleetRouter` so a multi-engine gateway (fleet/gateway.py)
    can share ONE gate across every engine group: the 503 shed is a
    verdict about GLOBAL router pressure — per-engine budgets are the
    quota layer's job (429, ``EngineQuota``), and an engine-local 503
    would let one tenant's burst masquerade as fleet saturation."""

    def __init__(self, max_inflight: int):
        self.max_inflight = max_inflight
        self._inflight = 0
        self._lock = threading.Lock()

    def admit(self) -> bool:
        with self._lock:
            if self._inflight >= self.max_inflight:
                return False
            self._inflight += 1
            return True

    def release(self) -> None:
        with self._lock:
            self._inflight -= 1

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def headroom(self) -> float:
        """Free fraction of the gate, 0.0 (saturated) .. 1.0 (idle).
        An uncapped gate always reports full headroom. The burst-credit
        layer (fleet/gateway.py) reads this to decide whether borrowed
        capacity is really idle capacity."""
        if self.max_inflight <= 0:
            return 1.0
        with self._lock:
            return max(0.0, 1.0 - self._inflight / self.max_inflight)


class HedgePolicy:
    """When and how late to fire a tail-latency hedge.

    The delay derives from the observed upstream latency distribution:
    ``quantile`` (default p99) of everything the router has seen,
    clamped to ``[min_delay_ms, max_delay_ms]``. Until ``min_samples``
    observations exist the clamp floor applies — hedging too eagerly on
    no data would double fleet load for nothing. Deterministic given
    its observation history (pinned on ManualClock-style tests: no
    clock reads, no randomness)."""

    def __init__(self, min_delay_ms: float = 10.0,
                 max_delay_ms: float = 500.0,
                 quantile: float = 0.99,
                 min_samples: int = 20):
        self.min_delay_s = min_delay_ms / 1e3
        self.max_delay_s = max_delay_ms / 1e3
        self.quantile = quantile
        self.min_samples = min_samples
        self._latency = LatencyHistogram()

    def observe(self, seconds: float) -> None:
        self._latency.observe(seconds)

    def delay_s(self) -> float:
        """Seconds to wait for the primary before hedging."""
        snap = self._latency.snapshot()
        if snap.count < self.min_samples:
            return self.min_delay_s
        q = snap.quantile(self.quantile)
        if q is None:
            return self.min_delay_s
        return min(self.max_delay_s, max(self.min_delay_s, q))

    def should_hedge(self, alternates: int,
                     remaining_budget: float | None) -> bool:
        """A hedge needs somewhere to go and enough budget that the
        hedged attempt could still answer in time."""
        if alternates <= 0:
            return False
        if remaining_budget is not None \
                and remaining_budget <= self.delay_s():
            return False
        return True


def _env_field(key: str, default, cast):
    """``PIO_ROUTER_<KEY>`` env-overridable frozen-dataclass default,
    read at construction time (the ServerConfig discipline; shared
    implementation in utils/envcfg.py)."""
    from predictionio_tpu.utils.envcfg import env_field

    return env_field("PIO_ROUTER_", key, default, cast)


def _cast_bool(raw: str) -> bool:
    return raw.strip().lower() in ("1", "true", "yes", "on")


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """`pio router` knobs (docs/fleet.md has the full table)."""

    ip: str = "0.0.0.0"
    port: int = 8100
    #: stable replica addresses, ``host:port`` — these become the
    #: DEFAULT engine's backend group (fleet/gateway.py)
    backends: tuple[str, ...] = ()
    #: canary replica addresses (the new model generation)
    canary_backends: tuple[str, ...] = ()
    #: named engine groups behind this one router
    #: (:class:`~predictionio_tpu.fleet.gateway.EngineSpec` instances;
    #: `pio router --engine name=...,backend=...`): each engine gets
    #: its OWN membership, breakers, canary controller, hedging state
    #: and quota — blast-radius isolation per tenant (docs/fleet.md
    #: "Multi-engine routing")
    engines: tuple = ()
    #: the engine bare ``/queries.json`` routes to — zero breakage for
    #: single-engine clients. When ``backends`` above is non-empty it
    #: names the engine built from them; otherwise it must name one of
    #: ``engines`` (falls back to the first declared engine)
    default_engine: str = _env_field("DEFAULT_ENGINE", "default", str)
    #: per-engine admission defaults for engines that do not set their
    #: own (PIO_ROUTER_ENGINE_*): token-bucket qps (0 = unlimited),
    #: burst (0 = max(1, qps)), and per-engine in-flight cap (0 = only
    #: the GLOBAL max_inflight applies). Over-quota requests answer
    #: 429 + Retry-After; the 503 shed stays a global-pressure verdict
    engine_quota_qps: float = _env_field("ENGINE_QPS", 0.0, float)
    engine_quota_burst: float = _env_field("ENGINE_BURST", 0.0, float)
    engine_max_inflight: int = _env_field("ENGINE_MAX_INFLIGHT", 0, int)
    #: burst-credit reservoir cap for engines that do not set their own
    #: (0 = credits off): under-quota refill accrues as credits, spent
    #: during bursts while the shared gate has headroom — weighted fair
    #: queueing atop the token bucket (docs/fleet.md "Per-tenant
    #: elasticity")
    engine_burst_credits: float = _env_field("ENGINE_BURST_CREDITS",
                                             0.0, float)
    #: membership probe loop (fleet/membership.py)
    probe_interval_s: float = _env_field("PROBE_INTERVAL_S", 1.0, float)
    probe_timeout_s: float = _env_field("PROBE_TIMEOUT_S", 1.0, float)
    down_after: int = _env_field("DOWN_AFTER", 2, int)
    up_after: int = _env_field("UP_AFTER", 2, int)
    #: per-backend breaker (utils/resilience.CircuitBreaker)
    breaker_threshold: int = _env_field("BREAKER_THRESHOLD", 3, int)
    breaker_reset_s: float = _env_field("BREAKER_RESET_S", 5.0, float)
    #: socket bound per upstream attempt (tightened by the deadline)
    upstream_timeout_s: float = _env_field("UPSTREAM_TIMEOUT_S", 30.0, float)
    #: bounded admission: concurrent requests in flight through the
    #: router; beyond it requests shed with 503 + Retry-After
    max_inflight: int = _env_field("MAX_INFLIGHT", 128, int)
    #: router-imposed request budget (0 = none); clients may only
    #: tighten via X-PIO-Deadline-Ms
    request_deadline_ms: float = _env_field("REQUEST_DEADLINE_MS", 0.0, float)
    #: tail-latency hedging (off by default: it spends fleet capacity)
    hedge: bool = _env_field("HEDGE", False, _cast_bool)
    hedge_min_delay_ms: float = _env_field("HEDGE_MIN_DELAY_MS", 10.0, float)
    hedge_max_delay_ms: float = _env_field("HEDGE_MAX_DELAY_MS", 500.0, float)
    #: initial canary traffic share (0..100) and guardrails
    canary_weight_pct: float = _env_field("CANARY_WEIGHT_PCT", 0.0, float)
    guardrail_min_requests: int = _env_field("GUARDRAIL_MIN_REQUESTS", 20, int)
    guardrail_max_error_rate: float = _env_field(
        "GUARDRAIL_MAX_ERROR_RATE", 0.5, float)
    guardrail_max_p99_ms: float = _env_field("GUARDRAIL_MAX_P99_MS", 0.0, float)
    guardrail_window: int = _env_field("GUARDRAIL_WINDOW", 200, int)
    #: when set, /fleet/canary and /stop require ?accessKey=<router_key>
    router_key: str | None = None
    #: structured access logs; None defers to PIO_ACCESS_LOG
    access_log: bool | None = None
    #: per-request root spans on the forward path (admission, pick,
    #: attempt/retry/hedge), with trace context forwarded to replicas
    #: so the fleet trace stitches back together; None defers to the
    #: PIO_TRACE env var (the ServerConfig discipline)
    tracing: bool | None = None
    #: socket bound for every scrape-time fan-out fetch — worker peers,
    #: replica /metrics behind /fleet/metrics, /traces.json stitching.
    #: Every cross-process fetch on these paths must be timed (the
    #: untimed-blocking-io lint contract): a wedged peer costs one
    #: timeout, never a hung scrape
    scrape_timeout_s: float = _env_field("SCRAPE_TIMEOUT_S", 2.0, float)
    #: directory where `--workers N` processes register their loopback
    #: peer endpoints (fleet/workers.py) so a /metrics scrape landing
    #: on one worker can report all of them; None = no worker peering
    worker_spool_dir: str | None = None
    #: cadence of the shared-admin-state sync loop under `--workers N`
    #: (fleet/workers.py admin spool): canary weight mutations and
    #: guardrail abort verdicts published by ANY worker are applied by
    #: every sibling within about this many seconds
    admin_sync_interval_s: float = _env_field("ADMIN_SYNC_INTERVAL_S",
                                              0.5, float)
    #: bind with SO_REUSEPORT so N router worker processes share one
    #: listen port (`pio router --workers N`): one CPython router tops
    #: out on its GIL long before the fleet does — workers scale the
    #: router tier horizontally exactly like replicas scale the model
    #: tier. Caveat: each worker holds its own canary/membership state
    #: (docs/fleet.md), so canary admin calls address ONE worker.
    reuse_port: bool = False

    def guardrail(self) -> GuardrailConfig:
        return GuardrailConfig(
            min_requests=self.guardrail_min_requests,
            max_error_rate=self.guardrail_max_error_rate,
            max_p99_ms=self.guardrail_max_p99_ms,
            window=self.guardrail_window,
        )


class FleetRouter:
    """Transport-free routing logic; the HTTP surface lives in
    api/router_server.py."""

    def __init__(self, config: RouterConfig,
                 membership: FleetMembership | None = None,
                 canary: CanaryController | None = None,
                 stats: RouterStats | None = None,
                 hedge_policy: HedgePolicy | None = None,
                 admission: AdmissionGate | None = None,
                 engine: str = "",
                 clock: Clock = SYSTEM_CLOCK):
        self.config = config
        #: which engine group this router serves, for snapshot/metric
        #: attribution ("" for the classic single-engine router)
        self.engine = engine
        if membership is None:
            backends = [
                Backend(BackendSpec.parse(addr, group, engine=engine),
                        breaker_threshold=config.breaker_threshold,
                        breaker_reset_s=config.breaker_reset_s,
                        clock=clock)
                for group, addrs in (("stable", config.backends),
                                     ("canary", config.canary_backends))
                for addr in addrs
            ]
            membership = FleetMembership(
                backends,
                probe_interval_s=config.probe_interval_s,
                probe_timeout_s=config.probe_timeout_s,
                down_after=config.down_after,
                up_after=config.up_after)
        self.membership = membership
        self.canary = canary or CanaryController(
            weight_pct=config.canary_weight_pct,
            guardrail=config.guardrail())
        if (self.canary.weight_pct > 0.0
                and not any(b.group == CANARY
                            for b in self.membership.backends)):
            # a positive weight with an empty canary set would send
            # weight% of picks through the spill path forever: the
            # group_spills alarm counter climbs on a healthy fleet and
            # the guardrail can never evaluate (no canary ever serves)
            logger.warning(
                "canary weight %.1f%% configured with no canary "
                "backends — forcing weight to 0", self.canary.weight_pct)
            self.canary.set_weight(0.0)
        self.stats = stats or RouterStats()
        self.hedge_policy = hedge_policy or HedgePolicy(
            min_delay_ms=config.hedge_min_delay_ms,
            max_delay_ms=config.hedge_max_delay_ms)
        #: bounded admission — shared across every engine group when a
        #: gateway fronts several (class docstring on AdmissionGate)
        self._admission = admission or AdmissionGate(config.max_inflight)
        #: fired (post-lock, best-effort) when the guardrail auto-abort
        #: latches — the HTTP layer publishes the verdict to the worker
        #: admin spool so every `--workers` sibling aborts too instead
        #: of each latching its own verdict (fleet/workers.py)
        self.on_canary_abort: "Callable[[], None] | None" = None
        #: rotation tiebreak for the least-loaded pick: under light or
        #: perfectly balanced load every replica's in-flight count is
        #: zero and a bare min() would pin all traffic to the first
        #: replica (itertools.count is a single C call, GIL-atomic)
        self._rr = itertools.count()
        #: hedge attempts run on pool threads so the handler can race
        #: primary vs hedge; sized for two attempts per admitted request
        self._pool = ThreadPoolExecutor(
            max_workers=max(4, 2 * config.max_inflight),
            thread_name_prefix="pio-router-hedge")

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self.membership.start()

    def close(self) -> None:
        self.membership.stop()
        self._pool.shutdown(wait=False)

    # -- admission + deadline -----------------------------------------------
    def _admit(self) -> bool:
        return self._admission.admit()

    def _release(self) -> None:
        self._admission.release()

    @property
    def inflight(self) -> int:
        """In-flight requests through the admission gate — the GLOBAL
        count when the gate is shared across engine groups."""
        return self._admission.inflight

    def _deadline_budget(self, headers: Mapping[str, str]) -> float | None:
        """Seconds of budget via the shared engine-server contract
        (http_base.parse_deadline_budget: the client header may only
        tighten). Raises ValueError on a malformed header (the
        caller's 400)."""
        return parse_deadline_budget(self.config.request_deadline_ms,
                                     headers)

    # -- the route ----------------------------------------------------------
    def route(self, body: bytes, headers: Mapping[str, str],
              request_id: str) -> RouterResponse:
        """Forward one ``POST /queries.json`` (module docstring). The
        ambient trace (bound by the HTTP handler when tracing is on)
        gains admission/pick/attempt spans; with tracing off the
        ``active_trace()`` read is the whole cost."""
        trace = active_trace()
        if not self._admit():
            self.stats.bump("requests")
            self.stats.bump("sheds")
            if trace is not None:
                trace.tags["outcome"] = "shed"
            return RouterResponse.error(
                503, "fleet saturated; retry shortly",
                {"Retry-After": retry_after_header(1.0)})
        try:
            try:
                budget = self._deadline_budget(headers)
            except ValueError as exc:
                self.stats.bump("requests")
                return RouterResponse.error(400, str(exc))
            deadline = (time.monotonic() + budget
                        if budget is not None else None)
            group = self.canary.pick_group()
            self.stats.bump_request(group)
            return self._route_with_retry(group, body, headers,
                                          request_id, deadline, trace)
        finally:
            self._release()

    def _remaining(self, deadline: float | None) -> float | None:
        if deadline is None:
            return None
        return deadline - time.monotonic()

    def _pick(self, group: str, exclude: set[str]) -> tuple[Backend | None, str]:
        """Least-loaded routable replica in ``group``; an empty group
        spills to the other one (counted). Returns (backend, group it
        actually came from)."""
        candidates = self.membership.routable(group, exclude=exclude)
        actual = group
        if not candidates:
            other = "canary" if group == "stable" else "stable"
            candidates = self.membership.routable(other, exclude=exclude)
            if candidates:
                self.stats.bump("group_spills")
                actual = other
        if not candidates:
            return None, actual
        # read each in-flight count ONCE: concurrent requests move the
        # counts between reads, and a min()-then-filter over live reads
        # can produce an empty tie set mid-burst
        loads = [(b.inflight, b) for b in candidates]
        lowest = min(load for load, _ in loads)
        ties = [b for load, b in loads if load == lowest]
        return ties[next(self._rr) % len(ties)], actual

    def _route_with_retry(self, group: str, body: bytes,
                          headers: Mapping[str, str], request_id: str,
                          deadline: float | None,
                          trace: Trace | None = None) -> RouterResponse:
        tried: set[str] = set()
        last_failure: BaseException | None = None
        #: hedge firings survive a failed attempt here — the ``hedged``
        #: flag _forward returns is lost when the attempt RAISES, and
        #: deriving it from len(tried) conflated a failed hedge with a
        #: retry in the access log's routing verdict
        meta = {"hedges": 0}
        retried = False
        for attempt in (0, 1):
            remaining = self._remaining(deadline)
            if remaining is not None and remaining <= 0:
                self.stats.bump("expired")
                out = RouterResponse.error(
                    503, "request deadline exceeded before a replica "
                         "could answer",
                    {"Retry-After": retry_after_header(1.0)})
                # a deadline blown AFTER attempt 0 already exchanged
                # with replicas (possibly a hedge pair) — the access
                # log's routing verdict must count them, not say 0
                out.attempts = len(tried)
                out.retried = retried
                out.hedged = meta["hedges"] > 0
                return out
            backend, actual_group = self._pick(group, tried)
            if backend is None:
                if last_failure is not None:
                    break  # no replica left to retry on
                self.stats.bump("no_backend")
                return RouterResponse.error(
                    503, "no healthy replica available",
                    {"Retry-After": retry_after_header(
                        max(1.0, self.membership.probe_interval_s))})
            if attempt > 0:
                self.stats.bump("retries")
                retried = True
            try:
                response, served_id, hedged = self._forward(
                    backend, actual_group, tried, body, headers,
                    request_id, deadline, trace,
                    label="retry" if attempt else "attempt", meta=meta)
                out = self._passthrough(response)
                out.backend_id = served_id
                out.group = actual_group
                out.attempts = attempt + 1 + meta["hedges"]
                out.retried = retried
                out.hedged = hedged or meta["hedges"] > 0
                return out
            except StorageUnavailableError as exc:
                self.stats.bump("upstream_errors")
                last_failure = exc
                tried.add(backend.id)
                continue
        # every routable replica failed: surface the most informative
        # thing we have — a real upstream response when one exists.
        # Pure TRANSPORT failure (no replica even answered — the
        # whole-group-killed case) is a retryable 503 + Retry-After,
        # not a 502: the client's correct move is to back off and
        # retry once the group's replicas return, and a dead tenant
        # must degrade to FAST bounded 503s behind the gateway
        # (docs/fleet.md "Multi-engine routing")
        response = _embedded_response(last_failure)
        if response is not None:
            out = self._passthrough(response)
        else:
            out = RouterResponse.error(
                503, f"no replica reachable: {last_failure}",
                {"Retry-After": retry_after_header(1.0)})
        # every exchanged replica is in `tried` on this path (the
        # except clause adds non-hedge failures, _forward adds both
        # hedge-race ids), so its size IS the attempt count
        out.attempts = max(1, len(tried))
        out.retried = retried
        out.hedged = meta["hedges"] > 0
        return out

    def _passthrough(self, response: UpstreamResponse) -> RouterResponse:
        out = RouterResponse(
            status=response.status,
            body=response.body,
            content_type=response.header(
                "content-type", "application/json; charset=UTF-8"),
        )
        for name in ("retry-after", "x-pio-trace-id"):
            value = response.header(name)
            if value:
                out.headers["-".join(p.capitalize()
                                     for p in name.split("-"))] = value
        return out

    # -- forwarding (single + hedged) ---------------------------------------
    def _forward_headers(self, headers: Mapping[str, str],
                         request_id: str, deadline: float | None,
                         trace: Trace | None = None,
                         parent_span: str = "") -> dict[str, str]:
        fwd = {"X-PIO-Request-Id": request_id}
        for name in _FORWARD_HEADERS:
            value = headers.get(name)
            if value:
                fwd[name] = value
        if trace is not None:
            # cross-process stitching (obs/stitch.py): the replica's
            # trace segment joins THIS trace, nested under the attempt
            # span whose id rides the parent-span header
            fwd[TRACE_ID_HEADER] = trace.trace_id
            if parent_span:
                fwd[PARENT_SPAN_HEADER] = parent_span
        else:
            # an untraced router still relays CLIENT-supplied context
            # so an upstream tracer (another router tier, a test
            # harness) keeps its continuity through this hop
            for name in (TRACE_ID_HEADER.lower(),
                         PARENT_SPAN_HEADER.lower()):
                value = headers.get(name)
                if value:
                    fwd[name] = value
        if deadline is not None:
            # the REMAINING budget, floored at 1ms: the backend must
            # see the end-to-end deadline, not the client's original
            remaining_ms = max(1.0, (deadline - time.monotonic()) * 1e3)
            fwd["X-PIO-Deadline-Ms"] = f"{remaining_ms:.0f}"
        return fwd

    def _exchange(self, backend: Backend, group: str,
                  body: bytes, headers: Mapping[str, str],
                  request_id: str, deadline: float | None,
                  trace: Trace | None = None,
                  label: str = "attempt") -> UpstreamResponse:
        """ONE attempt against ONE replica under its resilience policy.
        Raises StorageUnavailableError on transport failure, transient
        status, or an open breaker; returns any other response.

        May run on a hedge pool thread, so the trace is passed
        EXPLICITLY (no ambient contextvar there) and spans are appended
        with the lock-free ``add_span`` contract: the attempt's span id
        is reserved up front — it must ride the forward headers before
        the exchange runs — and recorded once the exchange finishes,
        so a hedge loser lands as its own sibling span and can never
        corrupt the winner's subtree."""
        parent_span = trace.reserve_span_id() if trace is not None else ""

        def attempt() -> UpstreamResponse:
            nonlocal attempted
            attempted = True
            remaining = self._remaining(deadline)
            timeout = self.config.upstream_timeout_s
            if remaining is not None:
                timeout = max(0.001, min(timeout, remaining))
            response = backend.transport.request(
                "POST", "/queries.json",
                headers=self._forward_headers(headers, request_id,
                                              deadline, trace, parent_span),
                body=body, timeout=timeout)
            if is_transient_http_status(response.status):
                # the shared retryability contract (utils/resilience):
                # 5xx/429 are health signals; other statuses —
                # including the backend's 4xx — are application answers
                raise UpstreamStatusError(backend.id, response)
            return response

        backend.begin()
        t0 = time.perf_counter()
        ok = False
        attempted = False
        try:
            response = resilient(backend.resilience, attempt)
            ok = True
            return response
        except StorageUnavailableError as exc:
            cause = exc.__cause__
            if isinstance(cause, (ConnectionRefusedError,
                                  ConnectionResetError)):
                # nothing is listening / the peer died mid-exchange:
                # don't wait for the probe loop to notice (it will
                # mark it back up when the replica returns)
                if backend.mark_down(str(cause)):
                    logger.warning(
                        "fleet backend %s marked down from the data "
                        "path: %s", backend.id, cause)
            raise
        finally:
            t1 = time.perf_counter()
            dt = t1 - t0
            backend.done()
            if attempted:
                # a breaker short-circuit never reached the replica:
                # it says nothing about the replica's health, so it
                # must not feed the canary guardrail window (a burst
                # racing one half-open probe slot would spuriously
                # abort a recovered canary) or the latency histograms
                self.stats.observe_upstream(group, dt)
                if ok:
                    # data-path proof for the membership starvation
                    # guard: a probe timeout against a replica that
                    # just answered is starvation, not death
                    backend.record_data_ok()
                if trace is not None:
                    # the attempt span, under its pre-reserved id (the
                    # one the replica's segment names as its parent)
                    trace.add_span(
                        f"{label}[{backend.id}]"
                        + ("" if ok else "!failed"),
                        t0, t1, span_id=parent_span)
                if ok and self.config.hedge:
                    # the hedge-delay histogram only matters when
                    # hedging can fire; disabled, its lock+bisect
                    # stays off the path
                    self.hedge_policy.observe(dt)
                if self.canary.record(group, ok, dt):
                    self.stats.bump("canary_aborts")
                    if self.on_canary_abort is not None:
                        try:
                            self.on_canary_abort()
                        except Exception:  # noqa: BLE001 — the abort itself already latched
                            logger.exception(
                                "canary abort propagation failed")

    def _forward(self, backend: Backend, group: str, tried: set[str],
                 body: bytes, headers: Mapping[str, str], request_id: str,
                 deadline: float | None, trace: Trace | None = None,
                 label: str = "attempt", meta: dict | None = None,
                 ) -> tuple[UpstreamResponse, str, bool]:
        """The primary exchange, optionally raced against one hedge.
        Returns ``(response, served_backend_id, hedge_fired)`` — with a
        hedge in flight the WINNER may be either replica, and the
        access log / trace tags must name the one that actually
        answered. ``meta["hedges"]`` is bumped when the hedge FIRES, so
        the caller still knows about it when both attempts fail and
        this raises instead of returning."""
        if not self.config.hedge:
            return (self._exchange(backend, group, body, headers,
                                   request_id, deadline, trace, label),
                    backend.id, False)
        remaining = self._remaining(deadline)
        alternates = self.membership.routable(
            group, exclude=tried | {backend.id})
        if not self.hedge_policy.should_hedge(len(alternates), remaining):
            return (self._exchange(backend, group, body, headers,
                                   request_id, deadline, trace, label),
                    backend.id, False)
        primary: Future = self._pool.submit(
            self._exchange, backend, group, body, headers, request_id,
            deadline, trace, label)
        done, _ = wait([primary], timeout=self.hedge_policy.delay_s())
        if done:
            tried.add(backend.id)
            # raises through to the retry loop on failure
            return primary.result(), backend.id, False
        hedge_backend = min(alternates, key=lambda b: b.inflight)
        self.stats.bump("hedges")
        if meta is not None:
            meta["hedges"] += 1
        hedge: Future = self._pool.submit(
            self._exchange, hedge_backend, group, body, headers,
            request_id, deadline, trace, "hedge")
        tried.add(backend.id)
        tried.add(hedge_backend.id)
        pending = {primary, hedge}
        failure: BaseException | None = None
        while pending:
            remaining = self._remaining(deadline)
            done, pending = wait(
                pending, timeout=remaining, return_when=FIRST_COMPLETED)
            if not done:          # deadline expired while both pending
                break
            for fut in done:
                exc = fut.exception()
                if exc is None:
                    if fut is hedge:
                        self.stats.bump("hedge_wins")
                    winner = (hedge_backend.id if fut is hedge
                              else backend.id)
                    return fut.result(), winner, True
                failure = exc
        if failure is not None:
            raise failure
        raise StorageUnavailableError(
            "router/hedge", "deadline expired with attempts in flight",
            retry_after=1.0)


def _embedded_response(exc: BaseException | None) -> UpstreamResponse | None:
    """The upstream response a failure carried, when the failure was a
    transient HTTP status rather than a transport error."""
    seen: set[int] = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        if isinstance(exc, UpstreamStatusError):
            return exc.response
        exc = exc.__cause__
    return None
