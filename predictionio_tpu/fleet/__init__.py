"""The fleet tier: a thin router process fronting N engine-server
replicas (ROADMAP item 5; docs/fleet.md).

One engine server per deployed engine is the single-process ceiling;
serving heavy traffic needs a layer that survives replica death, slow
nodes, and bad model rollouts without returning 5xx. The router is that
layer — Clipper-style fault isolation between clients and model
servers, built from the primitives the repo already proved out:

- :mod:`predictionio_tpu.fleet.membership` — health-driven membership:
  every backend's ``/healthz`` + ``/readyz`` probed on a background
  loop with mark-down/mark-up hysteresis;
- :mod:`predictionio_tpu.fleet.canary` — weighted canary rollout of a
  new model generation with guardrail auto-abort;
- :mod:`predictionio_tpu.fleet.router` — the routing core: per-backend
  circuit breaker + one transparent retry on a *different* healthy
  replica, optional tail-latency hedging, bounded in-flight admission,
  end-to-end ``X-PIO-Deadline-Ms`` propagation;
- :mod:`predictionio_tpu.fleet.transport` — the lean upstream HTTP
  client (pooled keep-alive sockets, single-write requests);
- :mod:`predictionio_tpu.api.router_server` — the HTTP surface
  (``pio router``).
"""

from predictionio_tpu.fleet.canary import CanaryController, GuardrailConfig
from predictionio_tpu.fleet.gateway import (
    EngineGateway,
    EngineGroup,
    EngineQuota,
    EngineSpec,
)
from predictionio_tpu.fleet.membership import (
    DOWN,
    UP,
    Backend,
    BackendSpec,
    FleetMembership,
)
from predictionio_tpu.fleet.router import (
    AdmissionGate,
    FleetRouter,
    HedgePolicy,
    RouterConfig,
    RouterResponse,
)
from predictionio_tpu.fleet.stats import RouterStats

__all__ = [
    "AdmissionGate",
    "Backend",
    "BackendSpec",
    "CanaryController",
    "DOWN",
    "EngineGateway",
    "EngineGroup",
    "EngineQuota",
    "EngineSpec",
    "FleetMembership",
    "FleetRouter",
    "GuardrailConfig",
    "HedgePolicy",
    "RouterConfig",
    "RouterResponse",
    "RouterStats",
    "UP",
]
