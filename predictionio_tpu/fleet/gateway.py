"""Multi-tenant gateway: one router, many engines (docs/fleet.md
"Multi-engine routing"; ROADMAP item 5's remaining third).

The reference PredictionIO serves many apps/engines but makes each
deployed engine its own process+port; PR 6-9 built a fleet tier that
still fronts exactly ONE engine per ``pio router``. The gateway closes
that gap: an **EngineTable** maps engine names to fully independent
backend groups —

- each engine gets its OWN :class:`~predictionio_tpu.fleet.membership.
  FleetMembership` (probe loop + hysteresis), per-replica breakers,
  :class:`~predictionio_tpu.fleet.canary.CanaryController`, hedging
  state and :class:`~predictionio_tpu.fleet.stats.RouterStats` — a
  dying tenant's probes, breakers and canary verdicts never touch a
  sibling's (blast-radius isolation);
- requests select the engine by **path**
  (``/engines/<name>/queries.json``) or the ``X-PIO-Engine`` header;
  bare ``/queries.json`` keeps routing to the configured DEFAULT
  engine, so every existing single-engine client, test and bench is
  untouched;
- admission is **per-app fair**: a token-bucket quota per engine
  (qps + burst + per-engine in-flight cap, env/CLI-tunable) answers
  over-quota requests with ``429 + Retry-After``, while the 503 shed
  stays a GLOBAL-pressure verdict through ONE shared
  :class:`~predictionio_tpu.fleet.router.AdmissionGate` — one tenant's
  burst spends its own budget, never a sibling's;
- the table mutates at runtime (``POST /fleet/engines``: register /
  retire / re-weight) and propagates across ``--workers`` siblings via
  the PR 9/10 seq'd admin-state spool as a CUMULATIVE document, so a
  respawned worker adopts the WHOLE table at boot, not the launch-time
  config.

Route resolution is a precompiled O(1) dict hit on the request path:
the route table is REBUILT (a fresh dict, atomically swapped) on every
table mutation, so the per-request cost is one ``dict.get`` and — for
bare ``/queries.json`` only — one header lookup. No per-request regex,
no allocation. Mutation-vs-read safety rides the CPython object-swap
contract (readers grab the current dict reference once), the same
discipline as the serving-path codec tables.
"""

from __future__ import annotations

import dataclasses
import logging
import re
import threading
from typing import Mapping

from predictionio_tpu.api.http_base import retry_after_header
from predictionio_tpu.fleet.router import (
    AdmissionGate,
    FleetRouter,
    RouterConfig,
    RouterResponse,
)
from predictionio_tpu.fleet.stats import router_collector
from predictionio_tpu.obs.aggregate import relabel
from predictionio_tpu.obs.registry import Metric
from predictionio_tpu.obs.slo import SLOEngine, labeled_burn_metric
from predictionio_tpu.obs.trace import active_trace
from predictionio_tpu.utils.resilience import SYSTEM_CLOCK, Clock

logger = logging.getLogger(__name__)

#: bare query path — routes to the default engine
QUERIES_PATH = "/queries.json"
#: engine selection header for bare-path clients (lower-cased at the
#: router's single-buffer parser, so the lookup key is lower too)
ENGINE_HEADER = "X-PIO-Engine"
_ENGINE_HEADER_LC = ENGINE_HEADER.lower()

#: engine names share the request-id charset discipline: path- and
#: label-safe, bounded (validated at REGISTRATION time only — the
#: request path never pays a regex; an invalid name in a path or
#: header simply misses the table and 404s)
ENGINE_NAME_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")

DEFAULT_ENGINE = "default"

#: fraction of the shared admission gate that must be free before a
#: tenant may spend burst credits — borrowed capacity must be capacity
#: nobody else is queueing for (an uncapped gate always has headroom)
FLEET_IDLE_HEADROOM = 0.5


def engine_query_path(name: str) -> str:
    return f"/engines/{name}/queries.json"


@dataclasses.dataclass(frozen=True)
class EngineSpec:
    """One tenant's declaration: its backend groups, launch canary
    weight, and admission quota. Quota fields default to ``None`` =
    inherit the router-wide ``PIO_ROUTER_ENGINE_*`` defaults; ``0`` is
    an EXPLICIT unlimited."""

    name: str
    backends: tuple[str, ...] = ()
    canary_backends: tuple[str, ...] = ()
    canary_weight_pct: float = 0.0
    #: token-bucket rate (requests/second); None inherits, 0 unlimited
    quota_qps: float | None = None
    #: bucket depth; None inherits (then max(1, qps))
    quota_burst: float | None = None
    #: per-engine concurrent in-flight cap; None inherits, 0 uncapped
    max_inflight: int | None = None
    #: burst-credit reservoir cap (weighted fair queueing): unused
    #: quota accrues as credits, spendable during a burst while the
    #: fleet has headroom; None inherits ``PIO_ROUTER_ENGINE_BURST_
    #: CREDITS``, 0 disables (docs/fleet.md "Per-tenant elasticity")
    burst_credits: float | None = None
    #: per-engine scale bounds consumed by the elasticity loop
    #: (fleet/controller.py EngineScaleSet); None inherits the global
    #: PIO_FLEET_MIN/MAX_REPLICAS defaults
    min_replicas: int | None = None
    max_replicas: int | None = None

    def __post_init__(self):
        if not ENGINE_NAME_RE.match(self.name):
            raise ValueError(
                f"engine name {self.name!r} must match "
                f"{ENGINE_NAME_RE.pattern}")
        object.__setattr__(self, "backends", tuple(self.backends))
        object.__setattr__(self, "canary_backends",
                           tuple(self.canary_backends))

    def to_doc(self) -> dict:
        return {
            "name": self.name,
            "backends": list(self.backends),
            "canaryBackends": list(self.canary_backends),
            "canaryWeightPct": self.canary_weight_pct,
            "quotaQps": self.quota_qps,
            "quotaBurst": self.quota_burst,
            "maxInflight": self.max_inflight,
            "burstCredits": self.burst_credits,
            "minReplicas": self.min_replicas,
            "maxReplicas": self.max_replicas,
        }

    @classmethod
    def from_doc(cls, doc: Mapping) -> "EngineSpec":
        def opt(key, cast):
            value = doc.get(key)
            return None if value is None else cast(value)

        return cls(
            name=str(doc["name"]),
            backends=tuple(str(b) for b in doc.get("backends") or ()),
            canary_backends=tuple(
                str(b) for b in doc.get("canaryBackends") or ()),
            canary_weight_pct=float(doc.get("canaryWeightPct") or 0.0),
            quota_qps=opt("quotaQps", float),
            quota_burst=opt("quotaBurst", float),
            max_inflight=opt("maxInflight", int),
            burst_credits=opt("burstCredits", float),
            min_replicas=opt("minReplicas", int),
            max_replicas=opt("maxReplicas", int),
        )

    def topology_key(self) -> tuple:
        """Everything that requires REBUILDING the group when it
        changes (backend sets); quota and weight apply in place."""
        return (self.backends, self.canary_backends)

    def quota_key(self) -> tuple:
        return (self.quota_qps, self.quota_burst, self.max_inflight,
                self.burst_credits)


#: `pio router --engine` flag grammar: comma-separated key=value pairs.
#: `replicas`/`port-base` are consumed by the CLI (per-engine
#: supervisor spawns from the --replica-cmd template); the rest map
#: onto EngineSpec fields. Backend lists use `+` between addresses
#: (`,` is the pair separator).
_ENGINE_FLAG_KEYS = frozenset({
    "name", "backend", "canary", "weight", "qps", "burst",
    "max-inflight", "replicas", "port-base",
    "credits", "min-replicas", "max-replicas",
})


def parse_engine_flag(text: str) -> dict:
    """``name=rec,backend=h:p+h:p,canary=h:p,weight=10,qps=100,
    burst=200,max-inflight=64,replicas=2,port-base=8300`` → a typed
    dict (the CLI builds the EngineSpec and supervisor specs from it).
    Raises ValueError with a pointed message on bad grammar."""
    raw: dict[str, str] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, value = part.partition("=")
        key = key.strip()
        if not sep or key not in _ENGINE_FLAG_KEYS:
            raise ValueError(
                f"--engine entry {part!r}: expected key=value with key "
                f"in {sorted(_ENGINE_FLAG_KEYS)}")
        raw[key] = value.strip()
    if "name" not in raw:
        raise ValueError(f"--engine {text!r} needs name=<engine>")
    if not ENGINE_NAME_RE.match(raw["name"]):
        raise ValueError(
            f"--engine name {raw['name']!r} must match "
            f"{ENGINE_NAME_RE.pattern}")

    def addrs(key: str) -> tuple[str, ...]:
        value = raw.get(key, "")
        return tuple(a for a in (p.strip() for p in value.split("+")) if a)

    def num(key: str, cast):
        if key not in raw:
            return None
        try:
            return cast(raw[key])
        except ValueError:
            raise ValueError(
                f"--engine {raw['name']}: {key}={raw[key]!r} is not "
                f"a {cast.__name__}")

    return {
        "name": raw["name"],
        "backends": addrs("backend"),
        "canary_backends": addrs("canary"),
        "weight": num("weight", float),
        "qps": num("qps", float),
        "burst": num("burst", float),
        "max_inflight": num("max-inflight", int),
        "replicas": num("replicas", int),
        "port_base": num("port-base", int),
        "credits": num("credits", float),
        "min_replicas": num("min-replicas", int),
        "max_replicas": num("max-replicas", int),
    }


class EngineQuota:
    """Per-engine admission budget: a token bucket (qps, burst) plus an
    in-flight cap, on the injectable clock so refill/burst behavior is
    deterministic under ``ManualClock``. ``try_admit`` returns None on
    admission (an in-flight slot is held until :meth:`release`) or a
    Retry-After hint in seconds — the 429 the gateway answers with, so
    one tenant's burst queues against its OWN budget and never a
    sibling's. Unlimited (qps=0, max_inflight=0) costs one uncontended
    lock acquisition per request.

    With ``burst_credits`` > 0 the bucket gains a weighted-fair
    reservoir: refill that would overflow the bucket cap (the tenant
    running UNDER its quota) accrues as credits instead of vanishing,
    and a credit substitutes for a token during a burst — but only
    while the fleet has admission headroom (``fleet_idle``), so
    borrowed capacity is capacity nobody else was using and compliant
    tenants' p99 stays pinned."""

    def __init__(self, qps: float = 0.0, burst: float = 0.0,
                 max_inflight: int = 0, burst_credits: float = 0.0,
                 clock: Clock = SYSTEM_CLOCK):
        self.qps = max(0.0, float(qps or 0.0))
        self.burst = (float(burst) if burst and burst > 0
                      else max(1.0, self.qps))
        self.max_inflight = max(0, int(max_inflight or 0))
        self.burst_credits = max(0.0, float(burst_credits or 0.0))
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = self.burst
        self._last = clock.monotonic()
        self._inflight = 0
        self._credits = 0.0
        self._credit_spends = 0

    @property
    def limited(self) -> bool:
        return self.qps > 0 or self.max_inflight > 0

    def try_admit(self, fleet_idle: bool = False) -> float | None:
        """None = admitted (call :meth:`release` when done); else the
        seconds-until-a-token-exists hint for Retry-After.
        ``fleet_idle`` gates credit spends: the caller (the gateway)
        passes whether the shared admission gate has headroom."""
        with self._lock:
            spend = 0  # 0 = free (unlimited qps), 1 = token, 2 = credit
            if self.qps > 0:
                now = self._clock.monotonic()
                tokens = self._tokens + (now - self._last) * self.qps
                if tokens > self.burst:
                    if self.burst_credits > 0:
                        self._credits = min(self.burst_credits,
                                            self._credits
                                            + tokens - self.burst)
                    tokens = self.burst
                self._tokens = tokens
                self._last = now
                if tokens >= 1.0:
                    spend = 1
                elif fleet_idle and self._credits >= 1.0:
                    spend = 2
                else:
                    return max(0.001, (1.0 - tokens) / self.qps)
            if self.max_inflight and self._inflight >= self.max_inflight:
                # no refill schedule to size the hint from: one qps
                # beat when a rate exists, else a short constant (the
                # header layer jitters every hint anyway)
                return 1.0 / self.qps if self.qps > 0 else 0.25
            if spend == 1:
                self._tokens -= 1.0
            elif spend == 2:
                self._credits -= 1.0
                self._credit_spends += 1
            self._inflight += 1
            return None

    def release(self) -> None:
        with self._lock:
            self._inflight -= 1

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "limited": self.limited,
                "qps": self.qps or None,
                "burst": self.burst if self.qps > 0 else None,
                "maxInflight": self.max_inflight or None,
                "inflight": self._inflight,
                "tokens": (round(self._tokens, 3)
                           if self.qps > 0 else None),
                "burstCredits": self.burst_credits or None,
                "credits": (round(self._credits, 3)
                            if self.burst_credits > 0 else None),
                "creditSpends": self._credit_spends,
            }


class EngineGroup:
    """One tenant behind the gateway: its own :class:`FleetRouter`
    (membership, breakers, canary, hedging, stats — everything the
    single-engine router owns) plus its admission quota and a
    per-engine SLO engine for the burn-rate gauges."""

    def __init__(self, spec: EngineSpec, config: RouterConfig,
                 admission: AdmissionGate, clock: Clock = SYSTEM_CLOCK,
                 router: FleetRouter | None = None,
                 stamped: bool = True):
        self.spec = spec
        self._config = config
        self._clock = clock
        if router is None:
            engine_config = dataclasses.replace(
                config,
                backends=spec.backends,
                canary_backends=spec.canary_backends,
                canary_weight_pct=spec.canary_weight_pct,
                engines=())
            # `stamped` False = the IMPLICIT lone default engine: its
            # backend snapshots keep the pre-gateway shape (no engine
            # key) so the single-engine suite and dashboards see no
            # change; explicit/runtime engines stamp their name
            router = FleetRouter(engine_config, admission=admission,
                                 engine=spec.name if stamped else "",
                                 clock=clock)
        self.router = router
        self.quota = self._build_quota(spec)
        #: per-engine SLO ring: what THIS tenant's clients experienced
        #: (the per-engine autoscaling-signal contract, docs/fleet.md)
        self.slo = SLOEngine(clock=clock)

    def _build_quota(self, spec: EngineSpec) -> EngineQuota:
        cfg = self._config
        return EngineQuota(
            qps=(spec.quota_qps if spec.quota_qps is not None
                 else cfg.engine_quota_qps),
            burst=(spec.quota_burst if spec.quota_burst is not None
                   else cfg.engine_quota_burst),
            max_inflight=(spec.max_inflight if spec.max_inflight is not None
                          else cfg.engine_max_inflight),
            burst_credits=(spec.burst_credits
                           if spec.burst_credits is not None
                           else cfg.engine_burst_credits),
            clock=self._clock)

    @property
    def name(self) -> str:
        return self.spec.name

    def apply_quota(self, spec: EngineSpec) -> None:
        """Re-weight in place: swap the quota object (readers grab the
        attribute once; in-flight slots held on the OLD bucket release
        against it harmlessly) and remember the new spec."""
        # pio: lint-ignore[shared-state-race]: lock-free reference swap — readers grab self.spec/self.quota once per request (GIL-atomic); stale reads for one request are the documented re-weight semantics
        self.spec = dataclasses.replace(
            spec, backends=self.spec.backends,
            canary_backends=self.spec.canary_backends)
        # pio: lint-ignore[shared-state-race]: same swap discipline — in-flight slots release against the old bucket harmlessly (docstring)
        self.quota = self._build_quota(spec)

    def start(self) -> None:
        self.router.start()

    def close(self) -> None:
        self.router.close()

    def spec_doc(self) -> dict:
        return self.spec.to_doc()

    def snapshot(self) -> dict:
        backends = self.router.membership.snapshot()
        groups: dict[str, dict] = {}
        for b in backends:
            g = groups.setdefault(b["group"], {"size": 0, "up": 0,
                                               "down": 0})
            g["size"] += 1
            g["up" if b["state"] == "up" else "down"] += 1
        return {
            "name": self.name,
            "groups": groups,
            "backends": backends,
            "canary": self.router.canary.snapshot(),
            "quota": self.quota.snapshot(),
            "router": self.router.stats.snapshot(),
        }


class EngineGateway:
    """The EngineTable + request-path dispatch (module docstring).

    Concurrency: the ``_groups`` and ``_routes`` dicts are REPLACED,
    never mutated — handler threads read the current reference once per
    request (GIL-atomic), table mutations build fresh dicts under
    ``_lock`` and swap. Per-group state (membership, canary, quota)
    carries its own locks."""

    def __init__(self, config: RouterConfig, clock: Clock = SYSTEM_CLOCK,
                 default_router: FleetRouter | None = None):
        self.config = config
        self._clock = clock
        #: ONE gate across every engine: 503 = global pressure
        self.admission = (default_router._admission
                          if default_router is not None
                          else AdmissionGate(config.max_inflight))
        self._lock = threading.Lock()
        self._started = False
        groups: dict[str, EngineGroup] = {}
        specs = [s if isinstance(s, EngineSpec) else EngineSpec.from_doc(s)
                 for s in config.engines]
        if default_router is not None:
            # legacy explicit-router construction
            # (RouterServer(config, router)): wrap it as the default
            # engine; declared engines ride alongside
            default_spec = EngineSpec(
                name=config.default_engine,
                backends=tuple(config.backends),
                canary_backends=tuple(config.canary_backends),
                canary_weight_pct=config.canary_weight_pct)
            groups[default_spec.name] = EngineGroup(
                default_spec, config, self.admission, clock,
                router=default_router)
        elif (config.backends or config.canary_backends or not specs):
            # the single-engine configuration (and the empty one):
            # config.backends ARE the default engine — zero breakage
            default_spec = EngineSpec(
                name=config.default_engine,
                backends=tuple(config.backends),
                canary_backends=tuple(config.canary_backends),
                canary_weight_pct=config.canary_weight_pct)
            if any(s.name == default_spec.name for s in specs):
                raise ValueError(
                    f"--engine name {default_spec.name!r} collides with "
                    "the default engine built from --backend; name it "
                    "differently or declare every engine explicitly")
            groups[default_spec.name] = EngineGroup(
                default_spec, config, self.admission, clock,
                stamped=bool(specs))
        for spec in specs:
            if spec.name in groups:
                raise ValueError(f"duplicate engine {spec.name!r}")
            groups[spec.name] = EngineGroup(spec, config,
                                            self.admission, clock)
        self._groups = groups
        if config.default_engine in groups:
            self.default_engine = config.default_engine
        elif config.default_engine != DEFAULT_ENGINE:
            # an EXPLICIT default (--default-engine / the env var) that
            # names no engine is a typo — silently falling back would
            # misroute every legacy bare-/queries.json client onto
            # whichever engine happened to be declared first
            raise ValueError(
                f"default engine {config.default_engine!r} is not in "
                f"the engine table {sorted(groups)}")
        else:
            self.default_engine = next(iter(groups))
        #: engine labels appear on metric families once the deployment
        #: is EXPLICITLY multi-engine — the lone implicit default
        #: engine keeps the pre-gateway exposition byte-for-byte
        self._explicit = bool(specs)
        self._routes = self._compile_routes(groups, self.default_engine)

    # -- views ----------------------------------------------------------------
    @property
    def labeled(self) -> bool:
        return self._explicit or len(self._groups) > 1

    def groups(self) -> list[EngineGroup]:
        return list(self._groups.values())

    def get(self, name: str) -> EngineGroup | None:
        return self._groups.get(name)

    @property
    def default_group(self) -> EngineGroup:
        return self._groups[self.default_engine]

    def is_query_path(self, path: str) -> bool:
        """The O(1) routed-path test the HTTP handler runs per request."""
        return path in self._routes

    def engine_names(self) -> list[str]:
        return list(self._groups)

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            self._started = True
            groups = self._groups
        for group in groups.values():
            group.start()

    def close(self) -> None:
        with self._lock:
            self._started = False
            groups = self._groups
        for group in groups.values():
            group.close()

    # -- table mutation (all under _lock; dicts swapped, never mutated) -------
    @staticmethod
    def _compile_routes(groups: Mapping[str, EngineGroup],
                        default: str) -> dict[str, str]:
        routes = {engine_query_path(name): name for name in groups}
        routes[QUERIES_PATH] = default
        return routes

    def _swap(self, groups: dict[str, EngineGroup],
              default: str | None = None) -> None:
        """Caller holds ``_lock``. Publish a new table atomically:
        groups first, then the route dict compiled FROM it — a reader
        that wins a route hit always finds the group."""
        # pio: lint-ignore[shared-state-race]: writers serialize on _lock; readers deliberately take none — dict references are swapped whole (GIL-atomic) in groups-then-routes order so a route hit always finds its group
        self._groups = groups
        if default is not None:
            # pio: lint-ignore[shared-state-race]: same publish discipline — a reader sees either the old or the new default, both valid tables
            self.default_engine = default
        # pio: lint-ignore[shared-state-race]: same publish discipline — routes compiled FROM the already-published groups
        self._routes = self._compile_routes(groups, self.default_engine)

    def register(self, spec: EngineSpec) -> EngineGroup:
        """Add an engine at runtime. Its membership probe loop starts
        immediately (when the gateway is live), so a dead backend is
        marked down within ``down_after`` probes just like a launch
        backend."""
        with self._lock:
            if spec.name in self._groups:
                raise ValueError(f"engine {spec.name!r} already registered")
            group = EngineGroup(spec, self.config, self.admission,
                                self._clock)
            groups = dict(self._groups)
            groups[spec.name] = group
            self._swap(groups)
            started = self._started
        if started:
            group.start()
        logger.info("engine %s registered (%d backends)",
                    spec.name, len(spec.backends))
        return group

    def retire(self, name: str) -> EngineGroup:
        """Remove an engine: it leaves the route table first (new
        requests 404), then its probe loop and transports close.
        Retiring the default engine is refused — bare ``/queries.json``
        must always resolve."""
        with self._lock:
            if name == self.default_engine:
                raise ValueError(
                    f"engine {name!r} is the default engine; point "
                    "defaultEngine elsewhere before retiring it")
            group = self._groups.get(name)
            if group is None:
                raise KeyError(name)
            groups = dict(self._groups)
            del groups[name]
            self._swap(groups)
        group.close()
        logger.info("engine %s retired", name)
        return group

    def set_default(self, name: str) -> None:
        with self._lock:
            if name not in self._groups:
                raise KeyError(name)
            self._swap(dict(self._groups), default=name)

    # -- the request path -----------------------------------------------------
    def resolve(self, path: str,
                headers: Mapping[str, str]) -> "EngineGroup | None":
        """One dict hit on the path; bare ``/queries.json`` consults
        the ``X-PIO-Engine`` header (absent → default engine). Returns
        None for an unknown engine (the caller's 404)."""
        name = self._routes.get(path)
        if name is None:
            return None
        if path == QUERIES_PATH:
            header = headers.get(_ENGINE_HEADER_LC)
            if header is not None:
                name = header
        return self._groups.get(name)

    def route(self, path: str, body: bytes, headers: Mapping[str, str],
              request_id: str) -> RouterResponse:
        """Resolve → per-engine quota (429) → the engine's own
        FleetRouter (global-pressure 503 shed, pick/forward/retry/
        hedge). The response carries the resolved engine for the
        access log, root trace span and SLO attribution."""
        group = self.resolve(path, headers)
        if group is None:
            trace = active_trace()
            if trace is not None:
                trace.tags["outcome"] = "unknown_engine"
            wanted = (headers.get(_ENGINE_HEADER_LC)
                      if path == QUERIES_PATH else path)
            return RouterResponse.error(
                404, f"unknown engine for {wanted!r} "
                     "(GET /fleet/engines lists the registered table)")
        # ONE quota reference for admit AND release: a concurrent
        # runtime re-quota swaps group.quota, and releasing against the
        # fresh bucket would drive its in-flight count negative (and
        # quietly widen the cap by the number of in-flight requests)
        quota = group.quota
        # Burst credits only spend into idle fleet capacity: gate on
        # the SHARED admission gate's headroom so borrowed slots are
        # slots no compliant tenant was using.
        fleet_idle = self.admission.headroom() >= FLEET_IDLE_HEADROOM
        hint = quota.try_admit(fleet_idle=fleet_idle)
        if hint is not None:
            group.router.stats.bump_throttled()
            trace = active_trace()
            if trace is not None:
                trace.tags["outcome"] = "quota_throttled"
            out = RouterResponse.error(
                429, f"engine {group.name!r} is over its request "
                     "quota; retry shortly",
                {"Retry-After": retry_after_header(hint)})
            out.engine = group.name
            return out
        try:
            out = group.router.route(body, headers, request_id)
        finally:
            quota.release()
        out.engine = group.name
        return out

    def record_outcome(self, engine: str | None, ok: bool,
                       latency_s: float) -> None:
        """Feed the per-engine SLO ring (handler-measured walltime)."""
        if engine is None:
            return
        group = self._groups.get(engine)
        if group is not None:
            group.slo.record(ok=ok, latency_s=latency_s)

    # -- shared admin state (the cumulative engines document) -----------------
    def table_doc(self) -> dict:
        """The WHOLE table as a JSON-able document: specs + per-engine
        canary state. Published into the worker admin spool on every
        mutation so a respawned sibling adopts everything from one
        read (fleet/workers.py)."""
        groups = self._groups
        return {
            "defaultEngine": self.default_engine,
            "table": [
                {"spec": g.spec_doc(),
                 "canary": g.router.canary.state_doc()}
                for g in groups.values()
            ],
        }

    def adopt_table(self, doc: Mapping) -> bool:
        """Diff-apply a sibling's :meth:`table_doc`: register engines
        we lack, retire engines the document dropped, re-apply quotas
        and canary state ONLY where they differ (an identical document
        re-read every sync pass must be a no-op — see
        CanaryController.adopt_state). Returns True when anything
        changed. Individual malformed entries are skipped with a
        warning; they must never take the sync loop down."""
        table = doc.get("table")
        if not isinstance(table, list):
            return False
        changed = False
        want: dict[str, tuple[EngineSpec, dict | None]] = {}
        #: engines whose entry was PRESENT but unreadable (torn spool
        #: write, version skew): they must be exempt from the
        #: retire-what's-absent pass below — conflating "unparseable"
        #: with "deliberately dropped" would retire a healthy tenant
        #: locally AND, via this worker's next cumulative publish,
        #: fleet-wide. If even the NAME is unreadable, skip retirement
        #: entirely this cycle (the next committed doc settles it).
        unparsed: set[str] = set()
        doc_complete = True
        for entry in table:
            try:
                spec = EngineSpec.from_doc(entry["spec"])
            except (KeyError, TypeError, ValueError) as exc:
                logger.warning("ignoring malformed engine entry %r: %s",
                               entry, exc)
                try:
                    unparsed.add(str(entry["spec"]["name"]))
                except (KeyError, TypeError):
                    doc_complete = False
                continue
            canary = entry.get("canary")
            want[spec.name] = (spec, canary if isinstance(canary, dict)
                               else None)
        if not want:
            return False
        default = doc.get("defaultEngine")
        for name, (spec, canary) in want.items():
            group = self._groups.get(name)
            if group is None:
                try:
                    group = self.register(spec)
                except ValueError as exc:
                    logger.warning("cannot adopt engine %s: %s", name, exc)
                    continue
                changed = True
            elif group.spec.topology_key() != spec.topology_key():
                # backend sets changed: rebuild the group (breaker and
                # probe state restart clean against the new replicas)
                try:
                    self.retire(name)
                    group = self.register(spec)
                    changed = True
                except (KeyError, ValueError) as exc:
                    logger.warning("cannot rebuild engine %s: %s",
                                   name, exc)
                    continue
            elif group.spec.quota_key() != spec.quota_key():
                group.apply_quota(spec)
                changed = True
            if canary is not None and group.router.canary.adopt_state(
                    canary):
                changed = True
        if default in want and default in self._groups \
                and default != self.default_engine:
            self.set_default(default)
            changed = True
        if doc_complete:
            for name in list(self._groups):
                if name not in want and name not in unparsed \
                        and name != self.default_engine:
                    try:
                        self.retire(name)
                        changed = True
                    except (KeyError, ValueError):
                        pass
        return changed

    # -- admin mutations behind POST /fleet/engines ---------------------------
    def admin_mutate(self, doc: Mapping) -> dict:
        """Apply one ``POST /fleet/engines`` action and return the new
        table snapshot. Raises ValueError with an operator-readable
        message on a bad request (the HTTP layer's 400/404/409)."""
        action = doc.get("action")
        if action == "register":
            engine = doc.get("engine")
            if not isinstance(engine, dict):
                raise ValueError(
                    'register needs {"engine": {"name": ..., '
                    '"backends": [...]}}')
            try:
                spec = EngineSpec.from_doc(engine)
            except (KeyError, TypeError, ValueError) as exc:
                raise ValueError(f"invalid engine spec: {exc}")
            self.register(spec)
            return self.snapshot()
        name = doc.get("name")
        if not isinstance(name, str):
            raise ValueError('expected {"action": ..., "name": <engine>}')
        if action == "retire":
            try:
                self.retire(name)
            except KeyError:
                raise ValueError(f"unknown engine {name!r}")
            return self.snapshot()
        group = self._groups.get(name)
        if group is None:
            raise ValueError(f"unknown engine {name!r}")
        if action == "quota":
            # a key ABSENT from the document keeps the engine's current
            # value (a partial re-quota must not silently reset the
            # fields it did not mention); an explicit JSON null resets
            # that field to the router-wide PIO_ROUTER_ENGINE_* default
            def field(key: str, current, cast):
                if key not in doc:
                    return current
                return None if doc[key] is None else cast(doc[key])

            try:
                spec = dataclasses.replace(
                    group.spec,
                    quota_qps=field("quotaQps", group.spec.quota_qps,
                                    float),
                    quota_burst=field("quotaBurst",
                                      group.spec.quota_burst, float),
                    max_inflight=field("maxInflight",
                                       group.spec.max_inflight, int),
                    burst_credits=field("burstCredits",
                                        group.spec.burst_credits, float))
            except (TypeError, ValueError) as exc:
                raise ValueError(f"invalid quota: {exc}")
            group.apply_quota(spec)
            return self.snapshot()
        if action == "weight":
            try:
                weight = float(doc["weight"])
            except (KeyError, TypeError, ValueError):
                raise ValueError('weight needs {"weight": <0..100>}')
            if not 0.0 <= weight <= 100.0:
                raise ValueError("weight must be within 0..100")
            group.router.canary.set_weight(weight)
            return self.snapshot()
        if action == "default":
            self.set_default(name)
            return self.snapshot()
        raise ValueError(
            f"unknown action {action!r}: expected register | retire | "
            "quota | weight | default")

    def snapshot(self) -> dict:
        """``GET /fleet/engines``: the table with per-engine health,
        canary and quota state — what ``pio status --router`` prints."""
        groups = self._groups
        return {
            "defaultEngine": self.default_engine,
            "engines": [g.snapshot() for g in groups.values()],
        }

    # -- registry adapter -----------------------------------------------------
    def collector(self):
        """Per-engine labeled metric families. Single implicit engine:
        byte-identical to the pre-gateway ``router_collector`` output
        (plus the ``pio_router_engines`` gauge) — existing dashboards
        and the pinned single-engine suite see no label change. Multi-
        engine: every router family gains ``engine=<name>`` (merged
        into ONE family per name — duplicate HELP/TYPE blocks are
        invalid exposition), plus the quota gauges and the per-engine
        SLO burn family."""

        def collect() -> list[Metric]:
            groups = self._groups
            labeled = self.labeled
            out: list[Metric] = []
            if not labeled:
                group = groups[self.default_engine]
                out.extend(router_collector(
                    group.router.stats, group.router.membership,
                    group.router.canary)())
            else:
                merged: dict[str, Metric] = {}
                inflight = Metric(
                    name="pio_router_engine_inflight", kind="gauge",
                    help="Requests currently in flight per engine "
                         "(quota-layer view; the global admission "
                         "gate is pio_router_backend_inflight's sum)")
                qps = Metric(
                    name="pio_router_engine_quota_qps", kind="gauge",
                    help="Configured token-bucket rate per engine "
                         "(0 = unlimited)")
                credits = Metric(
                    name="pio_router_engine_burst_credits", kind="gauge",
                    help="Accrued burst credits per engine (weighted "
                         "fair queueing reservoir; only engines with a "
                         "credit cap emit a sample)")
                spends = Metric(
                    name="pio_router_engine_credit_spends_total",
                    kind="counter",
                    help="Admissions paid with a burst credit instead "
                         "of a bucket token (fleet had headroom)")
                for name, group in groups.items():
                    fams = router_collector(
                        group.router.stats, group.router.membership,
                        group.router.canary)()
                    for fam in relabel(fams, {"engine": name}):
                        have = merged.get(fam.name)
                        if have is None:
                            merged[fam.name] = fam
                        else:
                            have.samples.extend(fam.samples)
                            have.histograms.extend(fam.histograms)
                    labels = {"engine": name}
                    quota = group.quota
                    inflight.samples.append(
                        (labels, float(quota.inflight)))
                    qps.samples.append((labels, float(quota.qps)))
                    if quota.burst_credits > 0:
                        snap = quota.snapshot()
                        credits.samples.append(
                            (labels, float(snap["credits"] or 0.0)))
                        spends.samples.append(
                            (labels, float(snap["creditSpends"])))
                out.extend(merged.values())
                out.append(inflight)
                out.append(qps)
                if credits.samples:
                    out.append(credits)
                    out.append(spends)
                out.append(labeled_burn_metric(
                    [({"engine": name}, group.slo)
                     for name, group in groups.items()],
                    name="pio_router_engine_slo_burn_rate",
                    help="Per-engine error-budget burn rate by SLO and "
                         "window — the per-tenant autoscaling signal "
                         "(docs/fleet.md \"Multi-engine routing\")"))
            out.append(Metric(
                name="pio_router_engines", kind="gauge",
                help="Engines registered in this router's EngineTable",
                samples=[({}, float(len(groups)))]))
            return out

        return collect


__all__ = [
    "DEFAULT_ENGINE",
    "ENGINE_HEADER",
    "ENGINE_NAME_RE",
    "EngineGateway",
    "EngineGroup",
    "EngineQuota",
    "EngineSpec",
    "engine_query_path",
    "parse_engine_flag",
]
