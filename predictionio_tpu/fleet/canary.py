"""Weighted canary rollout with guardrail auto-abort.

A new model generation deploys to the ``canary`` replica group; the
router sends ``weight_pct`` percent of queries there and watches a
sliding window of canary outcomes. When the window holds at least
``min_requests`` samples and either the error rate or the p99 latency
breaches its guardrail, the canary AUTO-ABORTS: weight snaps to zero,
the abort is latched (with its reason) until an operator sets a new
weight, and stable serves everything — a bad rollout degrades to the
previous generation, it does not take the fleet down.

Trustworthiness note: canary-vs-stable only means anything when the two
groups really serve the generations they claim — that is what the
crash-safe checkpoint manifest and the checksummed model envelope
(utils/checkpoint.py, workflow/persistence.py) guarantee at load time.

All state sits under one lock (writers: handler threads recording
outcomes, the admin endpoint; readers: routing picks, snapshots).
"""

from __future__ import annotations

import dataclasses
import logging
import math
import random
import threading
from collections import deque

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class GuardrailConfig:
    """When to pull the plug on a canary."""

    #: no verdict before this many canary samples are in the window —
    #: a single unlucky first request must not abort a rollout
    min_requests: int = 20
    #: abort when window error rate exceeds this (0..1); <=0 disables
    max_error_rate: float = 0.5
    #: abort when window p99 exceeds this many ms; <=0 disables
    max_p99_ms: float = 0.0
    #: sliding window length (newest N canary outcomes)
    window: int = 200


class CanaryController:
    """Traffic split + guardrail evaluation (module docstring)."""

    def __init__(self, weight_pct: float = 0.0,
                 guardrail: GuardrailConfig | None = None,
                 rng: random.Random | None = None):
        self.guardrail = guardrail or GuardrailConfig()
        self._lock = threading.Lock()
        self._weight_pct = min(100.0, max(0.0, weight_pct))
        self._window: deque[tuple[bool, float]] = deque(
            maxlen=max(1, self.guardrail.window))
        self._aborted = False
        self._abort_reason: str | None = None
        self._aborts = 0
        #: seeded in tests for a deterministic split
        self._rng = rng or random.Random()

    # -- routing ------------------------------------------------------------
    def pick_group(self) -> str:
        """``canary`` for weight_pct% of calls, else ``stable``."""
        with self._lock:
            weight = self._weight_pct
            if weight <= 0.0:
                return "stable"
            return "canary" if self._rng.random() * 100.0 < weight \
                else "stable"

    @property
    def weight_pct(self) -> float:
        with self._lock:
            return self._weight_pct

    @property
    def aborted(self) -> bool:
        with self._lock:
            return self._aborted

    # -- outcome feed + guardrail -------------------------------------------
    def record(self, group: str, ok: bool, latency_s: float) -> bool:
        """Fold one routed outcome in; returns True when THIS sample
        tripped the guardrail (the caller counts/logs the abort)."""
        if group != "canary":
            return False
        with self._lock:
            self._window.append((ok, latency_s))
            if self._aborted or self._weight_pct <= 0.0:
                return False
            reason = self._breach_locked()
            if reason is None:
                return False
            self._weight_pct = 0.0
            self._aborted = True
            self._abort_reason = reason
            self._aborts += 1
        logger.warning("canary auto-abort: %s", reason)
        return True

    def _breach_locked(self) -> str | None:
        g = self.guardrail
        n = len(self._window)
        if n < max(1, g.min_requests):
            return None
        errors = sum(1 for ok, _ in self._window if not ok)
        if g.max_error_rate > 0 and errors / n > g.max_error_rate:
            return (f"error rate {errors}/{n} = {errors / n:.2f} "
                    f"> {g.max_error_rate:.2f} over the last {n} requests")
        if g.max_p99_ms > 0:
            lat = sorted(l for _, l in self._window)
            # upper-index convention (ceil(q*n)-1): at window sizes
            # near min_requests the p99 must see the max, not the
            # second-largest
            p99 = lat[min(n - 1, math.ceil(0.99 * n) - 1)] * 1e3
            if p99 > g.max_p99_ms:
                return (f"p99 {p99:.1f}ms > {g.max_p99_ms:.1f}ms "
                        f"over the last {n} requests")
        return None

    # -- operator surface ---------------------------------------------------
    def set_weight(self, weight_pct: float,
                   guardrail: GuardrailConfig | None = None) -> None:
        """Start (or resize) a rollout: clears a previous abort latch
        and the outcome window — a NEW generation must not inherit the
        failed one's verdict."""
        with self._lock:
            if guardrail is not None:
                self.guardrail = guardrail
                self._window = deque(maxlen=max(1, guardrail.window))
            self._weight_pct = min(100.0, max(0.0, weight_pct))
            self._aborted = False
            self._abort_reason = None
            self._window.clear()

    def abort(self, reason: str = "operator abort") -> None:
        with self._lock:
            self._weight_pct = 0.0
            self._aborted = True
            self._abort_reason = reason
            self._aborts += 1

    # -- shared-admin-state round-trip (fleet/gateway.py) --------------------
    def state_doc(self) -> dict:
        """The controller's rollout state as a JSON-able document for
        the worker-pool admin spool: weight, abort latch (+reason), and
        the guardrail — everything a sibling (or a respawned worker)
        needs to adopt this controller's verdict."""
        with self._lock:
            g = self.guardrail
            return {
                "weight": self._weight_pct,
                "aborted": self._aborted,
                "abortReason": self._abort_reason,
                "guardrail": {
                    "minRequests": g.min_requests,
                    "maxErrorRate": g.max_error_rate,
                    "maxP99Ms": g.max_p99_ms,
                    "window": g.window,
                },
            }

    def adopt_state(self, doc: dict) -> bool:
        """Diff-apply a sibling's :meth:`state_doc`: only an ACTUAL
        difference mutates (``set_weight`` clears the guardrail outcome
        window, so re-applying an identical document on every admin
        sync pass would reset the window forever and the guardrail
        could never accumulate a verdict). Returns True when something
        changed. Malformed documents are ignored — a torn or hostile
        spool entry must never take the canary down."""
        try:
            weight = float(doc["weight"])
            aborted = bool(doc["aborted"])
        except (KeyError, TypeError, ValueError):
            logger.warning("ignoring malformed canary state doc: %r", doc)
            return False
        guardrail = None
        g = doc.get("guardrail")
        if isinstance(g, dict):
            try:
                guardrail = GuardrailConfig(
                    min_requests=int(g["minRequests"]),
                    max_error_rate=float(g["maxErrorRate"]),
                    max_p99_ms=float(g["maxP99Ms"]),
                    window=int(g["window"]))
            except (KeyError, TypeError, ValueError):
                guardrail = None
        with self._lock:
            same_guardrail = guardrail is None or guardrail == self.guardrail
            if (self._aborted == aborted
                    and self._weight_pct == weight and same_guardrail):
                return False
        if aborted:
            if guardrail is not None:
                with self._lock:
                    if guardrail != self.guardrail:
                        self.guardrail = guardrail
                        self._window = deque(
                            maxlen=max(1, guardrail.window))
            self.abort(str(doc.get("abortReason") or "sibling abort"))
        else:
            self.set_weight(weight, guardrail=guardrail)
        return True

    def snapshot(self) -> dict:
        with self._lock:
            n = len(self._window)
            errors = sum(1 for ok, _ in self._window if not ok)
            return {
                "weightPct": self._weight_pct,
                "aborted": self._aborted,
                **({"abortReason": self._abort_reason}
                   if self._abort_reason else {}),
                "aborts": self._aborts,
                "windowRequests": n,
                "windowErrors": errors,
                "guardrail": {
                    "minRequests": self.guardrail.min_requests,
                    "maxErrorRate": self.guardrail.max_error_rate,
                    "maxP99Ms": self.guardrail.max_p99_ms,
                    "window": self.guardrail.window,
                },
            }
