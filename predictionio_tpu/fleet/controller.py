"""The scale controller: the actor behind the autoscaling signals
(docs/fleet.md "Autoscaling").

PR 7 shipped the contract — ``pio_fleet_pressure`` and
``pio_slo_burn_rate{slo,window}`` on ``GET /fleet/metrics`` — and
documented the policy a controller should run. This module IS that
controller: a background loop that polls the router's own merged fleet
metrics and applies a hysteresis policy,

- **scale up** on SUSTAINED pressure above ``pressure_up`` (latency is
  queueing, not model time) or a fast-window SLO burn above
  ``burn_up`` (the incident is happening now),
- **scale down** only after a COOLDOWN of sustained quiet (pressure
  below ``pressure_down`` with both burn windows under 1.0),
- clamped to ``[min_replicas, max_replicas]``, with a global
  ``cooldown_s`` between actions so one hot scrape cannot ratchet the
  fleet,
- **dry-run first**: with ``dry_run`` the controller only EXPORTS its
  verdicts (``pio_fleet_desired_replicas`` vs actual, decision
  counters) so operators can watch it against production traffic
  before trusting it with actuation.

Everything is deterministic on the injectable Clock: ``tick()`` is the
loop body AND the test hook, and the decision table
(tests/test_fleet_supervisor.py) drives it with scripted signals on a
``ManualClock``. Actuation goes through a small interface so the
supervised-fleet actuator (spawn a replica via the supervisor, join it
to membership; detach + drain on the way down) and test doubles are
interchangeable.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import threading
from typing import Callable

from predictionio_tpu.fleet.membership import Backend, BackendSpec
from predictionio_tpu.fleet.supervisor import (
    CRASH_LOOPED,
    FleetSupervisor,
    SpawnSpec,
    _env_field,
)
from predictionio_tpu.obs.registry import Metric
from predictionio_tpu.utils.resilience import SYSTEM_CLOCK, Clock

logger = logging.getLogger(__name__)

UP, DOWN, HOLD, ERROR = "up", "down", "hold", "error"

#: decision counter keys (cooldown_hold = a verdict suppressed by the
#: global action cooldown; actuation_failed = the actuator said no)
DECISIONS = (UP, DOWN, HOLD, ERROR, "cooldown_hold", "actuation_failed")


@dataclasses.dataclass(frozen=True)
class ScaleSignals:
    """One poll of the autoscaling contract. ``pressure`` is None when
    the fleet scrape produced no pressure gauge (no traffic yet, or
    every replica scrape failed) — the controller treats that as
    neither hot nor quiet."""

    pressure: float | None
    fast_burn: float = 0.0
    slow_burn: float = 0.0


@dataclasses.dataclass(frozen=True)
class ScalePolicy:
    """Controller knobs, ``PIO_FLEET_*`` env-overridable at
    construction (docs/fleet.md "Autoscaling" has the table)."""

    min_replicas: int = _env_field("MIN_REPLICAS", 1, int)
    max_replicas: int = _env_field("MAX_REPLICAS", 4, int)
    #: scale-up triggers: queue-bound pressure, or the classic 5m-fast
    #: burn threshold for a 99.9% objective (14.4 = the page line)
    pressure_up: float = _env_field("PRESSURE_UP", 0.5, float)
    burn_up: float = _env_field("BURN_UP", 14.4, float)
    #: scale-down trigger: pressure at or below this AND both burn
    #: windows under 1.0 (budget spend at sustainable rate)
    pressure_down: float = _env_field("PRESSURE_DOWN", 0.1, float)
    #: how long a trigger must hold before it becomes a verdict
    up_sustain_s: float = _env_field("UP_SUSTAIN_S", 15.0, float)
    down_sustain_s: float = _env_field("DOWN_SUSTAIN_S", 120.0, float)
    #: minimum gap between ACTIONS (and dry-run verdicts): one hot
    #: scrape must not ratchet the fleet replica-by-replica
    cooldown_s: float = _env_field("COOLDOWN_S", 60.0, float)
    #: poll cadence of the background loop
    interval_s: float = _env_field("SCALE_INTERVAL_S", 5.0, float)
    #: export decisions without actuating (the rollout posture)
    dry_run: bool = False


class ScaleController:
    """Hysteresis policy loop over ``read_signals`` + an actuator
    (module docstring)."""

    def __init__(self, policy: ScalePolicy,
                 read_signals: Callable[[], ScaleSignals],
                 actuator, clock: Clock = SYSTEM_CLOCK):
        self.policy = policy
        self.read_signals = read_signals
        self.actuator = actuator
        self.clock = clock
        self._lock = threading.Lock()
        self._counts = dict.fromkeys(DECISIONS, 0)
        self._hot_since: float | None = None
        self._quiet_since: float | None = None
        self._last_action_at: float | None = None
        self._desired: int | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- the decision engine --------------------------------------------------
    def tick(self) -> str:
        """One evaluation — the loop body and the deterministic test
        hook. Returns the decision taken."""
        p = self.policy
        now = self.clock.monotonic()
        try:
            signals = self.read_signals()
        except Exception as exc:  # noqa: BLE001 — a failed scrape is a held tick
            logger.warning("scale signals unreadable: %s", exc)
            return self._count(ERROR)
        current = self.actuator.current()
        hot = ((signals.pressure is not None
                and signals.pressure >= p.pressure_up)
               or signals.fast_burn >= p.burn_up)
        quiet = (signals.pressure is not None
                 and signals.pressure <= p.pressure_down
                 and signals.fast_burn < 1.0 and signals.slow_burn < 1.0)
        if hot:
            if self._hot_since is None:     # not `or`: t=0 is a real time
                self._hot_since = now
            self._quiet_since = None
        elif quiet:
            if self._quiet_since is None:
                self._quiet_since = now
            self._hot_since = None
        else:
            # neither hot nor quiet resets BOTH sustain windows — the
            # hysteresis that keeps a flapping signal from scaling
            self._hot_since = self._quiet_since = None
        delta = 0
        if hot and now - self._hot_since >= p.up_sustain_s:
            delta = 1
        elif quiet and now - self._quiet_since >= p.down_sustain_s:
            delta = -1
        desired = min(p.max_replicas, max(p.min_replicas, current + delta))
        if desired == current:
            self._set_desired(desired)
            return self._count(HOLD)
        if self._last_action_at is not None \
                and now - self._last_action_at < p.cooldown_s:
            self._set_desired(current)
            return self._count("cooldown_hold")
        # a verdict: record it, restart the sustain windows, and (when
        # not dry-running) actuate one step
        self._set_desired(desired)
        self._last_action_at = now
        self._hot_since = self._quiet_since = None
        decision = UP if desired > current else DOWN
        if p.dry_run:
            logger.info("scale %s verdict (dry-run): desired %d vs "
                        "actual %d", decision, desired, current)
            return self._count(decision)
        acted = (self.actuator.add_replica() if decision == UP
                 else self.actuator.remove_replica())
        if not acted:
            self._count("actuation_failed")
            logger.warning("scale %s actuation failed (desired %d, "
                           "actual %d)", decision, desired, current)
        return self._count(decision)

    def _count(self, decision: str) -> str:
        with self._lock:
            self._counts[decision] += 1
        return decision

    def _set_desired(self, desired: int) -> None:
        with self._lock:
            self._desired = desired

    # -- reporting ------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            counts = dict(self._counts)
            desired = self._desired
        return {
            "dryRun": self.policy.dry_run,
            "minReplicas": self.policy.min_replicas,
            "maxReplicas": self.policy.max_replicas,
            "desiredReplicas": desired,
            "actualReplicas": self.actuator.current(),
            "decisions": counts,
        }

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="pio-fleet-scaler", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(self.policy.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def controller_collector(controller: ScaleController):
    """Registry adapter: desired vs actual replica gauges + decision
    counters — the whole dry-run trust story is these families."""

    def collect() -> list[Metric]:
        snap = controller.snapshot()
        out = [
            Metric(name="pio_fleet_desired_replicas", kind="gauge",
                   help="Replica count the scale controller wants "
                        "(compare with pio_fleet_actual_replicas; in "
                        "--scale-dry-run only this moves)",
                   samples=[({}, float(snap["desiredReplicas"]
                                       if snap["desiredReplicas"]
                                       is not None
                                       else snap["actualReplicas"]))]),
            Metric(name="pio_fleet_actual_replicas", kind="gauge",
                   help="Replicas the actuator currently owns",
                   samples=[({}, float(snap["actualReplicas"]))]),
            Metric(name="pio_fleet_scale_dry_run", kind="gauge",
                   help="1 while the controller only exports verdicts",
                   samples=[({}, 1.0 if snap["dryRun"] else 0.0)]),
        ]
        decisions = Metric(
            name="pio_fleet_scale_decisions_total", kind="counter",
            help="Scale controller verdicts by outcome")
        for decision, n in sorted(snap["decisions"].items()):
            decisions.samples.append(({"decision": decision}, float(n)))
        out.append(decisions)
        return out

    return collect


# ---------------------------------------------------------------------------
# signal reader + the supervised-fleet actuator
# ---------------------------------------------------------------------------

def fleet_signals_reader(service) -> Callable[[], ScaleSignals]:
    """Read the autoscaling contract off the router's OWN merged fleet
    metrics — the controller consumes exactly what an external operator
    would scrape from ``GET /fleet/metrics`` (docs/fleet.md), so
    trusting the dry-run gauges means trusting the real inputs. The
    Metric families are consumed BEFORE text rendering
    (``fleet_metrics_families``): same scrape, same merge, without a
    render→reparse round-trip stealing serving CPU every tick. Burn
    rates come from the router's SLO engine (what clients experienced:
    sheds spend budget)."""

    def read() -> ScaleSignals:
        pressure: float | None = None
        for family in service.fleet_metrics_families():
            if family.name == "pio_fleet_pressure" and family.samples:
                pressure = family.samples[0][1]
        burns = service.slo.burn_rates()
        fast = max((rate for (_, window), rate in burns.items()
                    if window == "fast"), default=0.0)
        slow = max((rate for (_, window), rate in burns.items()
                    if window == "slow"), default=0.0)
        return ScaleSignals(pressure=pressure, fast_burn=fast,
                            slow_burn=slow)

    return read


class MembershipCountActuator:
    """Dry-run stand-in when no replica command is configured: the
    controller can still evaluate and export verdicts against the real
    membership count, but actuation always refuses (nothing owns the
    replicas)."""

    def __init__(self, membership, group: str = "stable"):
        self.membership = membership
        self.group = group

    def current(self) -> int:
        return sum(1 for b in self.membership.backends
                   if b.group == self.group)

    def add_replica(self) -> bool:
        return False

    def remove_replica(self) -> bool:
        return False


class SupervisedFleetActuator:
    """Actuation against a supervisor-owned replica set.

    Scale-up: ``make_spec(index)`` yields a fresh :class:`SpawnSpec`
    (the CLI's ``--replica-cmd`` template), the supervisor spawns it,
    and its backend joins membership marked DOWN — the probe loop marks
    it up once it actually serves, so the router never races a replica
    that is still importing jax. Scale-down: newest-first victim,
    DETACHED from membership before the supervisor's drain-then-SIGTERM
    sequence, so no new traffic can land after the verdict."""

    def __init__(self, supervisor: FleetSupervisor, membership,
                 make_spec: Callable[[int], SpawnSpec],
                 breaker_threshold: int = 3, breaker_reset_s: float = 5.0,
                 clock: Clock = SYSTEM_CLOCK):
        self.supervisor = supervisor
        self.membership = membership
        self.make_spec = make_spec
        self.breaker_threshold = breaker_threshold
        self.breaker_reset_s = breaker_reset_s
        self.clock = clock
        self._lock = threading.Lock()
        #: spec ids this actuator owns, spawn order (LIFO victims)
        self._owned: list[str] = []
        self._index = itertools.count(1)

    def adopt(self, spec_id: str) -> None:
        """Register a replica spawned at launch time (the CLI's initial
        ``--replicas``) as scale-managed."""
        with self._lock:
            self._owned.append(spec_id)

    def current(self) -> int:
        """Owned replicas that still count as capacity — a crash-looped
        child is NOT capacity (scaling up past a latched spec is
        exactly what an operator wants while triaging it)."""
        with self._lock:
            owned = set(self._owned)
        return sum(1 for doc in self.supervisor.children()
                   if doc["id"] in owned and doc["state"] != CRASH_LOOPED)

    def add_replica(self) -> bool:
        with self._lock:
            owned = set(self._owned)
        if any(doc["id"] in owned and doc["state"] == CRASH_LOOPED
               for doc in self.supervisor.children()):
            # a latched child means the replica SPEC is broken: another
            # spawn of the same command would latch too, and since
            # latched children don't count as capacity the min-replica
            # clamp would demand a fresh (identically broken) spawn
            # every cooldown forever — leaking children and DOWN
            # backends. Refuse until an operator clears the crash loop;
            # desired>actual + actuation_failed climbing is the alarm.
            logger.warning(
                "scale-up refused: a crash-looped replica is latched "
                "(pio_fleet_crash_loop=1) — triage it before the "
                "controller spawns more of the same spec "
                "(docs/fleet.md crash-loop runbook)")
            return False
        spec = self.make_spec(next(self._index))
        if spec.address is None:
            logger.warning("replica spec %s has no address; cannot "
                           "join membership", spec.id)
            return False
        try:
            self.supervisor.add(spec)
        except Exception:
            logger.exception("scale-up spawn of %s failed", spec.id)
            return False
        backend = Backend(BackendSpec.parse(spec.address, spec.group),
                          breaker_threshold=self.breaker_threshold,
                          breaker_reset_s=self.breaker_reset_s,
                          clock=self.clock)
        # join DOWN: the membership probe loop marks it up when the
        # child actually answers /healthz + /readyz
        backend.mark_down("starting")
        self.membership.add(backend)
        with self._lock:
            self._owned.append(spec.id)
        logger.info("scale-up: replica %s spawning at %s", spec.id,
                    spec.address)
        return True

    def remove_replica(self) -> bool:
        with self._lock:
            if not self._owned:
                return False
            spec_id = self._owned.pop()
        address = next((doc.get("address")
                        for doc in self.supervisor.children()
                        if doc["id"] == spec_id), None)
        if address is not None:
            # detach FIRST: this router stops routing there before the
            # drain begins (other routers notice via /readyz)
            self.membership.remove(address)
        self.supervisor.remove(spec_id, drain=True)
        logger.info("scale-down: replica %s drained and stopped", spec_id)
        return True
