"""The scale controller: the actor behind the autoscaling signals
(docs/fleet.md "Autoscaling").

PR 7 shipped the contract — ``pio_fleet_pressure`` and
``pio_slo_burn_rate{slo,window}`` on ``GET /fleet/metrics`` — and
documented the policy a controller should run. This module IS that
controller: a background loop that polls the router's own merged fleet
metrics and applies a hysteresis policy,

- **scale up** on SUSTAINED pressure above ``pressure_up`` (latency is
  queueing, not model time) or a fast-window SLO burn above
  ``burn_up`` (the incident is happening now),
- **scale down** only after a COOLDOWN of sustained quiet (pressure
  below ``pressure_down`` with both burn windows under 1.0),
- clamped to ``[min_replicas, max_replicas]``, with a global
  ``cooldown_s`` between actions so one hot scrape cannot ratchet the
  fleet,
- **dry-run first**: with ``dry_run`` the controller only EXPORTS its
  verdicts (``pio_fleet_desired_replicas`` vs actual, decision
  counters) so operators can watch it against production traffic
  before trusting it with actuation.

Everything is deterministic on the injectable Clock: ``tick()`` is the
loop body AND the test hook, and the decision table
(tests/test_fleet_supervisor.py) drives it with scripted signals on a
``ManualClock``. Actuation goes through a small interface so the
supervised-fleet actuator (spawn a replica via the supervisor, join it
to membership; detach + drain on the way down) and test doubles are
interchangeable.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import os
import re
import threading
from typing import Callable

from predictionio_tpu.fleet.membership import Backend, BackendSpec
from predictionio_tpu.fleet.supervisor import (
    CRASH_LOOPED,
    FleetSupervisor,
    SpawnSpec,
    _env_field,
)
from predictionio_tpu.obs.registry import Metric
from predictionio_tpu.utils.resilience import SYSTEM_CLOCK, Clock

logger = logging.getLogger(__name__)

UP, DOWN, HOLD, ERROR = "up", "down", "hold", "error"

#: decision counter keys (cooldown_hold = a verdict suppressed by the
#: global action cooldown; actuation_failed = the actuator said no)
DECISIONS = (UP, DOWN, HOLD, ERROR, "cooldown_hold", "actuation_failed")

#: decision attribution — every verdict carries WHY (docs/fleet.md
#: "Per-tenant elasticity"): `burn` (fast-window SLO burn tripped),
#: `pressure` (queue-bound), `quiet` (sustained calm), `steady`,
#: `cooldown`, `signals_unreadable`, or the actuator's own refusal
#: (`budget_exhausted`, `crash_loop`, ...). The lone-default unlabeled
#: exposition is untouched — reasons surface on the per-engine
#: `pio_fleet_scale_decisions_total{engine,decision,reason}` family and
#: in snapshots only.
REASON_BURN = "burn"
REASON_PRESSURE = "pressure"
REASON_QUIET = "quiet"
REASON_STEADY = "steady"
REASON_COOLDOWN = "cooldown"
REASON_SIGNALS = "signals_unreadable"


@dataclasses.dataclass(frozen=True)
class ScaleSignals:
    """One poll of the autoscaling contract. ``pressure`` is None when
    the fleet scrape produced no pressure gauge (no traffic yet, or
    every replica scrape failed) — the controller treats that as
    neither hot nor quiet."""

    pressure: float | None
    fast_burn: float = 0.0
    slow_burn: float = 0.0


@dataclasses.dataclass(frozen=True)
class ScalePolicy:
    """Controller knobs, ``PIO_FLEET_*`` env-overridable at
    construction (docs/fleet.md "Autoscaling" has the table)."""

    min_replicas: int = _env_field("MIN_REPLICAS", 1, int)
    max_replicas: int = _env_field("MAX_REPLICAS", 4, int)
    #: scale-up triggers: queue-bound pressure, or the classic 5m-fast
    #: burn threshold for a 99.9% objective (14.4 = the page line)
    pressure_up: float = _env_field("PRESSURE_UP", 0.5, float)
    burn_up: float = _env_field("BURN_UP", 14.4, float)
    #: scale-down trigger: pressure at or below this AND both burn
    #: windows under 1.0 (budget spend at sustainable rate)
    pressure_down: float = _env_field("PRESSURE_DOWN", 0.1, float)
    #: how long a trigger must hold before it becomes a verdict
    up_sustain_s: float = _env_field("UP_SUSTAIN_S", 15.0, float)
    down_sustain_s: float = _env_field("DOWN_SUSTAIN_S", 120.0, float)
    #: minimum gap between ACTIONS (and dry-run verdicts): one hot
    #: scrape must not ratchet the fleet replica-by-replica
    cooldown_s: float = _env_field("COOLDOWN_S", 60.0, float)
    #: poll cadence of the background loop
    interval_s: float = _env_field("SCALE_INTERVAL_S", 5.0, float)
    #: export decisions without actuating (the rollout posture)
    dry_run: bool = False


class ScaleController:
    """Hysteresis policy loop over ``read_signals`` + an actuator
    (module docstring)."""

    def __init__(self, policy: ScalePolicy,
                 read_signals: Callable[[], ScaleSignals],
                 actuator, clock: Clock = SYSTEM_CLOCK):
        self.policy = policy
        self.read_signals = read_signals
        self.actuator = actuator
        self.clock = clock
        self._lock = threading.Lock()
        self._counts = dict.fromkeys(DECISIONS, 0)
        #: ``(decision, reason) -> count`` — the attribution behind the
        #: per-engine decision counters; ``_counts`` stays the pinned
        #: unlabeled view
        self._reasons: dict[tuple[str, str], int] = {}
        self._last_decision: str | None = None
        self._last_reason: str | None = None
        self._hot_since: float | None = None
        self._quiet_since: float | None = None
        self._last_action_at: float | None = None
        self._desired: int | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- the decision engine --------------------------------------------------
    def tick(self) -> str:
        """One evaluation — the loop body and the deterministic test
        hook. Returns the decision taken."""
        p = self.policy
        now = self.clock.monotonic()
        try:
            signals = self.read_signals()
        except Exception as exc:  # noqa: BLE001 — a failed scrape is a held tick
            logger.warning("scale signals unreadable: %s", exc)
            return self._count(ERROR, REASON_SIGNALS)
        current = self.actuator.current()
        hot = ((signals.pressure is not None
                and signals.pressure >= p.pressure_up)
               or signals.fast_burn >= p.burn_up)
        quiet = (signals.pressure is not None
                 and signals.pressure <= p.pressure_down
                 and signals.fast_burn < 1.0 and signals.slow_burn < 1.0)
        if hot:
            if self._hot_since is None:     # not `or`: t=0 is a real time
                self._hot_since = now
            self._quiet_since = None
        elif quiet:
            if self._quiet_since is None:
                self._quiet_since = now
            self._hot_since = None
        else:
            # neither hot nor quiet resets BOTH sustain windows — the
            # hysteresis that keeps a flapping signal from scaling
            self._hot_since = self._quiet_since = None
        delta = 0
        if hot and now - self._hot_since >= p.up_sustain_s:
            delta = 1
        elif quiet and now - self._quiet_since >= p.down_sustain_s:
            delta = -1
        desired = min(p.max_replicas, max(p.min_replicas, current + delta))
        if desired == current:
            self._set_desired(desired)
            return self._count(HOLD, REASON_STEADY)
        with self._lock:
            last_action = self._last_action_at
        if last_action is not None and now - last_action < p.cooldown_s:
            self._set_desired(current)
            return self._count("cooldown_hold", REASON_COOLDOWN)
        # a verdict: record it, restart the sustain windows, and (when
        # not dry-running) actuate one step. The reason names the
        # TRIGGER: a scale-up is attributed to the fast-window burn when
        # it tripped (it outranks pressure in the arbiter too), else to
        # pressure; a scale-down is always "quiet" (both conditions must
        # hold by construction)
        self._set_desired(desired)
        with self._lock:
            self._last_action_at = now
        self._hot_since = self._quiet_since = None
        decision = UP if desired > current else DOWN
        reason = (REASON_QUIET if decision == DOWN
                  else REASON_BURN if signals.fast_burn >= p.burn_up
                  else REASON_PRESSURE)
        if p.dry_run:
            logger.info("scale %s verdict (dry-run): desired %d vs "
                        "actual %d", decision, desired, current)
            return self._count(decision, reason)
        acted = (self.actuator.add_replica() if decision == UP
                 else self.actuator.remove_replica())
        out = self._count(decision, reason)
        if not acted:
            # attribute the refusal AFTER the verdict so lastDecision
            # reads the failure: the actuator says why when it can
            # (ArbitratedActuator.last_refusal carries the arbiter's
            # budget verdict)
            self._count("actuation_failed",
                        getattr(self.actuator, "last_refusal", None)
                        or "actuator_refused")
            logger.warning("scale %s actuation failed (desired %d, "
                           "actual %d)", decision, desired, current)
        return out

    def _count(self, decision: str, reason: str) -> str:
        with self._lock:
            self._counts[decision] += 1
            key = (decision, reason)
            self._reasons[key] = self._reasons.get(key, 0) + 1
            self._last_decision = decision
            self._last_reason = reason
        return decision

    @property
    def last_action_at(self) -> float | None:
        """Clock time of the last up/down verdict — the arbiter's
        cooldown-seniority input (None = never acted)."""
        with self._lock:
            return self._last_action_at

    def _set_desired(self, desired: int) -> None:
        with self._lock:
            self._desired = desired

    # -- reporting ------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            counts = dict(self._counts)
            desired = self._desired
            reasons = dict(self._reasons)
            last_decision = self._last_decision
            last_reason = self._last_reason
        by_reason: dict[str, dict[str, int]] = {}
        for (decision, reason), n in reasons.items():
            by_reason.setdefault(decision, {})[reason] = n
        return {
            "dryRun": self.policy.dry_run,
            "minReplicas": self.policy.min_replicas,
            "maxReplicas": self.policy.max_replicas,
            "desiredReplicas": desired,
            "actualReplicas": self.actuator.current(),
            "decisions": counts,
            "decisionReasons": by_reason,
            "lastDecision": last_decision,
            "lastReason": last_reason,
        }

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="pio-fleet-scaler", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(self.policy.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def controller_collector(controller: ScaleController):
    """Registry adapter: desired vs actual replica gauges + decision
    counters — the whole dry-run trust story is these families."""

    def collect() -> list[Metric]:
        snap = controller.snapshot()
        out = [
            Metric(name="pio_fleet_desired_replicas", kind="gauge",
                   help="Replica count the scale controller wants "
                        "(compare with pio_fleet_actual_replicas; in "
                        "--scale-dry-run only this moves)",
                   samples=[({}, float(snap["desiredReplicas"]
                                       if snap["desiredReplicas"]
                                       is not None
                                       else snap["actualReplicas"]))]),
            Metric(name="pio_fleet_actual_replicas", kind="gauge",
                   help="Replicas the actuator currently owns",
                   samples=[({}, float(snap["actualReplicas"]))]),
            Metric(name="pio_fleet_scale_dry_run", kind="gauge",
                   help="1 while the controller only exports verdicts",
                   samples=[({}, 1.0 if snap["dryRun"] else 0.0)]),
        ]
        decisions = Metric(
            name="pio_fleet_scale_decisions_total", kind="counter",
            help="Scale controller verdicts by outcome")
        for decision, n in sorted(snap["decisions"].items()):
            decisions.samples.append(({"decision": decision}, float(n)))
        out.append(decisions)
        return out

    return collect


# ---------------------------------------------------------------------------
# signal reader + the supervised-fleet actuator
# ---------------------------------------------------------------------------

def fleet_signals_reader(service) -> Callable[[], ScaleSignals]:
    """Read the autoscaling contract off the router's OWN merged fleet
    metrics — the controller consumes exactly what an external operator
    would scrape from ``GET /fleet/metrics`` (docs/fleet.md), so
    trusting the dry-run gauges means trusting the real inputs. The
    Metric families are consumed BEFORE text rendering
    (``fleet_metrics_families``): same scrape, same merge, without a
    render→reparse round-trip stealing serving CPU every tick. Burn
    rates come from the router's SLO engine (what clients experienced:
    sheds spend budget)."""

    def read() -> ScaleSignals:
        pressure: float | None = None
        for family in service.fleet_metrics_families():
            if family.name == "pio_fleet_pressure" and family.samples:
                pressure = family.samples[0][1]
        burns = service.slo.max_burns()
        return ScaleSignals(pressure=pressure,
                            fast_burn=burns.get("fast", 0.0),
                            slow_burn=burns.get("slow", 0.0))

    return read


class MembershipCountActuator:
    """Dry-run stand-in when no replica command is configured: the
    controller can still evaluate and export verdicts against the real
    membership count, but actuation always refuses (nothing owns the
    replicas)."""

    def __init__(self, membership, group: str = "stable"):
        self.membership = membership
        self.group = group

    def current(self) -> int:
        return sum(1 for b in self.membership.backends
                   if b.group == self.group)

    def add_replica(self) -> bool:
        return False

    def remove_replica(self, reason: str | None = None) -> bool:
        return False


class SupervisedFleetActuator:
    """Actuation against a supervisor-owned replica set.

    Scale-up: ``make_spec(index)`` yields a fresh :class:`SpawnSpec`
    (the CLI's ``--replica-cmd`` template), the supervisor spawns it,
    and its backend joins membership marked DOWN — the probe loop marks
    it up once it actually serves, so the router never races a replica
    that is still importing jax. Scale-down: newest-first victim,
    DETACHED from membership before the supervisor's drain-then-SIGTERM
    sequence, so no new traffic can land after the verdict."""

    def __init__(self, supervisor: FleetSupervisor, membership,
                 make_spec: Callable[[int], SpawnSpec],
                 breaker_threshold: int = 3, breaker_reset_s: float = 5.0,
                 clock: Clock = SYSTEM_CLOCK):
        self.supervisor = supervisor
        self.membership = membership
        self.make_spec = make_spec
        self.breaker_threshold = breaker_threshold
        self.breaker_reset_s = breaker_reset_s
        self.clock = clock
        self._lock = threading.Lock()
        #: spec ids this actuator owns, spawn order (LIFO victims)
        self._owned: list[str] = []
        self._index = itertools.count(1)

    def adopt(self, spec_id: str) -> None:
        """Register a replica spawned at launch time (the CLI's initial
        ``--replicas``) as scale-managed."""
        with self._lock:
            self._owned.append(spec_id)

    def current(self) -> int:
        """Owned replicas that still count as capacity — a crash-looped
        child is NOT capacity (scaling up past a latched spec is
        exactly what an operator wants while triaging it)."""
        with self._lock:
            owned = set(self._owned)
        return sum(1 for doc in self.supervisor.children()
                   if doc["id"] in owned and doc["state"] != CRASH_LOOPED)

    def add_replica(self) -> bool:
        with self._lock:
            owned = set(self._owned)
        if any(doc["id"] in owned and doc["state"] == CRASH_LOOPED
               for doc in self.supervisor.children()):
            # a latched child means the replica SPEC is broken: another
            # spawn of the same command would latch too, and since
            # latched children don't count as capacity the min-replica
            # clamp would demand a fresh (identically broken) spawn
            # every cooldown forever — leaking children and DOWN
            # backends. Refuse until an operator clears the crash loop;
            # desired>actual + actuation_failed climbing is the alarm.
            logger.warning(
                "scale-up refused: a crash-looped replica is latched "
                "(pio_fleet_crash_loop=1) — triage it before the "
                "controller spawns more of the same spec "
                "(docs/fleet.md crash-loop runbook)")
            return False
        spec = self.make_spec(next(self._index))
        if spec.address is None:
            logger.warning("replica spec %s has no address; cannot "
                           "join membership", spec.id)
            return False
        try:
            self.supervisor.add(spec)
        except Exception:
            logger.exception("scale-up spawn of %s failed", spec.id)
            return False
        backend = Backend(BackendSpec.parse(spec.address, spec.group),
                          breaker_threshold=self.breaker_threshold,
                          breaker_reset_s=self.breaker_reset_s,
                          clock=self.clock)
        # join DOWN: the membership probe loop marks it up when the
        # child actually answers /healthz + /readyz
        backend.mark_down("starting")
        self.membership.add(backend)
        with self._lock:
            self._owned.append(spec.id)
        logger.info("scale-up: replica %s spawning at %s", spec.id,
                    spec.address)
        return True

    def remove_replica(self, reason: str | None = None) -> bool:
        with self._lock:
            if not self._owned:
                return False
            spec_id = self._owned.pop()
        address = next((doc.get("address")
                        for doc in self.supervisor.children()
                        if doc["id"] == spec_id), None)
        if address is not None:
            # detach FIRST: this router stops routing there before the
            # drain begins (other routers notice via /readyz)
            self.membership.remove(address)
        self.supervisor.remove(spec_id, drain=True, reason=reason)
        logger.info("scale-down: replica %s drained and stopped%s",
                    spec_id, f" ({reason})" if reason else "")
        return True


# ---------------------------------------------------------------------------
# per-tenant elasticity: the arbiter, the per-engine policy resolver,
# and the scale set that runs one controller per engine group
# (docs/fleet.md "Per-tenant elasticity")
# ---------------------------------------------------------------------------

class CapacityArbiter:
    """The fleet-wide replica budget and its contention policy.

    Every per-engine scale-up flows through :meth:`request_up` (via
    :class:`ArbitratedActuator`). With ``budget == 0`` (unlimited) every
    request is granted — each engine's own ``max_replicas`` clamp is the
    only ceiling. With a budget, the arbiter enforces a GLOBAL device/
    HBM replica count across every registered tenant:

    - **used capacity** sums each tenant actuator's ``current()`` —
      which already excludes crash-looped children
      (:meth:`SupervisedFleetActuator.current`), so a latched replica
      frees its budget slot exactly as it stops counting as capacity;
    - when the budget is spent, a scale-up may **preempt** an IDLE
      tenant's above-min replica: the victim must be quiet (fast burn
      under 1.0, pressure under its own ``pressure_up``) and above its
      ``min_replicas`` floor, and it is retired through the actuator's
      drain-then-retire path — never killed. Hot-vs-hot contention is a
      deny, not a tug-of-war;
    - **priority** is burn-rate-weighted: fast-window burn beats
      pressure beats cooldown seniority (longest since last action
      wins ties) — both for picking the preemption victim (lowest
      priority) and for the scale set's tick ordering, so when two
      tenants want the last slot the hotter one asks first.
    """

    def __init__(self, budget: int = 0, clock: Clock = SYSTEM_CLOCK):
        self.budget = max(0, int(budget or 0))
        self.clock = clock
        self._lock = threading.Lock()
        self._tenants: dict[str, dict] = {}
        self._grants: dict[str, int] = {}
        self._denials: dict[str, int] = {}
        self._preemptions: dict[str, int] = {}

    def register(self, name: str, policy: ScalePolicy, actuator,
                 last_action: Callable[[], float | None] | None = None
                 ) -> None:
        with self._lock:
            self._tenants[name] = {
                "policy": policy, "actuator": actuator,
                "signals": None, "last_action": last_action,
            }

    def observe(self, name: str, signals: ScaleSignals | None) -> None:
        """The scale set pushes each engine's latest sweep signals here
        — one fleet scrape feeds N tenants AND the arbiter."""
        with self._lock:
            tenant = self._tenants.get(name)
            if tenant is not None:
                tenant["signals"] = signals

    def used(self) -> int:
        """Replicas currently counting against the budget (crash-looped
        children are excluded by the actuators themselves)."""
        with self._lock:
            actuators = [t["actuator"] for t in self._tenants.values()]
        return sum(a.current() for a in actuators)

    def priority(self, name: str) -> tuple[float, float, float]:
        """``(fast_burn, pressure, seniority)`` — compared
        lexicographically: burn beats pressure beats cooldown seniority
        (seconds since the tenant's last scale action; never-acted =
        infinitely senior)."""
        with self._lock:
            tenant = self._tenants.get(name)
            if tenant is None:
                return (0.0, 0.0, 0.0)
            signals = tenant["signals"]
            last_action = tenant["last_action"]
        fast = signals.fast_burn if signals is not None else 0.0
        pressure = (signals.pressure
                    if signals is not None and signals.pressure is not None
                    else 0.0)
        last = last_action() if last_action is not None else None
        seniority = (float("inf") if last is None
                     else self.clock.monotonic() - last)
        return (fast, pressure, seniority)

    def _bump(self, table: dict[str, int], name: str) -> None:
        with self._lock:
            table[name] = table.get(name, 0) + 1

    def _pick_victim(self, requester: str):
        """The lowest-priority IDLE tenant holding an above-min replica,
        or None. Idle = fast burn under 1.0 AND pressure under its own
        scale-up threshold (an unknown pressure — no traffic — is
        idle). ``current()`` is read outside the lock: actuators take
        their own locks and may call back into the supervisor."""
        with self._lock:
            items = [(name, dict(t)) for name, t in self._tenants.items()]
        candidates = []
        for name, tenant in items:
            if name == requester:
                continue
            signals = tenant["signals"]
            if signals is not None and signals.fast_burn >= 1.0:
                continue
            if signals is not None and signals.pressure is not None \
                    and signals.pressure >= tenant["policy"].pressure_up:
                continue
            if tenant["actuator"].current() <= tenant["policy"].min_replicas:
                continue
            candidates.append((name, tenant["actuator"]))
        if not candidates:
            return None
        return min(candidates, key=lambda nv: self.priority(nv[0]))

    def request_up(self, name: str) -> tuple[bool, str]:
        """``(granted, reason)`` — reason is the attribution string the
        controller counts on denial (``budget_exhausted``) and the log
        line on preemption (``preempted_<victim>``)."""
        if self.budget <= 0:
            self._bump(self._grants, name)
            return True, "unbudgeted"
        if self.used() < self.budget:
            self._bump(self._grants, name)
            return True, "within_budget"
        victim = self._pick_victim(name)
        if victim is not None:
            victim_name, actuator = victim
            # drain-then-retire, never kill: the victim's replica goes
            # through the actuator's detach-membership-first +
            # supervisor-drain sequence, same as any scale-down
            if actuator.remove_replica(
                    reason=f"preempted_by_{name}"):
                self._bump(self._preemptions, victim_name)
                self._bump(self._grants, name)
                logger.info(
                    "budget preemption: %s's above-min replica drained "
                    "for high-priority tenant %s", victim_name, name)
                return True, f"preempted_{victim_name}"
        self._bump(self._denials, name)
        return False, "budget_exhausted"

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "budget": self.budget or None,
                "grants": dict(self._grants),
                "denials": dict(self._denials),
                "preemptions": dict(self._preemptions),
            }


class ArbitratedActuator:
    """Wraps a tenant's actuator so every scale-up consults the
    :class:`CapacityArbiter` first. On denial, ``last_refusal`` carries
    the arbiter's verdict for the controller's ``actuation_failed``
    attribution."""

    def __init__(self, name: str, inner, arbiter: CapacityArbiter):
        self.name = name
        self.inner = inner
        self.arbiter = arbiter
        self.last_refusal: str | None = None

    def current(self) -> int:
        return self.inner.current()

    def add_replica(self) -> bool:
        granted, verdict = self.arbiter.request_up(self.name)
        if not granted:
            self.last_refusal = verdict
            return False
        if self.inner.add_replica():
            self.last_refusal = None
            return True
        self.last_refusal = getattr(self.inner, "last_refusal", None) \
            or "actuator_refused"
        return False

    def remove_replica(self, reason: str | None = None) -> bool:
        return self.inner.remove_replica(reason=reason)


#: ScalePolicy field -> (env key suffix, cast) for the per-engine
#: ``PIO_FLEET_ENGINE_<NAME>_<KEY>`` overrides — same suffixes as the
#: global ``PIO_FLEET_<KEY>`` table (docs/fleet.md)
_POLICY_ENV_KEYS: dict[str, tuple[str, type]] = {
    "min_replicas": ("MIN_REPLICAS", int),
    "max_replicas": ("MAX_REPLICAS", int),
    "pressure_up": ("PRESSURE_UP", float),
    "burn_up": ("BURN_UP", float),
    "pressure_down": ("PRESSURE_DOWN", float),
    "up_sustain_s": ("UP_SUSTAIN_S", float),
    "down_sustain_s": ("DOWN_SUSTAIN_S", float),
    "cooldown_s": ("COOLDOWN_S", float),
    "interval_s": ("SCALE_INTERVAL_S", float),
}


def engine_scale_policy(name: str, dry_run: bool = False,
                        base: dict | None = None,
                        **overrides) -> ScalePolicy:
    """Resolve one tenant's :class:`ScalePolicy` with the documented
    precedence: explicit per-engine override (the ``--engine
    ...,min-replicas=,max-replicas=`` flag keys) beats
    ``PIO_FLEET_ENGINE_<NAME>_<KEY>`` env beats the router-wide
    ``base`` (the global ``--scale-*`` flags) beats the global
    ``PIO_FLEET_<KEY>`` env/defaults that :class:`ScalePolicy` itself
    reads. Engine names sanitize to env tokens by replacing every
    non-alphanumeric with ``_`` and upper-casing (``rec-v2`` →
    ``REC_V2``)."""
    token = re.sub(r"[^A-Za-z0-9]", "_", name).upper()
    kwargs = {k: v for k, v in overrides.items() if v is not None}
    for field, (key, cast) in _POLICY_ENV_KEYS.items():
        if field in kwargs:
            continue
        raw = os.environ.get(f"PIO_FLEET_ENGINE_{token}_{key}")
        if raw is not None:
            try:
                kwargs[field] = cast(raw)
                continue
            except ValueError:
                logger.warning(
                    "ignoring unparseable PIO_FLEET_ENGINE_%s_%s=%r",
                    token, key, raw)
        if base and base.get(field) is not None:
            kwargs[field] = base[field]
    return ScalePolicy(dry_run=dry_run, **kwargs)


class EngineScaleSet:
    """One :class:`ScaleController` per engine group under a shared
    :class:`CapacityArbiter` — the per-tenant elasticity loop
    (docs/fleet.md).

    Each tenant keeps its OWN hysteresis, sustain windows, cooldown and
    min/max bounds (engine A's cooldown never delays engine B), but the
    sweep is shared: ``tick_all`` fetches the router's merged fleet
    metric families ONCE, splits the per-engine ``pio_fleet_pressure``
    samples and per-engine SLO burns out of the one scrape, pushes each
    tenant's signals to the arbiter, then ticks the controllers in
    DESCENDING priority order — when two hot tenants want the last
    budget slot, the burn-weighted winner asks first. One scrape per
    sweep, not per tenant: N engines cost the same fan-out as one."""

    def __init__(self, service, arbiter: CapacityArbiter,
                 interval_s: float = 5.0, clock: Clock = SYSTEM_CLOCK):
        self.service = service
        self.arbiter = arbiter
        self.interval_s = interval_s
        self.clock = clock
        self._lock = threading.Lock()
        self._controllers: dict[str, ScaleController] = {}
        #: latest sweep's per-engine signals; readers raise on a missing
        #: entry so a failed sweep counts an ERROR tick per controller
        self._sweep: dict[str, ScaleSignals] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def gateway(self):
        return self.service.gateway

    def add_engine(self, name: str, policy: ScalePolicy,
                   actuator) -> ScaleController:
        """Register one tenant: its actuator is wrapped so scale-ups
        consult the arbiter, and its controller reads signals from the
        shared sweep cache."""
        wrapped = ArbitratedActuator(name, actuator, self.arbiter)
        controller = ScaleController(
            policy, self._reader_for(name), wrapped, clock=self.clock)
        self.arbiter.register(
            name, policy, wrapped,
            last_action=lambda: controller.last_action_at)
        with self._lock:
            self._controllers[name] = controller
        return controller

    def controllers(self) -> dict[str, ScaleController]:
        with self._lock:
            return dict(self._controllers)

    def get(self, name: str) -> ScaleController | None:
        with self._lock:
            return self._controllers.get(name)

    def _reader_for(self, name: str) -> Callable[[], ScaleSignals]:
        def read() -> ScaleSignals:
            with self._lock:
                signals = self._sweep.get(name)
            if signals is None:
                raise RuntimeError(
                    f"no fleet signals for engine {name!r} this sweep")
            return signals

        return read

    def sweep_signals(self) -> dict[str, ScaleSignals]:
        """ONE fleet scrape split per engine: the labeled
        ``pio_fleet_pressure{engine}`` samples (the unlabeled sample
        serves the lone implicit default engine) plus each engine
        group's own SLO burn windows."""
        with self._lock:
            names = list(self._controllers)
        pressures: dict[str | None, float] = {}
        for family in self.service.fleet_metrics_families():
            if family.name != "pio_fleet_pressure":
                continue
            for labels, value in family.samples:
                pressures[labels.get("engine")] = value
        gateway = self.service.gateway
        sweep: dict[str, ScaleSignals] = {}
        for name in names:
            pressure = pressures.get(name)
            if pressure is None and not gateway.labeled:
                pressure = pressures.get(None)
            group = gateway.get(name)
            burns = group.slo.max_burns() if group is not None else {}
            sweep[name] = ScaleSignals(
                pressure=pressure,
                fast_burn=burns.get("fast", 0.0),
                slow_burn=burns.get("slow", 0.0))
        return sweep

    def tick_all(self) -> list[str]:
        """One sweep — the loop body and the deterministic test hook.
        Returns the engine names in the order they were ticked."""
        try:
            sweep = self.sweep_signals()
        except Exception as exc:  # noqa: BLE001 — a failed sweep holds every tenant
            logger.warning("fleet sweep unreadable: %s", exc)
            sweep = {}
        with self._lock:
            self._sweep = sweep
            controllers = dict(self._controllers)
        for name in controllers:
            self.arbiter.observe(name, sweep.get(name))
        # descending priority: the hottest tenant's scale-up reaches
        # the arbiter first, so "two tenants want the last slot" is
        # decided by burn > pressure > seniority, not dict order
        order = sorted(controllers,
                       key=self.arbiter.priority, reverse=True)
        for name in order:
            controllers[name].tick()
        return order

    def snapshot(self) -> dict:
        with self._lock:
            controllers = dict(self._controllers)
        return {
            "budget": self.arbiter.budget or None,
            "used": self.arbiter.used(),
            "arbiter": self.arbiter.snapshot(),
            "engines": {name: controller.snapshot()
                        for name, controller in controllers.items()},
        }

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="pio-fleet-scale-set", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.tick_all()
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def scale_set_collector(scale_set: EngineScaleSet):
    """Registry adapter for the per-tenant loop. A lone implicit
    default engine delegates to :func:`controller_collector` — the
    unlabeled exposition stays byte-identical (the PR 15 convention).
    Explicitly multi-engine deployments export the same families with
    an ``engine`` label, the decision counters gain ``reason``
    attribution, and the budget/arbiter families appear."""

    def collect() -> list[Metric]:
        from predictionio_tpu.obs.registry import merge_families

        controllers = scale_set.controllers()
        if not scale_set.gateway.labeled and len(controllers) == 1:
            (controller,) = controllers.values()
            return controller_collector(controller)()
        desired = Metric(
            name="pio_fleet_desired_replicas", kind="gauge",
            help="Replica count the scale controller wants "
                 "(compare with pio_fleet_actual_replicas; in "
                 "--scale-dry-run only this moves)")
        actual = Metric(
            name="pio_fleet_actual_replicas", kind="gauge",
            help="Replicas the actuator currently owns")
        dry = Metric(
            name="pio_fleet_scale_dry_run", kind="gauge",
            help="1 while the controller only exports verdicts")
        decisions = Metric(
            name="pio_fleet_scale_decisions_total", kind="counter",
            help="Scale controller verdicts by engine, outcome and "
                 "reason (docs/fleet.md \"Per-tenant elasticity\")")
        for name, controller in controllers.items():
            snap = controller.snapshot()
            labels = {"engine": name}
            desired.samples.append(
                (labels, float(snap["desiredReplicas"]
                               if snap["desiredReplicas"] is not None
                               else snap["actualReplicas"])))
            actual.samples.append((labels, float(snap["actualReplicas"])))
            dry.samples.append((labels, 1.0 if snap["dryRun"] else 0.0))
            for decision, reasons in sorted(
                    snap["decisionReasons"].items()):
                for reason, n in sorted(reasons.items()):
                    decisions.samples.append(
                        ({"engine": name, "decision": decision,
                          "reason": reason}, float(n)))
        arbiter = scale_set.arbiter.snapshot()
        budget = Metric(
            name="pio_fleet_replica_budget", kind="gauge",
            help="Fleet-wide replica budget the CapacityArbiter "
                 "enforces (0 = unlimited)",
            samples=[({}, float(arbiter["budget"] or 0))])
        used = Metric(
            name="pio_fleet_replica_budget_used", kind="gauge",
            help="Replicas currently counting against the budget "
                 "(crash-looped children excluded)",
            samples=[({}, float(scale_set.arbiter.used()))])
        preempt = Metric(
            name="pio_fleet_preemptions_total", kind="counter",
            help="Above-min replicas drained from this (victim) engine "
                 "to free budget for a higher-priority tenant")
        denials = Metric(
            name="pio_fleet_budget_denials_total", kind="counter",
            help="Scale-ups the arbiter refused for lack of budget "
                 "and preemptable capacity")
        for name, n in sorted(arbiter["preemptions"].items()):
            preempt.samples.append(({"engine": name}, float(n)))
        for name, n in sorted(arbiter["denials"].items()):
            denials.samples.append(({"engine": name}, float(n)))
        return merge_families(
            [desired, actual, dry, decisions, budget, used, preempt,
             denials])

    return collect
