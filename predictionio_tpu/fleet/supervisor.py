"""Self-healing fleet: the process supervisor (docs/fleet.md
"Supervision").

PR 6/7 deliberately stopped at "dead children are not respawned" — an
operator restarting JVMs is the reference PredictionIO's deployment
story, and it is exactly the story a self-healing fleet deletes. The
supervisor owns replica/worker child processes from declarative
:class:`SpawnSpec` s and closes the loop:

- **liveness** — pid (``poll()``) plus an optional bounded ``/healthz``
  probe over the lean fleet transport; children are checked
  CONCURRENTLY (``fleet/transport.fan_out``) so one wedged child eats
  its own probe timeout, not the whole pass;
- **respawn with damping** — a dead child is restarted after a
  full-jitter exponential backoff drawn from the shared
  :class:`~predictionio_tpu.utils.resilience.RetryPolicy` semantics
  (the AWS-discipline the storage layer already uses), on the
  injectable :class:`~predictionio_tpu.utils.resilience.Clock` so the
  whole schedule is deterministic under ``ManualClock``;
- **crash-loop damping** — ``crash_loop_threshold`` deaths inside
  ``crash_loop_window_s`` latch the child into a GIVE-UP state
  (visible as ``pio_fleet_crash_loop``) instead of hot-spinning spawn
  attempts against a child that exits immediately;
- **drain before kill** — a removed replica is drained first
  (``POST /drain`` flips its ``/readyz`` to 503 so EVERY router's
  membership loop stops routing there, confirmed by a bounded
  ``/readyz`` poll, then a settle period for in-flight work), then
  SIGTERM with a grace window, then SIGKILL — the ordering the
  drain-before-kill test pins;
- **full-fleet shutdown** — :meth:`FleetSupervisor.shutdown` drains
  and stops EVERY child, which is what routes a parent SIGTERM into a
  graceful fleet-wide drain (fixing the documented "stop from the
  shell stops one worker" quirk).

Every probe/drain exchange carries a timeout (the untimed-blocking-io
lint contract) and the supervision loop never calls ``time.sleep`` —
waits ride the injected clock or the stop event, which the lint rule
for ``fleet/`` now enforces (docs/static-analysis.md).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from collections import deque
from typing import Any, Callable

from predictionio_tpu.fleet.transport import BackendTransport, fan_out
from predictionio_tpu.obs.registry import Metric
from predictionio_tpu.utils.envcfg import env_field
from predictionio_tpu.utils.resilience import (
    SYSTEM_CLOCK,
    Clock,
    RetryPolicy,
)

logger = logging.getLogger(__name__)

#: child lifecycle states
RUNNING, BACKOFF, CRASH_LOOPED, STOPPED = (
    "running", "backoff", "crash_looped", "stopped")

REPLICA, WORKER = "replica", "worker"


@dataclasses.dataclass(frozen=True)
class SpawnSpec:
    """One supervised child, declaratively: a stable identity, how to
    (re)launch it, and — for replicas — the address whose ``/healthz``
    and drain surfaces the supervisor talks to. ``spawn`` returns a
    process handle satisfying the ``subprocess.Popen`` slice the
    supervisor uses: ``pid``, ``poll()`` (None while alive),
    ``terminate()``, ``kill()``, ``wait(timeout=...)``."""

    id: str
    spawn: Callable[[], Any]
    role: str = REPLICA
    #: ``host:port`` probed for liveness and drained on removal; None
    #: (worker siblings on a shared SO_REUSEPORT port) = pid-only
    address: str | None = None
    group: str = "stable"


class ProcessHandle:
    """``multiprocessing.Process`` adapted to the Popen handle contract
    (router worker siblings are multiprocessing children, replicas are
    ``subprocess.Popen`` which satisfies it natively)."""

    def __init__(self, process):
        self._process = process
        if process.pid is None:
            process.start()

    @property
    def pid(self) -> int:
        return self._process.pid

    def poll(self) -> int | None:
        return self._process.exitcode

    def terminate(self) -> None:
        self._process.terminate()

    def kill(self) -> None:
        self._process.kill()

    def wait(self, timeout: float | None = None) -> int | None:
        self._process.join(timeout)
        return self._process.exitcode


def _env_field(key: str, default, cast):
    """``PIO_FLEET_<KEY>`` env-overridable frozen-dataclass default,
    read at construction time (the ServerConfig discipline; shared
    implementation in utils/envcfg.py)."""
    return env_field("PIO_FLEET_", key, default, cast)


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    """Supervision knobs (docs/fleet.md "Supervision" has the table)."""

    #: supervision pass cadence (liveness checks + due respawns)
    poll_interval_s: float = _env_field("POLL_INTERVAL_S", 0.5, float)
    #: socket bound per /healthz probe and per drain exchange
    probe_timeout_s: float = _env_field("PROBE_TIMEOUT_S", 1.0, float)
    #: consecutive failed /healthz probes on a LIVE pid before the
    #: child is declared wedged and recycled; 0 disables (pid-only).
    #: Generous by default: the probe-starvation pitfall
    #: (docs/fleet.md runbook) applies here exactly as it does to
    #: router membership — a GIL-saturated child answers late, and
    #: recycling a healthy-but-busy process is worse than waiting
    unhealthy_after: int = _env_field("UNHEALTHY_AFTER", 10, int)
    #: full-jitter exponential respawn backoff (RetryPolicy semantics)
    backoff_base_s: float = _env_field("BACKOFF_BASE_S", 0.5, float)
    backoff_max_s: float = _env_field("BACKOFF_MAX_S", 30.0, float)
    backoff_multiplier: float = _env_field("BACKOFF_MULTIPLIER", 2.0, float)
    #: crash-loop damping: this many deaths inside the window latches
    #: the child into give-up instead of respawning forever
    crash_loop_threshold: int = _env_field("CRASH_LOOP_THRESHOLD", 5, int)
    crash_loop_window_s: float = _env_field("CRASH_LOOP_WINDOW_S", 60.0, float)
    #: drain-before-kill bounds: how long to wait for /readyz to
    #: acknowledge the drain, poll cadence, and the settle period that
    #: lets routers notice and in-flight work finish before SIGTERM
    drain_timeout_s: float = _env_field("DRAIN_TIMEOUT_S", 10.0, float)
    drain_poll_s: float = _env_field("DRAIN_POLL_S", 0.25, float)
    drain_settle_s: float = _env_field("DRAIN_SETTLE_S", 1.0, float)
    #: SIGTERM grace before SIGKILL
    term_grace_s: float = _env_field("TERM_GRACE_S", 5.0, float)
    #: accessKey appended to POST /drain for replicas launched with a
    #: server key (engine_server._check_server_key) — without it a
    #: keyed replica answers 401 and the drain degrades to bare
    #: SIGTERM exactly for secured deployments
    drain_key: str | None = _env_field("DRAIN_KEY", None, str)

    def backoff_policy(self) -> RetryPolicy:
        return RetryPolicy(
            max_attempts=1,  # the supervisor loops; the policy only
                             # contributes the jittered delay schedule
            base_delay=self.backoff_base_s,
            max_delay=self.backoff_max_s,
            multiplier=self.backoff_multiplier,
            jitter=True,
        )


class _Child:
    """Mutable supervision state for one spec. Guarded by the
    supervisor-wide lock; the spawn/probe/drain I/O itself runs outside
    it (one child's slow exchange must not freeze the bookkeeping)."""

    def __init__(self, spec: SpawnSpec):
        self.spec = spec
        self.handle: Any | None = None
        self.state = STOPPED
        self.deaths: deque[float] = deque()
        self.respawns = 0
        self.unhealthy_streak = 0
        self.next_spawn_at = 0.0
        self.last_exit: int | str | None = None
        #: ordered action log ("spawn"/"death"/"drain"/"terminate"/
        #: "kill"/"give_up") — the drain-before-kill ordering pin
        self.events: list[str] = []
        self._transport: BackendTransport | None = None

    def transport(self) -> BackendTransport | None:
        if self.spec.address is None:
            return None
        if self._transport is None:
            host, _, port = self.spec.address.rpartition(":")
            self._transport = BackendTransport(host or "127.0.0.1",
                                               int(port), pool_size=2)
        return self._transport

    def close_transport(self) -> None:
        if self._transport is not None:
            self._transport.close()
            self._transport = None

    def snapshot(self) -> dict:
        doc = {
            "id": self.spec.id,
            "role": self.spec.role,
            "state": self.state,
            "respawns": self.respawns,
            "deaths": len(self.deaths),
        }
        if self.spec.address:
            doc["address"] = self.spec.address
        if self.handle is not None:
            doc["pid"] = self.handle.pid
        if self.last_exit is not None:
            doc["lastExit"] = self.last_exit
        return doc


class FleetSupervisor:
    """The supervision loop over a set of :class:`SpawnSpec` children
    (module docstring). ``on_respawn(spec)`` / ``on_give_up(spec)``
    hooks let the router layer log/alert without the supervisor knowing
    about it."""

    def __init__(self, specs=(), config: SupervisorConfig | None = None,
                 clock: Clock = SYSTEM_CLOCK,
                 rng=None,
                 on_respawn: Callable[[SpawnSpec], None] | None = None,
                 on_give_up: Callable[[SpawnSpec], None] | None = None):
        import random

        self.config = config or SupervisorConfig()
        self.clock = clock
        self._rng = rng or random.Random()
        self._policy = self.config.backoff_policy()
        self._lock = threading.Lock()
        self._children: dict[str, _Child] = {}
        #: removed/shut-down children keep their event logs around for
        #: the drain-ordering tests and post-mortem snapshots
        self._retired: dict[str, _Child] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.on_respawn = on_respawn
        self.on_give_up = on_give_up
        for spec in specs:
            self.add(spec, start=False)

    # -- membership of the supervised set ------------------------------------
    def add(self, spec: SpawnSpec, start: bool = True) -> None:
        """Adopt (and optionally immediately spawn) a new child."""
        child = _Child(spec)
        with self._lock:
            if spec.id in self._children:
                raise ValueError(f"duplicate supervised child {spec.id!r}")
            self._children[spec.id] = child
        if start:
            self._spawn(child)

    def remove(self, spec_id: str, drain: bool = True,
               reason: str | None = None) -> bool:
        """Stop owning ``spec_id``: drain (replicas), SIGTERM with a
        grace window, SIGKILL stragglers. Returns False for an unknown
        id. The caller is expected to have detached the replica from
        routing FIRST (membership removal) — the drain here covers
        routers this process does not own. ``reason`` stamps the
        child's event log (e.g. ``remove:preempted_by_<engine>`` from
        the CapacityArbiter) so a retirement is attributable."""
        with self._lock:
            child = self._children.pop(spec_id, None)
            if child is not None and reason:
                # only attributed removals stamp the log — unattributed
                # ones keep the pinned ["spawn", "drain", ...] shape
                child.events.append(f"remove:{reason}")
        if child is None:
            return False
        self._drain_and_stop(child, drain=drain)
        with self._lock:
            self._retired[spec_id] = child
        return True

    def children(self) -> list[dict]:
        with self._lock:
            return [c.snapshot() for c in self._children.values()]

    def child_pid(self, spec_id: str) -> int | None:
        with self._lock:
            child = self._children.get(spec_id)
        if child is None or child.handle is None:
            return None
        return child.handle.pid

    def child_events(self, spec_id: str) -> list[str]:
        with self._lock:
            child = (self._children.get(spec_id)
                     or self._retired.get(spec_id))
            return list(child.events) if child is not None else []

    def crash_looped(self) -> bool:
        with self._lock:
            return any(c.state == CRASH_LOOPED
                       for c in self._children.values())

    # -- spawning + death bookkeeping ----------------------------------------
    def _spawn(self, child: _Child) -> None:
        try:
            handle = child.spec.spawn()
        except Exception:
            logger.exception("spawn of %s failed", child.spec.id)
            self._record_death(child, "spawn-failed")
            return
        with self._lock:
            child.handle = handle
            child.state = RUNNING
            child.unhealthy_streak = 0
            child.events.append("spawn")
        logger.info("supervised child %s up (pid %d)", child.spec.id,
                    handle.pid)

    def _record_death(self, child: _Child, exit_code) -> None:
        now = self.clock.monotonic()
        cfg = self.config
        with self._lock:
            child.events.append("death")
            child.last_exit = exit_code
            child.handle = None
            child.deaths.append(now)
            # only deaths inside the crash-loop window count toward the
            # latch AND toward the backoff index — a child that ran
            # stably for longer than the window restarts from the base
            # delay, not from wherever its history left off
            while child.deaths and now - child.deaths[0] > cfg.crash_loop_window_s:
                child.deaths.popleft()
            if len(child.deaths) >= max(2, cfg.crash_loop_threshold):
                child.state = CRASH_LOOPED
                child.events.append("give_up")
                spec = child.spec
            else:
                retry_index = len(child.deaths) - 1
                delay = self._policy.backoff(retry_index, self._rng)
                child.next_spawn_at = now + delay
                child.state = BACKOFF
                logger.warning(
                    "supervised child %s died (exit %s); respawn in "
                    "%.2fs (death %d in window)", child.spec.id,
                    exit_code, delay, len(child.deaths))
                return
        logger.error(
            "supervised child %s is crash-looping (%d deaths in %.0fs) "
            "— giving up; pio_fleet_crash_loop=1 until an operator "
            "fixes the spec and restarts (docs/fleet.md crash-loop "
            "triage)", spec.id, cfg.crash_loop_threshold,
            cfg.crash_loop_window_s)
        if self.on_give_up is not None:
            self.on_give_up(spec)

    def _respawn_due(self, child: _Child) -> None:
        self._spawn(child)
        if child.state == RUNNING:
            with self._lock:
                child.respawns += 1
            if self.on_respawn is not None:
                self.on_respawn(child.spec)

    # -- the supervision pass -------------------------------------------------
    def poll_once(self) -> None:
        """One supervision pass — the loop body and the deterministic
        test hook. Children are checked concurrently: a black-holed
        /healthz eats its own probe timeout, not the pass."""
        with self._lock:
            children = list(self._children.values())
        fan_out(children, self._check_child)

    def _check_child(self, child: _Child) -> None:
        with self._lock:
            state = child.state
            handle = child.handle
        if state == RUNNING and handle is not None:
            code = handle.poll()
            if code is not None:
                self._record_death(child, code)
                return
            self._health_check(child)
        elif state == BACKOFF \
                and self.clock.monotonic() >= child.next_spawn_at:
            self._respawn_due(child)

    def _health_check(self, child: _Child) -> None:
        cfg = self.config
        transport = child.transport()
        if transport is None or cfg.unhealthy_after <= 0:
            return
        try:
            response = transport.request("GET", "/healthz",
                                         timeout=cfg.probe_timeout_s)
            ok = response.status == 200
        except Exception:  # noqa: BLE001 — a probe failure is a data point
            ok = False
        with self._lock:
            if ok:
                child.unhealthy_streak = 0
                return
            child.unhealthy_streak += 1
            wedged = child.unhealthy_streak >= cfg.unhealthy_after
            handle = child.handle
        if not wedged or handle is None:
            return
        # a live pid that stopped answering /healthz for a sustained
        # streak is wedged (deadlocked, out of memory, spinning):
        # recycle it through the normal death path so backoff and the
        # crash-loop latch apply
        logger.warning(
            "supervised child %s (pid %d) is alive but failed %d "
            "consecutive health probes — recycling", child.spec.id,
            handle.pid, child.unhealthy_streak)
        handle.kill()
        self._await(handle, cfg.term_grace_s)
        self._record_death(child, "unhealthy")

    # -- drain + stop ---------------------------------------------------------
    @staticmethod
    def _await(handle, timeout: float) -> None:
        try:
            handle.wait(timeout=timeout)
        except Exception:  # subprocess.TimeoutExpired — caller re-checks
            pass

    def _drain(self, child: _Child) -> None:
        """Flip the replica's readiness off and wait, bounded, for the
        fleet to stop sending it work: ``POST /drain`` makes its
        ``/readyz`` answer 503 (api/engine_server.py), a bounded poll
        confirms the flip, and a settle period lets routers' membership
        loops notice and in-flight requests finish."""
        cfg = self.config
        transport = child.transport()
        if transport is None:
            return
        with self._lock:
            child.events.append("drain")
        drain_path = "/drain"
        if cfg.drain_key:
            from urllib.parse import quote

            drain_path += f"?accessKey={quote(cfg.drain_key)}"
        try:
            response = transport.request("POST", drain_path,
                                         timeout=cfg.probe_timeout_s)
            if response.status != 200:
                # the replica REFUSED the drain (key-authed server and
                # we hold no key, or no such route): the latch is not
                # set, so polling /readyz would burn the full drain
                # timeout for nothing — fall straight back to SIGTERM
                raise RuntimeError(f"HTTP {response.status}")
        except Exception as exc:  # noqa: BLE001 — degrade to the grace window
            logger.warning("drain request to %s failed (%s); falling "
                           "back to the SIGTERM grace window",
                           child.spec.id, exc)
            return
        deadline = self.clock.monotonic() + cfg.drain_timeout_s
        while self.clock.monotonic() < deadline:
            try:
                response = transport.request(
                    "GET", "/readyz", timeout=cfg.probe_timeout_s)
                if response.status != 200:
                    break               # drain acknowledged: not ready
            except Exception:  # noqa: BLE001 — the child may already be gone
                break
            self.clock.sleep(cfg.drain_poll_s)
        self.clock.sleep(cfg.drain_settle_s)

    def _drain_and_stop(self, child: _Child, drain: bool) -> None:
        handle = child.handle
        with self._lock:
            child.state = STOPPED
        if handle is not None and handle.poll() is None:
            if drain and child.spec.role == REPLICA:
                self._drain(child)
            with self._lock:
                child.events.append("terminate")
            handle.terminate()
            self._await(handle, self.config.term_grace_s)
            if handle.poll() is None:
                with self._lock:
                    child.events.append("kill")
                handle.kill()
                self._await(handle, self.config.term_grace_s)
        child.close_transport()

    def shutdown(self) -> None:
        """Graceful FULL-FLEET drain: stop the loop, then drain and
        stop every child (replicas concurrently — the shutdown pays the
        slowest drain, not the sum). This is what a parent SIGTERM
        routes into, so stopping `pio router --supervise` from the
        shell stops the whole supervised fleet, not one worker."""
        self.stop()
        with self._lock:
            children = list(self._children.values())
            self._children.clear()
            self._retired.update(
                (c.spec.id, c) for c in children)
        fan_out(children, lambda c: self._drain_and_stop(c, drain=True))

    # -- lifecycle ------------------------------------------------------------
    def start(self, loop: bool = True) -> None:
        """Spawn every not-yet-running child and start the loop.
        ``loop=False`` spawns only — tests drive :meth:`poll_once`
        themselves so the whole schedule rides the injected clock."""
        with self._lock:
            pending = [c for c in self._children.values()
                       if c.state == STOPPED and c.handle is None]
        for child in pending:
            self._spawn(child)
        if not loop or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="pio-fleet-supervisor", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            # Event.wait is the interval sleep AND the prompt stop
            # signal (the membership-loop idiom; never time.sleep here)
            self._stop.wait(self.config.poll_interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def snapshot(self) -> dict:
        docs = self.children()
        return {
            "children": docs,
            "crashLooped": any(d["state"] == CRASH_LOOPED for d in docs),
            "respawns": sum(d["respawns"] for d in docs),
        }


def supervisor_collector(supervisor: FleetSupervisor):
    """Registry adapter (obs/registry.py): the crash-loop alarm gauge,
    per-child liveness, and respawn counters."""

    def collect() -> list[Metric]:
        docs = supervisor.children()
        crash = Metric(
            name="pio_fleet_crash_loop", kind="gauge",
            help="1 while any supervised child is latched in crash-loop "
                 "give-up (docs/fleet.md crash-loop triage)",
            samples=[({}, 1.0 if any(d["state"] == CRASH_LOOPED
                                     for d in docs) else 0.0)])
        up = Metric(
            name="pio_fleet_child_up", kind="gauge",
            help="Supervised child state: 1 running, 0 anything else")
        respawns = Metric(
            name="pio_fleet_respawns_total", kind="counter",
            help="Times the supervisor restarted this child")
        for doc in docs:
            labels = {"child": doc["id"], "role": doc["role"]}
            up.samples.append(
                (labels, 1.0 if doc["state"] == RUNNING else 0.0))
            respawns.samples.append((labels, float(doc["respawns"])))
        return [crash, up, respawns]

    return collect
