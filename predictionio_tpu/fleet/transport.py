"""Lean upstream HTTP client for the router's forward path.

``http.client`` costs milliseconds of CPU per request (header assembly
plus the email-parser response machinery — the same measurement that
drove bench_serving.py's raw-socket load generator), and the router
sits on EVERY query, so its upstream hop uses the same discipline as
the engine server's response path: pre-built single-write requests over
pooled keep-alive sockets, and a minimal Content-Length response
parser. The engine server always sends ``Content-Length``
(api/engine_server._respond), which is what makes the minimal parser
sufficient.

Resilience contract: the ONLY raw network call lives in
:meth:`BackendTransport._connect` (the lint-declared guarded site);
every routed request goes through the owning backend's
:class:`~predictionio_tpu.utils.resilience.Resilience` policy at the
router layer (``resilient(backend.resilience, ...)``), so breaker
accounting and failure classification are never bypassed. A stale
pooled socket (the peer idled us out between requests) gets ONE
in-transport refresh with a fresh connection — only when ZERO response
bytes arrived (a reused socket the peer had already closed); once any
response byte has been read the backend executed the request, so the
failure is surfaced instead of replayed (a replay would run the query
twice). The refresh keeps keep-alive reuse from burning the router's
cross-replica retry.

Every socket operation is bounded: ``timeout`` is mandatory on
:meth:`BackendTransport.request` and is a TOTAL budget for the
exchange — the remaining budget is re-armed before every read, so a
replica trickling bytes cannot hold a router thread past the deadline.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import socket
import threading
import time
from typing import Callable, Iterable, Mapping, TypeVar

from predictionio_tpu.utils.resilience import TransientError  # noqa: F401  (re-export for callers)

logger = logging.getLogger(__name__)

_T = TypeVar("_T")
_R = TypeVar("_R")


def fan_out(items: Iterable[_T],
            fn: Callable[[_T], _R]) -> list[_R | None]:
    """Run ``fn`` over ``items`` CONCURRENTLY (one thread per item, the
    probe-pass idiom from fleet/membership.py) and return results in
    item order. Scrape-time fan-outs must pay the SLOWEST target's
    timeout, not the sum — sequentially, three black-holed replicas
    turn a "bounded" 2s-per-target scrape into 6s of wall clock and
    blow the Prometheus scrape deadline. ``fn`` is expected to handle
    its own per-target failures (degrade, don't raise); an escaped
    exception is logged and yields ``None`` in that slot."""
    items = list(items)

    def run(item: _T) -> _R | None:
        try:
            return fn(item)
        except Exception:  # noqa: BLE001 — one target must not kill the fan-out
            logger.exception("fan-out target failed")
            return None

    if len(items) <= 1:
        return [run(item) for item in items]
    results: list[_R | None] = [None] * len(items)

    def runner(idx: int, item: _T) -> None:
        results[idx] = run(item)

    threads = [
        threading.Thread(target=runner, args=(i, item), daemon=True,
                         name=f"pio-fan-out-{i}")
        for i, item in enumerate(items)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results

#: response headers the router forwards / acts on; everything else an
#: upstream sends is dropped at the parse (the router is not a general
#: reverse proxy — it fronts engine servers it knows)
_MAX_HEADER_BYTES = 64 * 1024


class UpstreamProtocolError(TransientError):
    """The upstream's response could not be parsed (closed mid-message,
    no Content-Length, oversized headers) — transient: the replica is
    misbehaving and the breaker should know."""


@dataclasses.dataclass
class UpstreamResponse:
    """One parsed upstream response: status, body bytes, and the
    (lower-cased) header map."""

    status: int
    body: bytes
    headers: dict[str, str]

    def header(self, name: str, default: str | None = None) -> str | None:
        return self.headers.get(name.lower(), default)


def build_request(method: str, path: str, host: str,
                  headers: Mapping[str, str] | None = None,
                  body: bytes | None = None) -> bytes:
    """One request as a single bytes blob (one ``sendall`` syscall)."""
    lines = [f"{method} {path} HTTP/1.1", f"Host: {host}"]
    for k, v in (headers or {}).items():
        lines.append(f"{k}: {v}")
    body = body or b""
    if body or method == "POST":
        lines.append(f"Content-Length: {len(body)}")
    head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
    return head + body


def _recv_within(sock: socket.socket, deadline: float) -> bytes:
    """One ``recv`` bounded by the exchange's remaining TOTAL budget.

    ``settimeout`` is per-operation: without re-arming it from the
    deadline each read, a replica trickling one byte per almost-timeout
    holds the handler thread (and its admission slot) indefinitely."""
    remaining = deadline - time.monotonic()
    if remaining <= 0:
        raise socket.timeout("upstream exchange exceeded its deadline")
    sock.settimeout(remaining)
    return sock.recv(65536)


def _parse_response(sock: socket.socket, buf: bytearray,
                    deadline: float) -> UpstreamResponse:
    """Read one response off ``sock`` into/out of ``buf`` (which may
    hold bytes from a previous read and keeps any trailing pipelined
    bytes — there are none in practice: one request in flight per
    pooled socket). On failure ``buf`` keeps everything read so far, so
    the caller can tell whether ANY response bytes arrived."""
    while True:
        head_end = buf.find(b"\r\n\r\n")
        if head_end >= 0:
            break
        if len(buf) > _MAX_HEADER_BYTES:
            raise UpstreamProtocolError("oversized response headers")
        chunk = _recv_within(sock, deadline)
        if not chunk:
            raise UpstreamProtocolError("upstream closed mid-headers")
        buf += chunk
    head = bytes(buf[:head_end]).decode("latin-1")
    lines = head.split("\r\n")
    parts = lines[0].split(" ", 2)
    if len(parts) < 2 or not parts[1].isdigit():
        raise UpstreamProtocolError(f"bad status line {lines[0]!r}")
    status = int(parts[1])
    headers: dict[str, str] = {}
    for line in lines[1:]:
        name, sep, value = line.partition(":")
        if sep:
            headers[name.strip().lower()] = value.strip()
    length_raw = headers.get("content-length")
    if length_raw is None or not length_raw.isdigit():
        # the engine server always sends Content-Length; anything else
        # cannot be framed on a keep-alive socket
        raise UpstreamProtocolError("upstream response has no Content-Length")
    need = head_end + 4 + int(length_raw)
    while len(buf) < need:
        chunk = _recv_within(sock, deadline)
        if not chunk:
            raise UpstreamProtocolError("upstream closed mid-body")
        buf += chunk
    body = bytes(buf[head_end + 4:need])
    del buf[:need]
    return UpstreamResponse(status=status, body=body, headers=headers)


class BackendTransport:
    """Pooled keep-alive HTTP/1.1 client for ONE backend address."""

    def __init__(self, host: str, port: int, pool_size: int = 32):
        self.host = host
        self.port = port
        self._addr = f"{host}:{port}"
        #: idle keep-alive sockets; SimpleQueue-style FIFO bounded by
        #: ``pool_size`` — beyond it sockets are closed, not pooled
        self._pool: "queue.Queue[socket.socket]" = queue.Queue(
            maxsize=max(1, pool_size))

    # -- pool ---------------------------------------------------------------
    def _connect(self, timeout: float) -> socket.socket:
        # THE guarded raw-network site (lint: resilience-bypass) —
        # reachable only from request(), whose callers route through
        # resilient(backend.resilience, ...) at the router layer
        sock = socket.create_connection((self.host, self.port), timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _checkout(self) -> socket.socket | None:
        try:
            return self._pool.get_nowait()
        except queue.Empty:
            return None

    def _checkin(self, sock: socket.socket) -> None:
        try:
            self._pool.put_nowait(sock)
        except queue.Full:
            sock.close()

    def close(self) -> None:
        while True:
            sock = self._checkout()
            if sock is None:
                return
            sock.close()

    # -- requests -----------------------------------------------------------
    def request(self, method: str, path: str,
                headers: Mapping[str, str] | None = None,
                body: bytes | None = None, *,
                timeout: float) -> UpstreamResponse:
        """One request/response exchange, bounded by ``timeout`` across
        connect + send + reads. Raises ``OSError`` subclasses /
        :class:`UpstreamProtocolError` on transport failure — both
        transient to the resilience layer. HTTP status codes (any of
        them) are returned, not raised: classification is the router's
        job."""
        raw = build_request(method, path, self._addr, headers, body)
        deadline = time.monotonic() + timeout
        sock = self._checkout()
        reused = sock is not None
        if sock is None:
            sock = self._connect(timeout)
        try:
            sock.settimeout(max(0.001, deadline - time.monotonic()))
            first_buf = bytearray()
            try:
                sock.sendall(raw)
                response = _parse_response(sock, first_buf, deadline)
            except (UpstreamProtocolError, OSError):
                sock.close()
                if not reused or first_buf:
                    # fresh socket, or response bytes already arrived:
                    # the backend executed the request, so replaying
                    # would run the query twice — surface the failure
                    # and let the router retry on a DIFFERENT replica
                    raise
                # a reused socket the peer already closed (keep-alive
                # idle timeout): zero response bytes means the request
                # was never processed — one fresh-connection refresh,
                # still inside the deadline
                sock = self._connect(max(0.001, deadline - time.monotonic()))
                sock.settimeout(max(0.001, deadline - time.monotonic()))
                sock.sendall(raw)
                response = _parse_response(sock, bytearray(), deadline)
        except BaseException:
            sock.close()
            raise
        if response.headers.get("connection", "").lower() == "close":
            sock.close()
        else:
            self._checkin(sock)
        return response
