"""Worker peering for ``--workers N`` SO_REUSEPORT processes: make a
scrape that lands on ONE worker report ALL workers (docs/fleet.md).

With SO_REUSEPORT the kernel spreads connections across N identical
processes, so ``GET /metrics`` samples a random worker's private
registry — a 1/N lie. The hub gives every worker:

- a **loopback peer endpoint** (127.0.0.1, ephemeral port) serving the
  worker's OWN exposition at ``/metrics`` and its trace ring at
  ``/traces.json`` — never bound beyond loopback: peers are same-host
  by construction (SO_REUSEPORT), and the public surface stays the
  shared port;
- a **spool directory** (one ``<pid>.json`` per live worker, written
  atomically) through which workers discover each other without a
  coordinator — the CLI creates it and passes the path through
  RouterConfig;
- **fan-out fetch** with a mandatory per-peer timeout (the lint
  untimed-blocking-io contract: a wedged worker must cost the scrape
  its timeout, not hang it), via the same lean transport the router
  uses for replicas. A peer whose process is gone (``os.kill(pid, 0)``
  raises ``ProcessLookupError``) has its spool entry reaped, so dead
  workers age out of the fleet view instead of eating a timeout on
  every scrape forever.

The scraped worker merges peers' parsed families with its own through
``obs/aggregate.merge_sources`` (counters summed, histograms merged
bucket-wise, gauges labeled ``worker="<pid>"``).

**Shared admin state** rides the same spool: canary weight mutations
and guardrail abort verdicts are published as a monotonically-sequenced
``admin.state`` document (atomic ``os.replace``, exactly like the
worker entries) that every sibling's sync loop applies — so a
``POST /fleet/canary`` landing on ONE ``SO_REUSEPORT`` worker reaches
ALL of them, and a respawned worker re-applies the latest document at
startup instead of booting with the launch-time weight. Concurrent
publishers race last-writer-wins on the ``os.replace``; admin
mutations are rare, human-speed events and the sequence number makes
the winner unambiguous to every reader. (Named ``admin.state``, not
``*.json``, so the peer-discovery listing never confuses it for a
worker entry.)
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from predictionio_tpu.fleet.transport import BackendTransport, fan_out

logger = logging.getLogger(__name__)

#: worker ids are pid + a per-process sequence: production workers are
#: one hub per process (the pid alone would do), but e2e tests run
#: several router "workers" in ONE process and each must register its
#: own spool entry instead of overwriting its sibling's
_HUB_SEQ = itertools.count(1)

#: per-peer fetch bound — scrapes degrade, they never hang
DEFAULT_PEER_TIMEOUT_S = 2.0

#: the shared admin-state document inside the spool (module docstring)
ADMIN_STATE_FILE = "admin.state"


class _PeerHandler(BaseHTTPRequestHandler):
    """Loopback-only peer surface: this worker's raw exposition and
    trace ring, for sibling workers' scrape-time fan-out."""

    hub: "WorkerHub"  # bound per server
    protocol_version = "HTTP/1.1"
    timeout = 10

    def do_GET(self) -> None:  # noqa: N802
        if self.path == "/metrics":
            body = self.hub._metrics_text().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif self.path == "/traces.json":
            body = json.dumps(
                {"traces": self.hub._traces_snapshot()}).encode()
            ctype = "application/json; charset=UTF-8"
        elif self.path in self.hub._extra_paths:
            # extra LOCAL documents a server registers for sibling
            # fan-out (the engine server's per-worker /stats.json);
            # the callback must return this worker's OWN view — a
            # callback that itself fans out to peers would recurse
            # A -> B -> A across the pool
            body = json.dumps(self.hub._extra_paths[self.path]()).encode()
            ctype = "application/json; charset=UTF-8"
        else:
            body, ctype = b'{"message": "not found"}', "application/json"
            self.send_response(404)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, format: str, *args) -> None:
        logger.debug("worker-peer %s - %s", self.address_string(),
                     format % args)


class WorkerHub:
    """One worker's membership in the spool + its peer endpoint."""

    def __init__(self, spool_dir: str,
                 metrics_text: Callable[[], str],
                 traces_snapshot: Callable[[], list],
                 timeout_s: float = DEFAULT_PEER_TIMEOUT_S,
                 extra_paths: dict[str, Callable[[], object]] | None = None):
        self.spool_dir = spool_dir
        self.worker_id = f"{os.getpid()}-{next(_HUB_SEQ)}"
        self.timeout_s = timeout_s
        self._metrics_text = metrics_text
        self._traces_snapshot = traces_snapshot
        #: additional loopback-only JSON documents (path -> callable
        #: returning this worker's LOCAL view; see _PeerHandler)
        self._extra_paths = dict(extra_paths or {})
        os.makedirs(spool_dir, exist_ok=True)
        handler = type("BoundPeerHandler", (_PeerHandler,), {"hub": self})
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        self.peer_port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="pio-worker-peer", daemon=True)
        self._thread.start()
        self._spool_path = os.path.join(spool_dir, f"{self.worker_id}.json")
        tmp = self._spool_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"worker": self.worker_id, "pid": os.getpid(),
                       "port": self.peer_port}, f)
        os.replace(tmp, self._spool_path)   # atomic: peers never see a torn file

    # -- discovery -----------------------------------------------------------
    def peers(self) -> list[dict]:
        """Live sibling workers ``{"pid", "port"}`` (self excluded);
        reaps spool entries whose process is gone."""
        out: list[dict] = []
        try:
            entries = os.listdir(self.spool_dir)
        except OSError:
            return out
        for entry in entries:
            if not entry.endswith(".json") \
                    or entry == f"{self.worker_id}.json":
                continue
            path = os.path.join(self.spool_dir, entry)
            try:
                with open(path) as f:
                    doc = json.load(f)
                worker = str(doc["worker"])
                pid = int(doc["pid"])
                port = int(doc["port"])
            except (OSError, ValueError, KeyError, json.JSONDecodeError):
                continue    # torn write in progress or junk: skip, not reap
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                self._reap(path, pid)
                continue
            except PermissionError:
                pass        # alive, different uid — keep it
            out.append({"worker": worker, "pid": pid, "port": port})
        return out

    def _reap(self, path: str, pid: int) -> None:
        try:
            os.unlink(path)
            logger.info("reaped dead worker %d from the spool", pid)
        except OSError:
            pass

    # -- fan-out -------------------------------------------------------------
    def fetch_peer_bodies(self, path: str) -> list[tuple[str, bytes]]:
        """``(worker_id, body)`` per live peer that answered ``path``
        within the timeout; failures are skipped (and logged), never
        raised — a wedged sibling degrades the merge, not the scrape.
        Peers are fetched concurrently (fleet/transport.fan_out): the
        scrape pays the slowest peer's timeout, not the sum."""

        def fetch(peer: dict) -> tuple[str, bytes] | None:
            transport = BackendTransport("127.0.0.1", peer["port"],
                                         pool_size=1)
            try:
                response = transport.request(
                    "GET", path, timeout=self.timeout_s)
                if response.status == 200:
                    return (peer["worker"], response.body)
                logger.warning(
                    "worker peer %d answered HTTP %d for %s",
                    peer["pid"], response.status, path)
            except Exception as exc:  # noqa: BLE001 — degrade, don't fail the scrape
                logger.warning("worker peer %d unreachable: %s",
                               peer["pid"], exc)
            finally:
                transport.close()
            return None

        return [body for body in fan_out(self.peers(), fetch)
                if body is not None]

    # -- shared admin state (module docstring) --------------------------------
    def read_admin(self) -> dict | None:
        """The latest admin document, or None (never published / torn
        write in progress — the next sync pass reads the committed
        one)."""
        path = os.path.join(self.spool_dir, ADMIN_STATE_FILE)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            return None
        if not isinstance(doc, dict) or not isinstance(
                doc.get("seq"), int):
            return None
        return doc

    def publish_admin(self, doc: dict) -> int:
        """Publish one admin mutation for every sibling to apply:
        assigns ``seq`` = latest + 1, stamps the publishing worker, and
        commits with an atomic ``os.replace`` (peers never see a torn
        document). Returns the assigned sequence number."""
        current = self.read_admin()
        seq = (current["seq"] if current else 0) + 1
        payload = {**doc, "seq": seq, "publishedBy": self.worker_id}
        path = os.path.join(self.spool_dir, ADMIN_STATE_FILE)
        tmp = f"{path}.{self.worker_id}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        logger.info("published admin state seq=%d: %s", seq,
                    doc.get("action"))
        return seq

    def close(self) -> None:
        try:
            os.unlink(self._spool_path)
        except OSError:
            pass
        try:
            # the admin document only matters while siblings remain;
            # removing it here would race a survivor's sync loop, so it
            # rides along until the spool dir itself goes (rmdir below
            # succeeds only for the LAST worker out, which first clears
            # the admin file)
            if not any(e.endswith(".json")
                       for e in os.listdir(self.spool_dir)):
                os.unlink(os.path.join(self.spool_dir, ADMIN_STATE_FILE))
        except OSError:
            pass
        try:
            # last worker out removes the spool the CLI mkdtemp'd;
            # rmdir (not rmtree) so a still-registered sibling keeps it
            os.rmdir(self.spool_dir)
        except OSError:
            pass
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
