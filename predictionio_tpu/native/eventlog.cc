// Native event-log codec/scanner for the `binevents` storage backend.
//
// This is the TPU build's native runtime data-loader: the training
// workflow's hot path is a full event scan (reference: Engine.scala:644
// readTrainingBase -> PEvents.find -> HBase TableInputFormat full table
// scan, SURVEY.md §3.1 "[HOT: full event scan]"). Where the reference
// delegates that scan to the JVM/HBase region servers, this library does
// the file IO, record framing, CRC verification, tombstone compaction and
// fixed-field filtering in C++; Python only JSON-parses the surviving
// payloads.
//
// File format (little-endian):
//   header: 8 bytes magic "PIOEVT1\n"
//   record: u32 body_len, u32 crc32(body), body
//     body: u8 op (0=put, 1=del)
//       del: u16 id_len, id bytes
//       put: i64 event_time (microseconds since epoch, UTC)
//            u16 id_len,  id
//            u16 name_len, event name
//            u16 etype_len, entity type
//            u16 eid_len,  entity id
//            u16 tet_len,  target entity type  (0xFFFF = absent)
//            u16 tei_len,  target entity id    (0xFFFF = absent)
//            u32 json_len, full canonical event JSON
//   A torn/corrupt tail record terminates the scan (normal append-crash
//   semantics); everything before it is served.
//
// C ABI (ctypes-consumed; see predictionio_tpu/native/__init__.py):
//   pio_open/pio_close/pio_write_put/pio_write_del/pio_flush
//   pio_scan (filtered, compacted scan -> [u32 n][u32 len,json]*)
//   pio_get  (single id lookup)
//   pio_free

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include <unistd.h>

namespace {

constexpr char kMagic[8] = {'P', 'I', 'O', 'E', 'V', 'T', '1', '\n'};
constexpr uint16_t kAbsent = 0xFFFF;

uint32_t crc_table[256];
bool crc_init_done = false;

void crc_init() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_init_done = true;
}

uint32_t crc32(const uint8_t* buf, size_t len) {
  crc_init();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++) c = crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

void put_u16(std::string& out, uint16_t v) { out.append((const char*)&v, 2); }
void put_u32(std::string& out, uint32_t v) { out.append((const char*)&v, 4); }
void put_i64(std::string& out, int64_t v) { out.append((const char*)&v, 8); }

void put_str16(std::string& out, const char* s) {
  if (s == nullptr) {
    put_u16(out, kAbsent);
    return;
  }
  size_t n = strlen(s);
  if (n >= kAbsent) n = kAbsent - 1;  // fixed fields are ids/names, never this long
  put_u16(out, (uint16_t)n);
  out.append(s, n);
}

struct Writer {
  FILE* f;
};

// One live (post-compaction) event's filterable view + payload.
struct LiveEvent {
  int64_t t_us;
  std::string name, etype, eid;
  bool has_tet, has_tei;
  std::string tet, tei;
  std::string json;
};

struct Cursor {
  const uint8_t* p;
  size_t n;
  bool ok = true;

  bool need(size_t k) {
    if (n < k) { ok = false; return false; }
    return true;
  }
  uint16_t u16() {
    if (!need(2)) return 0;
    uint16_t v; memcpy(&v, p, 2); p += 2; n -= 2; return v;
  }
  uint32_t u32() {
    if (!need(4)) return 0;
    uint32_t v; memcpy(&v, p, 4); p += 4; n -= 4; return v;
  }
  int64_t i64() {
    if (!need(8)) return 0;
    int64_t v; memcpy(&v, p, 8); p += 8; n -= 8; return v;
  }
  std::string bytes(size_t k) {
    if (!need(k)) return std::string();
    std::string s((const char*)p, k); p += k; n -= k; return s;
  }
};

// Replay the log into id -> LiveEvent (last put wins, del removes).
// Returns false only on open failure; a corrupt/torn tail just stops
// the replay.
bool replay(const char* path,
            std::unordered_map<std::string, LiveEvent>& live) {
  FILE* f = fopen(path, "rb");
  if (f == nullptr) return false;
  char magic[8];
  if (fread(magic, 1, 8, f) != 8 || memcmp(magic, kMagic, 8) != 0) {
    fclose(f);
    return true;  // empty/new file: nothing to replay
  }
  std::vector<uint8_t> body;
  for (;;) {
    uint32_t hdr[2];
    if (fread(hdr, 1, 8, f) != 8) break;
    uint32_t body_len = hdr[0], crc = hdr[1];
    if (body_len > (1u << 30)) break;  // implausible: corrupt length
    body.resize(body_len);
    if (fread(body.data(), 1, body_len, f) != body_len) break;  // torn tail
    if (crc32(body.data(), body_len) != crc) break;             // corrupt
    Cursor c{body.data(), body_len};
    uint8_t op = 0;
    if (!c.need(1)) continue;
    op = *c.p; c.p++; c.n--;
    if (op == 1) {  // del
      uint16_t idl = c.u16();
      std::string id = c.bytes(idl);
      if (c.ok) live.erase(id);
      continue;
    }
    LiveEvent ev;
    ev.t_us = c.i64();
    std::string id = c.bytes(c.u16());
    ev.name = c.bytes(c.u16());
    ev.etype = c.bytes(c.u16());
    ev.eid = c.bytes(c.u16());
    uint16_t tetl = c.u16();
    ev.has_tet = (tetl != kAbsent);
    if (ev.has_tet) ev.tet = c.bytes(tetl);
    uint16_t teil = c.u16();
    ev.has_tei = (teil != kAbsent);
    if (ev.has_tei) ev.tei = c.bytes(teil);
    ev.json = c.bytes(c.u32());
    if (c.ok) live[id] = std::move(ev);
  }
  fclose(f);
  return true;
}

// Byte length of the valid record prefix (header + intact records), or
// -1 if the file is non-empty with a foreign/corrupt header.
int64_t valid_prefix(FILE* f) {
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  if (size == 0) return 0;
  fseek(f, 0, SEEK_SET);
  char magic[8];
  if (size < 8 || fread(magic, 1, 8, f) != 8 || memcmp(magic, kMagic, 8) != 0)
    return -1;
  int64_t good = 8;
  std::vector<uint8_t> body;
  for (;;) {
    uint32_t hdr[2];
    if (fread(hdr, 1, 8, f) != 8) break;
    uint32_t body_len = hdr[0], crc = hdr[1];
    if (body_len > (1u << 30)) break;
    body.resize(body_len);
    if (fread(body.data(), 1, body_len, f) != body_len) break;
    if (crc32(body.data(), body_len) != crc) break;
    good += 8 + (int64_t)body_len;
  }
  return good;
}

}  // namespace

extern "C" {

// Opens for append, first truncating any torn/corrupt tail so records
// written after a crash are not appended behind an unreadable record
// (replay stops at the first bad record — without the repair those
// writes would be acknowledged but permanently invisible).
void* pio_open(const char* path) {
  FILE* f = fopen(path, "r+b");
  if (f == nullptr) {
    f = fopen(path, "wb");
    if (f == nullptr) return nullptr;
    if (fwrite(kMagic, 1, 8, f) != 8) { fclose(f); return nullptr; }
    fflush(f);
    return new Writer{f};
  }
  int64_t good = valid_prefix(f);
  if (good < 0) { fclose(f); return nullptr; }  // not an event log
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  if (good == 0) {  // empty file: write the header
    fseek(f, 0, SEEK_SET);
    if (fwrite(kMagic, 1, 8, f) != 8) { fclose(f); return nullptr; }
    fflush(f);
    good = 8;
  }
  if (size > good) {
    fflush(f);
    if (ftruncate(fileno(f), good) != 0) { fclose(f); return nullptr; }
  }
  fseek(f, (long)good, SEEK_SET);
  return new Writer{f};
}

int pio_close(void* h) {
  if (h == nullptr) return -1;
  Writer* w = (Writer*)h;
  int rc = fclose(w->f);
  delete w;
  return rc == 0 ? 0 : -1;
}

int pio_flush(void* h) {
  if (h == nullptr) return -1;
  return fflush(((Writer*)h)->f) == 0 ? 0 : -1;
}

static int write_record(Writer* w, const std::string& body) {
  uint32_t len = (uint32_t)body.size();
  uint32_t crc = crc32((const uint8_t*)body.data(), body.size());
  if (fwrite(&len, 1, 4, w->f) != 4) return -1;
  if (fwrite(&crc, 1, 4, w->f) != 4) return -1;
  if (fwrite(body.data(), 1, body.size(), w->f) != body.size()) return -1;
  return fflush(w->f) == 0 ? 0 : -1;
}

int pio_write_put(void* h, int64_t t_us, const char* id, const char* name,
                  const char* etype, const char* eid, const char* tet,
                  const char* tei, const uint8_t* json, uint32_t json_len) {
  if (h == nullptr || id == nullptr || name == nullptr) return -1;
  std::string body;
  body.reserve(64 + json_len);
  body.push_back((char)0);
  put_i64(body, t_us);
  put_str16(body, id);
  put_str16(body, name);
  put_str16(body, etype ? etype : "");
  put_str16(body, eid ? eid : "");
  put_str16(body, tet);  // NULL -> absent sentinel
  put_str16(body, tei);
  put_u32(body, json_len);
  body.append((const char*)json, json_len);
  return write_record((Writer*)h, body);
}

int pio_write_del(void* h, const char* id) {
  if (h == nullptr || id == nullptr) return -1;
  std::string body;
  body.push_back((char)1);
  put_str16(body, id);
  return write_record((Writer*)h, body);
}

// Filtered, compacted scan. Mode for target fields: 0 = any,
// 1 = must be absent, 2 = must equal the given value (matching
// EventFilter.matches, storage/base.py). Output: [u32 n][u32 len,json]*
// in unspecified order (the Python side sorts by event time).
int pio_scan(const char* path, int has_start, int64_t start_us, int has_until,
             int64_t until_us, const char* entity_type, const char* entity_id,
             const char* const* names, int32_t n_names, int tet_mode,
             const char* tet, int tei_mode, const char* tei, uint8_t** out,
             uint64_t* out_len) {
  if (out == nullptr || out_len == nullptr) return -1;
  std::unordered_map<std::string, LiveEvent> live;
  if (!replay(path, live)) return -2;

  std::string buf;
  uint32_t count = 0;
  put_u32(buf, 0);  // placeholder
  for (const auto& kv : live) {
    const LiveEvent& e = kv.second;
    if (has_start && e.t_us < start_us) continue;
    if (has_until && e.t_us >= until_us) continue;
    if (entity_type != nullptr && e.etype != entity_type) continue;
    if (entity_id != nullptr && e.eid != entity_id) continue;
    if (names != nullptr && n_names > 0) {
      bool hit = false;
      for (int32_t i = 0; i < n_names && !hit; i++)
        hit = (names[i] != nullptr && e.name == names[i]);
      if (!hit) continue;
    }
    if (tet_mode == 1 && e.has_tet) continue;
    if (tet_mode == 2 && (!e.has_tet || e.tet != (tet ? tet : ""))) continue;
    if (tei_mode == 1 && e.has_tei) continue;
    if (tei_mode == 2 && (!e.has_tei || e.tei != (tei ? tei : ""))) continue;
    put_u32(buf, (uint32_t)e.json.size());
    buf.append(e.json);
    count++;
  }
  memcpy(&buf[0], &count, 4);
  uint8_t* mem = (uint8_t*)malloc(buf.size());
  if (mem == nullptr) return -3;
  memcpy(mem, buf.data(), buf.size());
  *out = mem;
  *out_len = buf.size();
  return 0;
}

// Single-id lookup: returns 0 and the JSON payload if live, 1 if absent.
int pio_get(const char* path, const char* id, uint8_t** out,
            uint64_t* out_len) {
  if (id == nullptr || out == nullptr || out_len == nullptr) return -1;
  std::unordered_map<std::string, LiveEvent> live;
  if (!replay(path, live)) return -2;
  auto it = live.find(id);
  if (it == live.end()) return 1;
  const std::string& json = it->second.json;
  uint8_t* mem = (uint8_t*)malloc(json.size() ? json.size() : 1);
  if (mem == nullptr) return -3;
  memcpy(mem, json.data(), json.size());
  *out = mem;
  *out_len = json.size();
  return 0;
}

void pio_free(uint8_t* p) { free(p); }

}  // extern "C"
