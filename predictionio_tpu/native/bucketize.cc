// Native ratings bucketizer: COO triples -> padded per-row slabs.
//
// The host-side data-prep hot path for the ALS engine (ops/als.py
// bucket_rows): groups ratings by row, caps heavy rows keeping their
// top-valued entries, and packs each power-of-`growth` degree class
// into dense (n, pad_len) slabs. The Python/NumPy implementation loops
// per unique row (~|users| Python iterations at MovieLens-20M scale);
// this does one counting sort + one packing pass in C, O(nnz).
//
// Handle-based C API (ctypes, see native/__init__.py load_bucketize):
//   h  = pio_bucketize(nnz, rows, cols, vals, num_rows, min_len, growth,
//                      max_len)
//   nb = pio_bucketize_num_buckets(h)
//   pio_bucketize_bucket_info(h, b, &pad_len, &n)
//   pio_bucketize_fill(h, b, row_ids_out, cols_out, vals_out, deg_out)
//   pio_bucketize_free(h)
// Output buffers are caller(NumPy)-allocated; fill packs entries to the
// row prefix (cols/vals zero-padded past deg), matching the Python
// layout contract in ops/als.Bucket.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <memory>
#include <vector>

namespace {

struct RowRef {
    int64_t start;   // offset into the row-sorted order
    int32_t row_id;
    int32_t count;   // raw degree
    int32_t kept;    // capped degree
};

struct BucketPlan {
    int32_t pad_len;
    std::vector<int64_t> row_refs;  // indices into rows_
};

struct Bucketizer {
    std::vector<int64_t> order;     // nnz entries sorted by row (stable)
    std::vector<RowRef> rows_;
    std::vector<BucketPlan> buckets;
    const int32_t* cols;
    const float* vals;
};

int32_t pad_len_for(int32_t kept, int32_t min_len, int32_t growth) {
    int64_t len = min_len;
    while (len < kept) len *= growth;
    return static_cast<int32_t>(len);
}

// Shared grouping pipeline behind pio_bucketize and pio_ladder: row
// validation, counting sort, RowRef construction (max_len == 0 means
// no cap), and stable grouping by the caller's pad rule. Returns a
// heap Bucketizer, or nullptr on invalid input; exception-safe via
// unique_ptr (an allocation throw must not leak across the ctypes
// boundary).
template <typename PadFn>
Bucketizer* build_grouped(int64_t nnz, const int32_t* rows,
                          const int32_t* cols, const float* vals,
                          int32_t num_rows, int32_t max_len, PadFn pad_fn) {
    auto bz = std::make_unique<Bucketizer>();
    bz->cols = cols;
    bz->vals = vals;
    // row ids must be dense indices in [0, num_rows): out-of-range ids
    // (corrupted input / int32 overflow upstream) would be
    // out-of-bounds writes below — reject and let the caller fall back
    // to the NumPy path
    for (int64_t i = 0; i < nnz; ++i) {
        if (rows[i] < 0 || rows[i] >= num_rows) return nullptr;
    }
    const int64_t n_rows = num_rows;
    std::vector<int64_t> counts(n_rows + 1, 0);
    for (int64_t i = 0; i < nnz; ++i) ++counts[rows[i] + 1];
    std::vector<int64_t> offsets(counts);
    for (int64_t r = 0; r < n_rows; ++r) offsets[r + 1] += offsets[r];
    bz->order.resize(nnz);
    {
        std::vector<int64_t> cursor(offsets.begin(), offsets.end() - 1);
        for (int64_t i = 0; i < nnz; ++i) bz->order[cursor[rows[i]]++] = i;
    }
    for (int64_t r = 0; r < n_rows; ++r) {
        const int64_t c = offsets[r + 1] - offsets[r];
        if (c == 0) continue;
        RowRef ref;
        ref.start = offsets[r];
        ref.row_id = static_cast<int32_t>(r);
        ref.count = static_cast<int32_t>(c);
        ref.kept = (max_len > 0 && c > max_len) ? max_len
                                                : static_cast<int32_t>(c);
        bz->rows_.push_back(ref);
    }
    // group rows by pad length (ascending, like np.unique in Python)
    std::vector<std::pair<int32_t, int64_t>> keyed;
    keyed.reserve(bz->rows_.size());
    for (int64_t i = 0; i < static_cast<int64_t>(bz->rows_.size()); ++i) {
        keyed.emplace_back(pad_fn(bz->rows_[i].kept), i);
    }
    std::stable_sort(keyed.begin(), keyed.end(),
                     [](const auto& a, const auto& b) {
                         return a.first < b.first;
                     });
    for (const auto& [pl, idx] : keyed) {
        if (bz->buckets.empty() || bz->buckets.back().pad_len != pl) {
            bz->buckets.push_back(BucketPlan{pl, {}});
        }
        bz->buckets.back().row_refs.push_back(idx);
    }
    return bz.release();
}

}  // namespace

extern "C" {

void* pio_bucketize(int64_t nnz, const int32_t* rows, const int32_t* cols,
                    const float* vals, int32_t num_rows, int32_t min_len,
                    int32_t growth, int32_t max_len) try {
    if (nnz < 0 || num_rows < 0 || min_len <= 0 || growth < 2) return nullptr;
    return build_grouped(nnz, rows, cols, vals, num_rows, max_len,
                         [min_len, growth](int32_t kept) {
                             return pad_len_for(kept, min_len, growth);
                         });
} catch (...) {
    // no C++ exception may cross the ctypes boundary (std::terminate)
    return nullptr;
}

int32_t pio_bucketize_num_buckets(void* handle) {
    if (!handle) return -1;
    return static_cast<int32_t>(
        static_cast<Bucketizer*>(handle)->buckets.size());
}

int pio_bucketize_bucket_info(void* handle, int32_t b, int32_t* pad_len,
                              int64_t* n) {
    if (!handle) return -1;
    auto* bz = static_cast<Bucketizer*>(handle);
    if (b < 0 || b >= static_cast<int32_t>(bz->buckets.size())) return -1;
    *pad_len = bz->buckets[b].pad_len;
    *n = static_cast<int64_t>(bz->buckets[b].row_refs.size());
    return 0;
}

int pio_bucketize_fill(void* handle, int32_t b, int32_t* row_ids_out,
                       int32_t* cols_out, float* vals_out, int32_t* deg_out)
try {
    if (!handle) return -1;
    auto* bz = static_cast<Bucketizer*>(handle);
    if (b < 0 || b >= static_cast<int32_t>(bz->buckets.size())) return -1;
    const BucketPlan& plan = bz->buckets[b];
    const int32_t pl = plan.pad_len;

    std::vector<int64_t> scratch;  // value-sorted entry indices (capped rows)
    for (int64_t j = 0; j < static_cast<int64_t>(plan.row_refs.size()); ++j) {
        const RowRef& ref = bz->rows_[plan.row_refs[j]];
        row_ids_out[j] = ref.row_id;
        deg_out[j] = ref.kept;
        int32_t* crow = cols_out + j * pl;
        float* vrow = vals_out + j * pl;
        std::memset(crow, 0, sizeof(int32_t) * pl);
        std::memset(vrow, 0, sizeof(float) * pl);
        if (ref.kept < ref.count) {
            // capped heavy row: keep the top-valued entries
            scratch.resize(ref.count);
            for (int32_t t = 0; t < ref.count; ++t) {
                scratch[t] = bz->order[ref.start + t];
            }
            std::partial_sort(
                scratch.begin(), scratch.begin() + ref.kept, scratch.end(),
                [bz](int64_t a, int64_t c) {
                    return bz->vals[a] > bz->vals[c];
                });
            for (int32_t t = 0; t < ref.kept; ++t) {
                crow[t] = bz->cols[scratch[t]];
                vrow[t] = bz->vals[scratch[t]];
            }
        } else {
            for (int32_t t = 0; t < ref.kept; ++t) {
                const int64_t e = bz->order[ref.start + t];
                crow[t] = bz->cols[e];
                vrow[t] = bz->vals[e];
            }
        }
    }
    return 0;
} catch (...) {
    return -1;
}

void pio_bucketize_free(void* handle) {
    delete static_cast<Bucketizer*>(handle);
}

// Ladder variant (ops/als.ladder_rows): same handle/info/fill/free
// contract as pio_bucketize — the only difference is the pad rule:
// rows with degree <= small_len pad to small_len; otherwise to
// width * c with c the smallest ladder count covering ceil(deg/width),
// the ladder extending by doubling past its last entry (arbitrary
// degrees supported, no capping ever).
void* pio_ladder(int64_t nnz, const int32_t* rows, const int32_t* cols,
                 const float* vals, int32_t num_rows, int32_t width,
                 int32_t small_len, const int64_t* ladder,
                 int32_t n_ladder) try {
    if (nnz < 0 || num_rows < 0 || width <= 0 || small_len <= 0 ||
        n_ladder <= 0) {
        return nullptr;
    }
    auto ladder_pad = [width, small_len, ladder,
                       n_ladder](int32_t kept) -> int32_t {
        if (kept <= small_len) return small_len;
        const int64_t need = (static_cast<int64_t>(kept) + width - 1) / width;
        int64_t c = ladder[n_ladder - 1];
        for (int32_t j = 0; j < n_ladder; ++j) {
            if (ladder[j] >= need) { c = ladder[j]; break; }
        }
        while (c < need) c *= 2;                   // extend by doubling
        return static_cast<int32_t>(c * width);
    };
    // max_len = 0: the ladder never caps
    return build_grouped(nnz, rows, cols, vals, num_rows, 0, ladder_pad);
} catch (...) {
    return nullptr;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Chunker: greedy fixed-size decomposition (ops/als.chunk_rows contract)
// ---------------------------------------------------------------------------
//
// Every row decomposes greedily into full chunks of the largest size,
// cascading down; the final remainder pads to the smallest size. Chunks
// of one row are consecutive and carry the row's entries in their
// row-sorted order — identical layout to the NumPy implementation.
//
//   h = pio_chunk(nnz, rows, cols, vals, num_rows, sizes, n_sizes)
//       (sizes strictly descending, all > 0)
//   n = pio_chunk_num_slabs(h)            // one slab set per size with chunks
//   pio_chunk_slab_info(h, s, &L, &n_chunks)
//   pio_chunk_fill(h, s, row_ids_out, cols_out, vals_out, deg_out)
//   pio_chunk_free(h)

namespace {

struct ChunkRef {
    int64_t start;   // offset into the row-sorted entry order
    int32_t row_id;
    int32_t count;   // real entries in this chunk (<= L)
};

struct SlabPlan {
    int32_t len;
    std::vector<ChunkRef> chunks;
};

struct Chunker {
    std::vector<int64_t> order;
    std::vector<SlabPlan> slabs;
    const int32_t* cols;
    const float* vals;
};

}  // namespace

extern "C" {

void* pio_chunk(int64_t nnz, const int32_t* rows, const int32_t* cols,
                const float* vals, int32_t num_rows, const int32_t* sizes,
                int32_t n_sizes) try {
    if (nnz < 0 || num_rows < 0 || n_sizes <= 0) return nullptr;
    for (int32_t i = 0; i < n_sizes; ++i) {
        if (sizes[i] <= 0) return nullptr;
        if (i > 0 && sizes[i] >= sizes[i - 1]) return nullptr;  // descending
    }
    for (int64_t i = 0; i < nnz; ++i) {
        if (rows[i] < 0 || rows[i] >= num_rows) return nullptr;
    }
    auto* ck = new Chunker();
    ck->cols = cols;
    ck->vals = vals;

    // counting sort by row id (stable)
    const int64_t n_rows = num_rows;
    std::vector<int64_t> counts(n_rows + 1, 0);
    for (int64_t i = 0; i < nnz; ++i) ++counts[rows[i] + 1];
    std::vector<int64_t> offsets(counts);
    for (int64_t r = 0; r < n_rows; ++r) offsets[r + 1] += offsets[r];
    ck->order.resize(nnz);
    {
        std::vector<int64_t> cursor(offsets.begin(), offsets.end() - 1);
        for (int64_t i = 0; i < nnz; ++i) ck->order[cursor[rows[i]]++] = i;
    }

    // greedy cascade: per size class, full chunks (remainder pads into
    // the smallest class)
    std::vector<int64_t> consumed(n_rows, 0);
    ck->slabs.reserve(n_sizes);
    for (int32_t s = 0; s < n_sizes; ++s) {
        const int64_t L = sizes[s];
        SlabPlan plan;
        plan.len = sizes[s];
        for (int64_t r = 0; r < n_rows; ++r) {
            const int64_t deg = offsets[r + 1] - offsets[r];
            const int64_t remaining = deg - consumed[r];
            if (remaining <= 0) continue;
            int64_t covered;
            if (s < n_sizes - 1) {
                covered = (remaining / L) * L;   // full chunks only
            } else {
                covered = remaining;             // remainder pads to last size
            }
            for (int64_t off = 0; off < covered; off += L) {
                ChunkRef ref;
                ref.start = offsets[r] + consumed[r] + off;
                ref.row_id = static_cast<int32_t>(r);
                ref.count = static_cast<int32_t>(std::min(L, covered - off));
                plan.chunks.push_back(ref);
            }
            consumed[r] += covered;
        }
        if (!plan.chunks.empty()) ck->slabs.push_back(std::move(plan));
    }
    return ck;
} catch (...) {
    return nullptr;
}

int32_t pio_chunk_num_slabs(void* handle) {
    if (!handle) return -1;
    return static_cast<int32_t>(static_cast<Chunker*>(handle)->slabs.size());
}

int pio_chunk_slab_info(void* handle, int32_t s, int32_t* len,
                        int64_t* n_chunks) {
    if (!handle) return -1;
    auto* ck = static_cast<Chunker*>(handle);
    if (s < 0 || s >= static_cast<int32_t>(ck->slabs.size())) return -1;
    *len = ck->slabs[s].len;
    *n_chunks = static_cast<int64_t>(ck->slabs[s].chunks.size());
    return 0;
}

int pio_chunk_fill(void* handle, int32_t s, int32_t* row_ids_out,
                   int32_t* cols_out, float* vals_out, int32_t* deg_out) try {
    if (!handle) return -1;
    auto* ck = static_cast<Chunker*>(handle);
    if (s < 0 || s >= static_cast<int32_t>(ck->slabs.size())) return -1;
    const SlabPlan& plan = ck->slabs[s];
    const int32_t L = plan.len;
    for (int64_t j = 0; j < static_cast<int64_t>(plan.chunks.size()); ++j) {
        const ChunkRef& ref = plan.chunks[j];
        row_ids_out[j] = ref.row_id;
        deg_out[j] = ref.count;
        int32_t* crow = cols_out + j * L;
        float* vrow = vals_out + j * L;
        if (ref.count < L) {
            std::memset(crow + ref.count, 0, sizeof(int32_t) * (L - ref.count));
            std::memset(vrow + ref.count, 0, sizeof(float) * (L - ref.count));
        }
        for (int32_t t = 0; t < ref.count; ++t) {
            const int64_t e = ck->order[ref.start + t];
            crow[t] = ck->cols[e];
            vrow[t] = ck->vals[e];
        }
    }
    return 0;
} catch (...) {
    return -1;
}

void pio_chunk_free(void* handle) {
    delete static_cast<Chunker*>(handle);
}

}  // extern "C"
