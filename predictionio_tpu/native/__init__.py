"""Native runtime components (C++, ctypes-bound).

``load_eventlog()`` returns the compiled event-log library (see
eventlog.cc) or None when a toolchain isn't available — callers fall
back to the pure-Python codec in storage/binevents.py, which implements
the identical byte format.

The library is built on demand with g++ (baked into the image) and
cached next to the source; a rebuild happens only when the source is
newer than the .so.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "eventlog.cc")
_SO = os.path.join(_DIR, "_eventlog.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False

_bucketize_lock = threading.Lock()
_bucketize_lib: ctypes.CDLL | None = None
_bucketize_failed = False


def _build(src: str, so: str) -> str | None:
    try:
        if os.path.exists(so) and (
            not os.path.exists(src)  # prebuilt .so shipped without source
            or os.path.getmtime(so) >= os.path.getmtime(src)
        ):
            return so
    except OSError:
        pass
    # compile to a per-pid temp path, then atomically rename into place:
    # two processes racing on first use must never dlopen a partially
    # written .so (rename is atomic within the directory)
    tmp = f"{so}.tmp.{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp, src],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, so)
        return so
    except (OSError, subprocess.SubprocessError):
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass
        return None


def _ensure_built() -> str | None:
    return _build(_SRC, _SO)


def load_eventlog() -> ctypes.CDLL | None:
    """Compile (if needed) and load the native event log; None on failure."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        so = _ensure_built()
        if so is None:
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            _load_failed = True
            return None
        c_char_pp = ctypes.POINTER(ctypes.c_char_p)
        u8_pp = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))
        u64_p = ctypes.POINTER(ctypes.c_uint64)
        lib.pio_open.argtypes = [ctypes.c_char_p]
        lib.pio_open.restype = ctypes.c_void_p
        lib.pio_close.argtypes = [ctypes.c_void_p]
        lib.pio_close.restype = ctypes.c_int
        lib.pio_flush.argtypes = [ctypes.c_void_p]
        lib.pio_flush.restype = ctypes.c_int
        lib.pio_write_put.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.pio_write_put.restype = ctypes.c_int
        lib.pio_write_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.pio_write_del.restype = ctypes.c_int
        lib.pio_scan.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int,
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_char_p, c_char_pp,
            ctypes.c_int32, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, u8_pp, u64_p,
        ]
        lib.pio_scan.restype = ctypes.c_int
        lib.pio_get.argtypes = [ctypes.c_char_p, ctypes.c_char_p, u8_pp, u64_p]
        lib.pio_get.restype = ctypes.c_int
        lib.pio_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.pio_free.restype = None
        _lib = lib
        return _lib


def load_bucketize() -> ctypes.CDLL | None:
    """Compile (if needed) and load the native ratings bucketizer
    (bucketize.cc); None on failure — ops/als.bucket_rows falls back to
    the NumPy implementation with identical slab layout."""
    global _bucketize_lib, _bucketize_failed
    with _bucketize_lock:
        if _bucketize_lib is not None or _bucketize_failed:
            return _bucketize_lib
        so = _build(os.path.join(_DIR, "bucketize.cc"),
                    os.path.join(_DIR, "_bucketize.so"))
        if so is None:
            _bucketize_failed = True
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            _bucketize_failed = True
            return None
        return _bind_bucketize(lib)


def _bind_bucketize(lib: ctypes.CDLL) -> ctypes.CDLL | None:
    global _bucketize_lib, _bucketize_failed
    try:
        _bind_bucketize_symbols(lib)
    except AttributeError:
        # a stale/prebuilt .so without the full symbol set (e.g. built
        # from an older bucketize.cc) must mean "no native path", not a
        # crash on every call — fall back to NumPy everywhere
        _bucketize_failed = True
        return None
    _bucketize_lib = lib
    return _bucketize_lib


def _bind_bucketize_symbols(lib: ctypes.CDLL) -> None:
    i32_p = ctypes.POINTER(ctypes.c_int32)
    i64_p = ctypes.POINTER(ctypes.c_int64)
    f32_p = ctypes.POINTER(ctypes.c_float)
    lib.pio_bucketize.argtypes = [
        ctypes.c_int64, i32_p, i32_p, f32_p, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.pio_bucketize.restype = ctypes.c_void_p
    lib.pio_bucketize_num_buckets.argtypes = [ctypes.c_void_p]
    lib.pio_bucketize_num_buckets.restype = ctypes.c_int32
    lib.pio_bucketize_bucket_info.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, i32_p, i64_p,
    ]
    lib.pio_bucketize_bucket_info.restype = ctypes.c_int
    lib.pio_bucketize_fill.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, i32_p, i32_p, f32_p, i32_p,
    ]
    lib.pio_bucketize_fill.restype = ctypes.c_int
    lib.pio_bucketize_free.argtypes = [ctypes.c_void_p]
    lib.pio_bucketize_free.restype = None
    # ladder entry point (ops/als.ladder_rows) — shares the bucketize
    # handle/info/fill/free contract
    lib.pio_ladder.argtypes = [
        ctypes.c_int64, i32_p, i32_p, f32_p, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, i64_p, ctypes.c_int32,
    ]
    lib.pio_ladder.restype = ctypes.c_void_p
    # chunker entry points (same library; ops/als.chunk_rows)
    lib.pio_chunk.argtypes = [
        ctypes.c_int64, i32_p, i32_p, f32_p, ctypes.c_int32, i32_p,
        ctypes.c_int32,
    ]
    lib.pio_chunk.restype = ctypes.c_void_p
    lib.pio_chunk_num_slabs.argtypes = [ctypes.c_void_p]
    lib.pio_chunk_num_slabs.restype = ctypes.c_int32
    lib.pio_chunk_slab_info.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, i32_p, i64_p,
    ]
    lib.pio_chunk_slab_info.restype = ctypes.c_int
    lib.pio_chunk_fill.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, i32_p, i32_p, f32_p, i32_p,
    ]
    lib.pio_chunk_fill.restype = ctypes.c_int
    lib.pio_chunk_free.argtypes = [ctypes.c_void_p]
    lib.pio_chunk_free.restype = None
