"""Native runtime components (C++, ctypes-bound).

``load_eventlog()`` returns the compiled event-log library (see
eventlog.cc) or None when a toolchain isn't available — callers fall
back to the pure-Python codec in storage/binevents.py, which implements
the identical byte format.

The library is built on demand with g++ (baked into the image) and
cached next to the source; a rebuild happens only when the source is
newer than the .so.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "eventlog.cc")
_SO = os.path.join(_DIR, "_eventlog.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def _ensure_built() -> str | None:
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", _SO, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return _SO
    except (OSError, subprocess.SubprocessError):
        return None


def load_eventlog() -> ctypes.CDLL | None:
    """Compile (if needed) and load the native event log; None on failure."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        so = _ensure_built()
        if so is None:
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            _load_failed = True
            return None
        c_char_pp = ctypes.POINTER(ctypes.c_char_p)
        u8_pp = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))
        u64_p = ctypes.POINTER(ctypes.c_uint64)
        lib.pio_open.argtypes = [ctypes.c_char_p]
        lib.pio_open.restype = ctypes.c_void_p
        lib.pio_close.argtypes = [ctypes.c_void_p]
        lib.pio_close.restype = ctypes.c_int
        lib.pio_flush.argtypes = [ctypes.c_void_p]
        lib.pio_flush.restype = ctypes.c_int
        lib.pio_write_put.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.pio_write_put.restype = ctypes.c_int
        lib.pio_write_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.pio_write_del.restype = ctypes.c_int
        lib.pio_scan.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int,
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_char_p, c_char_pp,
            ctypes.c_int32, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, u8_pp, u64_p,
        ]
        lib.pio_scan.restype = ctypes.c_int
        lib.pio_get.argtypes = [ctypes.c_char_p, ctypes.c_char_p, u8_pp, u64_p]
        lib.pio_get.restype = ctypes.c_int
        lib.pio_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.pio_free.restype = None
        _lib = lib
        return _lib
