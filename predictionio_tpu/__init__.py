"""predictionio_tpu — a TPU-native machine-learning server framework.

A ground-up re-design of the capabilities of Apache PredictionIO
(reference: /root/reference, Scala/Spark) for TPU hardware:

- Event Server: REST event collection into pluggable storage backends
  (reference: data/src/main/scala/.../data/api/EventServer.scala).
- DASE controller API: DataSource / Preparator / Algorithm(s) / Serving /
  Evaluation, typed engine components
  (reference: core/src/main/scala/.../controller/Engine.scala:83).
- Training workflow: runs an engine's train pipeline on a JAX device mesh
  (replacing Spark) and persists models
  (reference: core/.../workflow/CoreWorkflow.scala:45).
- Deployment server: loads trained models, answers prediction queries over
  REST with pre-jitted predict functions
  (reference: core/.../workflow/CreateServer.scala).
- Evaluation/tuning workflow: grid-searches engine params against metrics
  (reference: core/.../controller/MetricEvaluator.scala).
- CLI (`pio`) orchestrating all of the above
  (reference: tools/.../console/Console.scala).
- Pluggable storage backends behind three repositories
  (metadata / event data / model data)
  (reference: data/.../storage/Storage.scala).

Where the reference distributes work over Spark executors, this framework
distributes over a `jax.sharding.Mesh` of TPU devices: pjit/shard_map with
XLA collectives (psum, all_gather, all_to_all) replace shuffle/broadcast;
host-side Arrow/NumPy batch loading replaces RDD reads.
"""

__version__ = "0.1.0"

from predictionio_tpu.core.datamap import DataMap, PropertyMap
from predictionio_tpu.core.event import Event, EventValidation

__all__ = [
    "DataMap",
    "PropertyMap",
    "Event",
    "EventValidation",
    "__version__",
]
