"""Experimentation platform: parallel grid eval → online A/B → promotion.

Three legs, one closed loop (ROADMAP item 5; PredictionIO capability
(5) raised from a single-process grid to the thing the multi-tenant
fleet was built for):

- :mod:`predictionio_tpu.experiment.grid` — fan ``engine.batch_eval``
  grid points across short-lived eval worker processes with per-point
  fault isolation (one crashed point = one FAILED result, never a dead
  grid), streaming per-point results into the evaluation-instances
  store (``pio eval --parallel N`` / ``PIO_EVAL_PARALLEL``);
- :mod:`predictionio_tpu.experiment.controller` — the
  :class:`ExperimentController` state machine (define → ramp → measure
  → promote|abort) that splits live traffic across top-k grid points
  deployed as named engines behind the gateway, scores them online
  from routed outcomes + conversion attribution, and auto-promotes the
  winner / auto-aborts losers through the CanaryController guardrail
  discipline — all published over the admin spool so ``--workers``
  siblings and respawns agree;
- :mod:`predictionio_tpu.experiment.cli` — ``pio experiment``
  (define/status/conversions) against a running ``pio router``.

docs/experimentation.md is the operator guide.
"""

from predictionio_tpu.experiment.controller import (
    ExperimentConfig,
    ExperimentController,
    VariantSpec,
)
from predictionio_tpu.experiment.grid import (
    GridPointResult,
    eval_points_collector,
    run_parallel_grid,
)

__all__ = [
    "ExperimentConfig",
    "ExperimentController",
    "VariantSpec",
    "GridPointResult",
    "eval_points_collector",
    "run_parallel_grid",
]
