"""Parallel grid evaluation: per-point eval worker processes.

Grid points are embarrassingly parallel — each point trains and scores
its own fold set through ``engine.batch_eval`` with no shared state —
so ``pio eval --parallel N`` fans them over N short-lived child
processes riding the same :class:`~predictionio_tpu.fleet.supervisor.
ProcessHandle` discipline the PR 9 supervisor uses for worker
siblings. The contract the tests pin:

- **per-point fault isolation** — a crashed (or poisoned) grid point
  becomes ONE ``FAILED`` point result carrying the child's error; the
  rest of the grid completes and the best point is picked over the
  survivors. A grid is only lost when EVERY point fails.
- **deterministic order** — results are assembled by grid index, not
  completion order, so the evaluation-instance JSON is reproducible
  regardless of scheduling.
- **streaming** — the caller's ``on_point`` hook fires as each point
  lands, which is how workflow/evaluation.py makes the partial grid
  visible in the metadata store mid-run.

Children hand results back through single-use JSON spool files written
atomically (``os.replace``) under a per-run temp dir — the same
crash-safe file discipline as the worker admin spool; a child that
dies mid-write leaves a ``.tmp`` orphan, never a torn result.

The fan-out only applies when the evaluator is a
:class:`~predictionio_tpu.controller.evaluation.MetricEvaluator`
(children ship plain metric scores, not live ``EvalDataSet`` objects);
a custom evaluator falls back to the sequential path with a warning.
Note the sequential path is also what preserves
:class:`~predictionio_tpu.controller.fast_eval.FastEvalEngine` prefix
sharing ACROSS points — parallelism trades that sharing for cores, a
trade that only pays on a multi-core host (docs/experimentation.md).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import multiprocessing
import os
import tempfile
import threading
import time
from typing import Any, Callable, Sequence

from predictionio_tpu.controller.evaluation import (
    Evaluation,
    MetricEvaluator,
    MetricEvaluatorResult,
    MetricScores,
)
from predictionio_tpu.controller.params import EngineParams
from predictionio_tpu.fleet.supervisor import ProcessHandle
from predictionio_tpu.obs.registry import Metric

logger = logging.getLogger(__name__)

#: point statuses (mirrors the evaluation-instance status vocabulary)
COMPLETED, FAILED = "COMPLETED", "FAILED"

#: how long the parent waits on any single child exit before re-polling
#: the whole set (bounded join — the untimed-blocking-io contract)
_JOIN_SLICE_S = 0.05

_counts_lock = threading.Lock()
_point_counts: dict[str, int] = {COMPLETED: 0, FAILED: 0}


def _count_point(status: str) -> None:
    with _counts_lock:
        _point_counts[status] = _point_counts.get(status, 0) + 1


def eval_points_collector() -> list[Metric]:
    """``pio_eval_points_total{status}`` — grid points evaluated in
    this process, by outcome. Registered on the router /metrics so the
    family is part of the scrape contract; it counts wherever the grid
    actually runs (the ``pio eval`` process, or tests)."""
    with _counts_lock:
        samples = [({"status": s.lower()}, float(n))
                   for s, n in sorted(_point_counts.items())]
    return [Metric("pio_eval_points_total", "counter",
                   "Evaluation grid points finished, by status.",
                   samples=samples)]


@dataclasses.dataclass
class GridPointResult:
    """One grid point's outcome, in grid order."""

    idx: int
    status: str  # COMPLETED | FAILED
    score: Any = None
    other_scores: list[Any] = dataclasses.field(default_factory=list)
    error: str = ""
    duration_s: float = 0.0

    def to_doc(self) -> dict[str, Any]:
        doc: dict[str, Any] = {"idx": self.idx, "status": self.status,
                               "score": self.score,
                               "otherScores": self.other_scores,
                               "durationS": round(self.duration_s, 3)}
        if self.error:
            doc["error"] = self.error
        return doc


def _json_safe(value: Any) -> Any:
    """Scores cross the process boundary as JSON; anything exotic a
    custom metric returns degrades to ``str`` rather than killing the
    point on the way home."""
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        return str(value)


def _eval_point_child(evaluation: Evaluation, evaluator: MetricEvaluator,
                      ctx: Any, idx: int, engine_params: EngineParams,
                      out_path: str) -> None:
    """Child body: evaluate ONE grid point, spool the scores, exit.
    Raising propagates to a nonzero exitcode, which the parent folds
    into a FAILED point result — fault isolation is the parent's job,
    the child just dies honestly."""
    started = time.monotonic()
    pairs = evaluation.engine.batch_eval(ctx, [engine_params])
    if not pairs:
        raise RuntimeError(f"batch_eval returned no data for point {idx}")
    _, eval_data = pairs[0]
    doc = {
        "idx": idx,
        "score": _json_safe(evaluator.metric.calculate(eval_data)),
        "otherScores": [_json_safe(m.calculate(eval_data))
                        for m in evaluator.other_metrics],
        "durationS": time.monotonic() - started,
    }
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out_path)


def _collect_point(idx: int, exitcode: int | None, out_path: str,
                   started: float) -> GridPointResult:
    duration = time.monotonic() - started
    if exitcode == 0 and os.path.exists(out_path):
        try:
            with open(out_path) as f:
                doc = json.load(f)
            return GridPointResult(
                idx=idx, status=COMPLETED, score=doc.get("score"),
                other_scores=list(doc.get("otherScores") or []),
                duration_s=float(doc.get("durationS") or duration))
        except (OSError, ValueError) as exc:
            return GridPointResult(
                idx=idx, status=FAILED, duration_s=duration,
                error=f"unreadable point result: {exc}")
    return GridPointResult(
        idx=idx, status=FAILED, duration_s=duration,
        error=f"eval worker exited with code {exitcode}"
              + ("" if os.path.exists(out_path) else " (no result spooled)"))


def run_parallel_grid(
    evaluation: Evaluation,
    evaluator: MetricEvaluator,
    params_list: Sequence[EngineParams],
    ctx: Any,
    parallel: int,
    on_point: Callable[[GridPointResult, int, int], None] | None = None,
) -> list[GridPointResult]:
    """Fan the grid over ``parallel`` eval worker processes; returns
    per-point results in grid-index order (module docstring has the
    isolation/ordering/streaming contract). ``on_point(result, done,
    total)`` fires after each point lands, in COMPLETION order."""
    total = len(params_list)
    width = max(1, min(int(parallel), total))
    # fork shares the evaluation/engine/storage objects without
    # pickling — the same start method the router worker pool rides
    mp = multiprocessing.get_context("fork")
    results: dict[int, GridPointResult] = {}
    pending = list(enumerate(params_list))
    live: dict[int, tuple[ProcessHandle, str, float]] = {}
    done = 0

    with tempfile.TemporaryDirectory(prefix="pio-eval-grid-") as spool:
        def _spawn(idx: int, ep: EngineParams) -> None:
            out_path = os.path.join(spool, f"point_{idx}.json")
            handle = ProcessHandle(mp.Process(
                target=_eval_point_child,
                args=(evaluation, evaluator, ctx, idx, ep, out_path),
                name=f"pio-eval-point-{idx}", daemon=True))
            live[idx] = (handle, out_path, time.monotonic())

        try:
            while pending or live:
                while pending and len(live) < width:
                    idx, ep = pending.pop(0)
                    _spawn(idx, ep)
                # bounded join on the oldest child, then sweep ALL
                # exits — one slow point never serializes collection
                oldest = min(live, key=lambda i: live[i][2])
                live[oldest][0].wait(timeout=_JOIN_SLICE_S)
                for idx in [i for i, (h, _, _) in live.items()
                            if h.poll() is not None]:
                    handle, out_path, started = live.pop(idx)
                    result = _collect_point(
                        idx, handle.poll(), out_path, started)
                    results[idx] = result
                    done += 1
                    _count_point(result.status)
                    if result.status == FAILED:
                        logger.warning("grid point %d FAILED: %s",
                                       idx, result.error)
                    else:
                        logger.info("grid point %d/%d: score=%s",
                                    idx, total, result.score)
                    if on_point is not None:
                        on_point(result, done, total)
        finally:
            for handle, _, _ in live.values():
                handle.kill()
                handle.wait(timeout=5.0)

    return [results[i] for i in sorted(results)]


def result_from_points(
    evaluator: MetricEvaluator,
    params_list: Sequence[EngineParams],
    points: Sequence[GridPointResult],
    evaluation: Evaluation | None = None,
) -> MetricEvaluatorResult:
    """Reassemble a :class:`MetricEvaluatorResult` from per-point
    results: ``engine_params_scores`` covers EVERY grid point in order
    (failed points carry a ``None`` score so downstream indices line
    up with the grid), while best-tracking only compares survivors.
    Raises when every point failed — a grid with no surviving point
    has no result to persist, and the caller records FAILED."""
    scores: list[tuple[EngineParams, MetricScores]] = []
    best_idx = -1
    for point in points:
        ms = MetricScores(score=point.score,
                          other_scores=list(point.other_scores))
        scores.append((params_list[point.idx], ms))
        if point.status != COMPLETED:
            continue
        if best_idx < 0 or evaluator.metric.compare(
                ms.score, scores[best_idx][1].score) > 0:
            best_idx = point.idx
    if best_idx < 0:
        raise RuntimeError(
            "every grid point failed: "
            + "; ".join(f"[{p.idx}] {p.error}" for p in points))
    best_params, best_score = scores[best_idx]
    result = MetricEvaluatorResult(
        best_score=best_score,
        best_engine_params=best_params,
        best_idx=best_idx,
        metric_header=evaluator.metric.header,
        other_metric_headers=[m.header for m in evaluator.other_metrics],
        engine_params_scores=scores,
        output_path=evaluator.output_path,
    )
    if evaluator.output_path and evaluation is not None:
        evaluator._save_best_json(evaluation, best_params)
    return result


def partial_grid_doc(points: Sequence[GridPointResult],
                     total: int) -> str:
    """The mid-run evaluation-instance JSON: which points have landed
    (by grid index) and how many remain — readable while the grid is
    still running, which is the round-trip the persistence tests pin."""
    by_idx = sorted(points, key=lambda p: p.idx)
    return json.dumps({
        "gridTotal": total,
        "gridDone": len(by_idx),
        "points": [p.to_doc() for p in by_idx],
    }, indent=2)


def count_sequential_points(n_completed: int, failed: bool = False) -> None:
    """Fold the sequential path's outcome into the same
    ``pio_eval_points_total`` family the parallel path feeds."""
    for _ in range(max(0, n_completed)):
        _count_point(COMPLETED)
    if failed:
        _count_point(FAILED)
