"""``pio experiment`` — drive the online A/B loop from the shell.

Subcommands (docs/experimentation.md has the full runbook):

- ``pio experiment start <name> --instance <evalId> --top-k 2
  --backends host:port[,host:port] --backends ...`` — read the scored
  grid from the evaluation instance, pick the top-k surviving points,
  register each as a named engine behind a running ``pio router``
  (``POST /fleet/engines``, one ``--backends`` group per variant in
  rank order), and define the experiment over them
  (``POST /fleet/experiments``). From here the router owns the
  lifecycle: ramp → measure → promote|abort.
- ``pio experiment status`` — the live lifecycle + per-variant online
  evidence from ``GET /fleet/experiments``.
- ``pio experiment conversions <name> --appid N`` — sweep the event
  store for accepted events carrying this experiment's served
  attribution stamp (``experimentId``/``variantId`` properties,
  excluding the server's own ``predict`` feedback events) and POST
  the per-variant TOTALS to the router, closing the loop from serving
  back through the event store into the online score. Totals are
  cumulative, so re-running the sweep never double-counts.
"""

from __future__ import annotations

import json
import logging
import urllib.error
import urllib.request

logger = logging.getLogger(__name__)

_DEFAULT_ROUTER = "127.0.0.1:8100"


def _router_call(router: str, path: str, doc: dict | None,
                 router_key: str | None, timeout: float) -> dict:
    """One bounded JSON exchange with the router; raises SystemExit-free
    RuntimeError with the router's message on a non-2xx."""
    url = f"http://{router}{path}"
    if router_key:
        url += f"?accessKey={router_key}"
    data = json.dumps(doc).encode() if doc is not None else None
    req = urllib.request.Request(
        url, data=data, method="POST" if doc is not None else "GET",
        headers={"Content-Type": "application/json"} if doc else {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as exc:
        try:
            message = json.loads(exc.read()).get("message", str(exc))
        except Exception:  # noqa: BLE001
            message = str(exc)
        raise RuntimeError(f"router {path}: {message}") from exc
    except (urllib.error.URLError, OSError) as exc:
        raise RuntimeError(f"router {router} unreachable: {exc}") from exc


def _ranked_points(instance, top_k: int, ascending: bool) -> list[dict]:
    """The surviving grid points of an evaluation instance, best
    first. Rank order follows the score sign (``--ascending`` for
    lower-is-better metrics); FAILED / unscored points never deploy."""
    doc = json.loads(instance.evaluator_results_json or "{}")
    scored = [
        {"idx": i, "score": entry.get("score"),
         "engineParams": entry.get("engineParams")}
        for i, entry in enumerate(doc.get("engineParamsScores", []))
        if isinstance(entry.get("score"), (int, float))
    ]
    scored.sort(key=lambda e: e["score"], reverse=not ascending)
    return scored[:max(1, top_k)]


def _latest_completed(instances):
    for instance in instances.get_completed():
        return instance
    return None


def _cmd_start(args, storage) -> int:
    instances = storage.get_meta_data_evaluation_instances()
    if args.instance:
        instance = instances.get(args.instance)
    else:
        instance = _latest_completed(instances)
    if instance is None:
        print("[ERROR] no completed evaluation instance found "
              "(run `pio eval` first, or pass --instance)")
        return 1
    if instance.status != "EVALCOMPLETED":
        print(f"[ERROR] evaluation instance {instance.id} is "
              f"{instance.status}, not EVALCOMPLETED")
        return 1
    points = _ranked_points(instance, args.top_k, args.ascending)
    if not points:
        print(f"[ERROR] evaluation instance {instance.id} has no "
              "scored grid points")
        return 1
    backend_groups = [b.split(",") for b in (args.backends or [])]
    if len(backend_groups) != len(points):
        print(f"[ERROR] {len(points)} variant(s) need {len(points)} "
              f"--backends group(s), got {len(backend_groups)} "
              "(one comma-separated replica list per ranked variant)")
        return 1
    weight = 100.0 / len(points)
    variants = []
    for rank, (point, backends) in enumerate(zip(points, backend_groups)):
        engine_name = f"{args.name}-v{point['idx']}"
        try:
            _router_call(args.router, "/fleet/engines", {
                "action": "register",
                "engine": {"name": engine_name, "backends": backends},
            }, args.router_key, args.timeout)
        except RuntimeError as exc:
            if "already registered" not in str(exc):
                print(f"[ERROR] registering {engine_name}: {exc}")
                return 1
            print(f"[INFO] engine {engine_name} already registered")
        variants.append({"name": engine_name, "weightPct": weight,
                         "gridIdx": point["idx"],
                         "offlineScore": point["score"]})
        print(f"[INFO] variant #{rank} {engine_name}: grid point "
              f"{point['idx']} (offline score {point['score']}) on "
              f"{len(backends)} replica(s)")
    experiment = {"name": args.name, "rampS": args.ramp_s,
                  "measureS": args.measure_s,
                  "minRequests": args.min_requests,
                  "conversionWeight": args.conversion_weight,
                  "guardrail": {"minRequests": args.guardrail_min_requests,
                                "maxErrorRate": args.max_error_rate,
                                "maxP99Ms": args.max_p99_ms,
                                "window": args.guardrail_window}}
    try:
        doc = _router_call(args.router, "/fleet/experiments",
                           {"action": "define", "experiment": experiment,
                            "variants": variants},
                           args.router_key, args.timeout)
    except RuntimeError as exc:
        print(f"[ERROR] defining experiment: {exc}")
        return 1
    snap = doc.get("experiment") or {}
    print(f"[INFO] experiment {args.name} defined: state "
          f"{snap.get('state')} over "
          f"{len(snap.get('variants', []))} variant(s)")
    return 0


def _print_snapshot(snap: dict | None) -> None:
    if not snap:
        print("[INFO] no experiment defined")
        return
    decision = snap.get("decision") or {}
    verdict = (f" — winner {decision.get('winner')}"
               if decision.get("winner") else "")
    print(f"[INFO] experiment {snap.get('name')}: "
          f"{snap.get('state')}{verdict}")
    for v in snap.get("variants", []):
        flag = "ABORTED" if v.get("aborted") else \
            f"score {v.get('onlineScore')}"
        print(f"[INFO]   {v.get('name')} ({v.get('weightPct'):g}%): "
              f"{v.get('requests')} req, {v.get('errors')} err, "
              f"{v.get('conversions')} conv | {flag}")


def _cmd_status(args, storage) -> int:
    try:
        doc = _router_call(args.router, "/fleet/experiments", None,
                           args.router_key, args.timeout)
    except RuntimeError as exc:
        print(f"[ERROR] {exc}")
        return 1
    _print_snapshot(doc.get("experiment"))
    return 0


def sweep_conversions(storage, app_id: int, experiment: str,
                      channel_id: int | None = None) -> dict[str, int]:
    """Count accepted events carrying this experiment's attribution
    stamp, per variant — the event-store half of the conversion loop.
    The server-generated ``predict`` feedback events are excluded:
    serving a rec is not the user acting on it."""
    from predictionio_tpu.storage.base import EventFilter

    counts: dict[str, int] = {}
    for event in storage.get_events().find(app_id, channel_id,
                                           EventFilter()):
        if event.event == "predict":
            continue
        try:
            if event.properties.get("experimentId") != experiment:
                continue
            variant = event.properties.get("variantId")
        except Exception:  # noqa: BLE001 — properties are client data
            continue
        if variant:
            counts[str(variant)] = counts.get(str(variant), 0) + 1
    return counts


def _cmd_conversions(args, storage) -> int:
    counts = sweep_conversions(storage, args.appid, args.name)
    if not counts:
        print(f"[INFO] no attributed conversion events for experiment "
              f"{args.name} in app {args.appid}")
        return 0
    try:
        doc = _router_call(args.router, "/fleet/experiments",
                           {"action": "conversions",
                            "experiment": args.name,
                            "conversions": counts},
                           args.router_key, args.timeout)
    except RuntimeError as exc:
        print(f"[ERROR] {exc}")
        return 1
    total = sum(counts.values())
    print(f"[INFO] folded {total} conversion(s) across "
          f"{len(counts)} variant(s) into experiment {args.name}")
    _print_snapshot(doc.get("experiment"))
    return 0


def _add_router_args(p) -> None:
    p.add_argument("--router", default=_DEFAULT_ROUTER,
                   metavar="HOST:PORT")
    p.add_argument("--router-key", default=None, dest="router_key")
    p.add_argument("--timeout", type=float, default=10.0)


def _configure_experiment(sub) -> None:
    p = sub.add_parser(
        "experiment",
        help="online A/B over grid-eval winners: deploy top-k variants "
             "behind the router, split traffic, auto-promote")
    ops = p.add_subparsers(dest="experiment_cmd", required=True)

    start = ops.add_parser("start", help="deploy top-k grid points as "
                                         "variants and start the experiment")
    start.add_argument("name", help="experiment id (rides every "
                                    "attribution stamp)")
    start.add_argument("--instance", default=None,
                       help="evaluation instance id (default: the "
                            "latest EVALCOMPLETED one)")
    start.add_argument("--top-k", type=int, default=2, dest="top_k")
    start.add_argument("--backends", action="append", metavar="HOST:PORT[,..]",
                       help="replica list for the k-th ranked variant "
                            "(repeat once per variant, rank order)")
    start.add_argument("--ascending", action="store_true",
                       help="lower score is better (error-style metrics)")
    start.add_argument("--ramp-s", type=float, default=5.0, dest="ramp_s")
    start.add_argument("--measure-s", type=float, default=30.0,
                       dest="measure_s")
    start.add_argument("--min-requests", type=int, default=20,
                       dest="min_requests")
    start.add_argument("--conversion-weight", type=float, default=0.5,
                       dest="conversion_weight")
    start.add_argument("--max-error-rate", type=float, default=0.5,
                       dest="max_error_rate")
    start.add_argument("--max-p99-ms", type=float, default=0.0,
                       dest="max_p99_ms")
    start.add_argument("--guardrail-min-requests", type=int, default=20,
                       dest="guardrail_min_requests")
    start.add_argument("--guardrail-window", type=int, default=200,
                       dest="guardrail_window")
    _add_router_args(start)

    status = ops.add_parser("status", help="lifecycle + per-variant "
                                           "online evidence")
    _add_router_args(status)

    conv = ops.add_parser(
        "conversions",
        help="sweep attributed conversion events from the event store "
             "into the router's online score")
    conv.add_argument("name", help="experiment id to sweep")
    conv.add_argument("--appid", type=int, required=True)
    _add_router_args(conv)


def _cmd_experiment(args, storage) -> int:
    if args.experiment_cmd == "start":
        return _cmd_start(args, storage)
    if args.experiment_cmd == "status":
        return _cmd_status(args, storage)
    if args.experiment_cmd == "conversions":
        return _cmd_conversions(args, storage)
    print(f"[ERROR] unknown experiment subcommand {args.experiment_cmd!r}")
    return 1


def register() -> None:
    from predictionio_tpu.cli.pio import register_command

    register_command("experiment", _configure_experiment, _cmd_experiment)


register()
