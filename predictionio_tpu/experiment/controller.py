"""ExperimentController: online A/B over gateway engines, one verdict.

``pio experiment`` deploys top-k grid points as named engines behind
the multi-tenant gateway; this controller owns what happens next:

    define → ramp → measure → promote | abort

- **define** — variants (engine name + traffic weight + the grid
  point they came from) are registered; the experiment immediately
  starts splitting bare-path query traffic by weight.
- **ramp** — guardrails are live (a breaching variant auto-aborts,
  exactly the CanaryController discipline, one controller per
  variant) but no promotion verdict is taken: the first
  ``ramp_s`` seconds are warmup — caches fill, JITs compile — and
  must not decide an experiment.
- **measure** — after ``measure_s`` seconds AND ``min_requests``
  routed outcomes on every surviving variant, each survivor gets an
  online score: success rate folded with conversion rate
  (``conversion_weight``), conversions arriving through the
  attribution loop (docs/experimentation.md). Best score wins.
- **promote** — the winner becomes the gateway default engine and the
  losers are retired; **abort** — every variant breached, nothing is
  promoted, the default engine is untouched.

Coherence: like the canary plane, outcome WINDOWS stay local to each
worker — only verdicts (variant aborts, state transitions, the
decision) and conversion counts (which arrive over the admin endpoint,
not per-request) ride the seq'd cumulative ``experiment`` doc on the
worker admin spool. Whichever ``--workers`` sibling first satisfies
the decision thresholds decides; the others adopt, and a respawned
worker adopts the verdict from the spool before serving (the e2e test
pins that round-trip).

Time is injectable (:class:`~predictionio_tpu.utils.resilience.Clock`)
so the whole lifecycle runs under ``ManualClock`` in tests; the
controller never sleeps — ticks ride the router's admin sync loop,
which waits on an Event (the banned-sleep lint contract over
``experiment/``).
"""

from __future__ import annotations

import dataclasses
import logging
import random
import threading
from typing import Callable, Sequence

from predictionio_tpu.fleet.canary import CanaryController, GuardrailConfig
from predictionio_tpu.obs.registry import Metric
from predictionio_tpu.utils.envcfg import env_field
from predictionio_tpu.utils.resilience import SYSTEM_CLOCK, Clock

logger = logging.getLogger(__name__)

#: experiment lifecycle states
RAMP, MEASURE, PROMOTED, ABORTED = "RAMP", "MEASURE", "PROMOTED", "ABORTED"

#: attribution surface: response/request headers + body fields
EXPERIMENT_HEADER = "X-PIO-Experiment"
VARIANT_HEADER = "X-PIO-Variant"
EXPERIMENT_FIELD = "experimentId"
VARIANT_FIELD = "variantId"


def _env_field(key: str, default, cast):
    return env_field("PIO_EXPERIMENT_", key, default, cast)


@dataclasses.dataclass(frozen=True)
class VariantSpec:
    """One experiment arm: a named gateway engine plus where it came
    from (the grid point index and offline score, for the runbook)."""

    name: str
    weight_pct: float
    grid_idx: int = -1
    offline_score: float | None = None

    def to_doc(self) -> dict:
        return {"name": self.name, "weightPct": self.weight_pct,
                "gridIdx": self.grid_idx, "offlineScore": self.offline_score}

    @classmethod
    def from_doc(cls, doc: dict) -> "VariantSpec":
        return cls(name=str(doc["name"]),
                   weight_pct=float(doc.get("weightPct", 0.0)),
                   grid_idx=int(doc.get("gridIdx", -1)),
                   offline_score=doc.get("offlineScore"))


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    """Lifecycle knobs (``PIO_EXPERIMENT_*`` env-overridable defaults,
    the ServerConfig discipline)."""

    name: str
    #: warmup before the measure clock starts — guardrails live,
    #: verdicts not
    ramp_s: float = _env_field("RAMP_S", 5.0, float)
    #: minimum measure period before any promotion verdict
    measure_s: float = _env_field("MEASURE_S", 30.0, float)
    #: routed outcomes required on EVERY surviving variant
    min_requests: int = _env_field("MIN_REQUESTS", 20, int)
    #: how much of the online score is conversion rate (0..1);
    #: the rest is success rate
    conversion_weight: float = _env_field("CONVERSION_WEIGHT", 0.5, float)
    guardrail: GuardrailConfig = dataclasses.field(
        default_factory=GuardrailConfig)

    def to_doc(self) -> dict:
        g = self.guardrail
        return {"name": self.name, "rampS": self.ramp_s,
                "measureS": self.measure_s,
                "minRequests": self.min_requests,
                "conversionWeight": self.conversion_weight,
                "guardrail": {"minRequests": g.min_requests,
                              "maxErrorRate": g.max_error_rate,
                              "maxP99Ms": g.max_p99_ms,
                              "window": g.window}}

    @classmethod
    def from_doc(cls, doc: dict) -> "ExperimentConfig":
        g = doc.get("guardrail") or {}
        return cls(
            name=str(doc["name"]),
            ramp_s=float(doc.get("rampS", 5.0)),
            measure_s=float(doc.get("measureS", 30.0)),
            min_requests=int(doc.get("minRequests", 20)),
            conversion_weight=float(doc.get("conversionWeight", 0.5)),
            guardrail=GuardrailConfig(
                min_requests=int(g.get("minRequests", 20)),
                max_error_rate=float(g.get("maxErrorRate", 0.5)),
                max_p99_ms=float(g.get("maxP99Ms", 0.0)),
                window=int(g.get("window", 200))))


class _Variant:
    """Mutable per-arm state: the guardrail rides a CanaryController
    (window + breach + abort latch, all its tested semantics) with the
    variant's traffic weight standing in for the canary weight."""

    def __init__(self, spec: VariantSpec,
                 guardrail: GuardrailConfig,
                 rng: random.Random | None = None):
        self.spec = spec
        self.canary = CanaryController(weight_pct=max(0.1, spec.weight_pct),
                                       guardrail=guardrail, rng=rng)
        self.requests = 0
        self.errors = 0
        self.conversions = 0

    @property
    def aborted(self) -> bool:
        return self.canary.aborted

    def success_rate(self) -> float:
        if self.requests <= 0:
            return 0.0
        return (self.requests - self.errors) / self.requests

    def conversion_rate(self) -> float:
        if self.requests <= 0:
            return 0.0
        return min(1.0, self.conversions / self.requests)


class ExperimentController:
    """The lifecycle state machine (module docstring). All state under
    one lock; gateway actions and the change callback run OUTSIDE it
    (the gateway has its own lock, and ``on_change`` re-enters
    :meth:`state_doc`)."""

    def __init__(self, gateway=None, clock: Clock = SYSTEM_CLOCK,
                 rng: random.Random | None = None,
                 on_change: Callable[[], None] | None = None):
        self._gateway = gateway
        self._clock = clock
        self._rng = rng or random.Random()
        self.on_change = on_change
        self._lock = threading.Lock()
        self._seq = 0
        self._config: ExperimentConfig | None = None
        self._variants: dict[str, _Variant] = {}
        self._state = ""
        self._started_at = 0.0
        self._measure_started_at = 0.0
        self._decision: dict | None = None

    # -- lifecycle -----------------------------------------------------------
    def define(self, config: ExperimentConfig,
               variants: Sequence[VariantSpec]) -> None:
        """Start (or replace) THE experiment: traffic splits
        immediately, the ramp clock starts now."""
        if not variants:
            raise ValueError("an experiment needs at least one variant")
        names = [v.name for v in variants]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate variant names: {names}")
        with self._lock:
            self._config = config
            self._variants = {
                v.name: _Variant(v, config.guardrail, rng=self._rng)
                for v in variants}
            self._state = RAMP
            self._started_at = self._clock.monotonic()
            self._measure_started_at = 0.0
            self._decision = None
            self._seq += 1
        logger.info("experiment %s: RAMP with variants %s",
                    config.name, names)
        self._changed()

    def abort(self, reason: str = "operator abort") -> None:
        with self._lock:
            if self._config is None or self._state in (PROMOTED, ABORTED):
                return
            for variant in self._variants.values():
                if not variant.aborted:
                    variant.canary.abort(reason)
            self._state = ABORTED
            self._decision = {"winner": None, "reason": reason,
                              "at": self._clock.monotonic()}
            self._seq += 1
        self._changed()

    # -- routing -------------------------------------------------------------
    def assign(self) -> tuple[str, str] | None:
        """Pick a variant for one bare-path query: weighted among the
        surviving arms. Returns ``(experiment, variant)`` or None when
        no experiment is splitting traffic."""
        with self._lock:
            if self._config is None or self._state not in (RAMP, MEASURE):
                return None
            live = [v for v in self._variants.values() if not v.aborted]
            if not live:
                return None
            total = sum(max(0.0, v.spec.weight_pct) for v in live)
            if total <= 0.0:
                choice = live[0]
            else:
                roll = self._rng.random() * total
                acc = 0.0
                choice = live[-1]
                for v in live:
                    acc += max(0.0, v.spec.weight_pct)
                    if roll < acc:
                        choice = v
                        break
            return (self._config.name, choice.spec.name)

    # -- outcome + conversion feed -------------------------------------------
    def record(self, variant: str, ok: bool, latency_s: float) -> bool:
        """Fold one routed outcome into the variant's window; returns
        True when THIS sample tripped the variant's guardrail (the
        abort is already latched and published)."""
        with self._lock:
            v = self._variants.get(variant)
            if v is None or self._state not in (RAMP, MEASURE):
                return False
            v.requests += 1
            if not ok:
                v.errors += 1
            tripped = v.canary.record("canary", ok, latency_s)
            if tripped:
                self._seq += 1
                name = self._config.name if self._config else "?"
        if tripped:
            logger.warning("experiment %s: variant %s auto-aborted",
                           name, variant)
            self._changed()
        self.tick()
        return tripped

    def record_conversions(self, variant: str, count: int) -> bool:
        """Fold attributed conversions in (from the admin endpoint —
        ``pio experiment conversions`` tails the event store and posts
        per-variant totals). Cumulative: ``count`` is the variant's
        TOTAL so far; adoption takes the max, so replays and sibling
        spools never double-count."""
        with self._lock:
            v = self._variants.get(variant)
            if v is None:
                return False
            if count <= v.conversions:
                return True
            v.conversions = int(count)
            self._seq += 1
        self._changed()
        self.tick()
        return True

    def online_score(self, v: _Variant) -> float:
        w = self._config.conversion_weight if self._config else 0.5
        w = min(1.0, max(0.0, w))
        return (1.0 - w) * v.success_rate() + w * v.conversion_rate()

    # -- the state machine ---------------------------------------------------
    def tick(self) -> bool:
        """Advance the lifecycle on the injected clock; returns True
        when the state changed. Called from the router's admin sync
        loop and opportunistically from the outcome feed."""
        actions: list[tuple[str, str]] = []
        changed = False
        with self._lock:
            if self._config is None or self._state in (PROMOTED, ABORTED):
                return False
            now = self._clock.monotonic()
            live = [v for v in self._variants.values() if not v.aborted]
            if not live:
                # every arm breached: nothing to promote
                self._state = ABORTED
                self._decision = {"winner": None, "at": now,
                                  "reason": "all variants aborted"}
                actions = [("retire", v.spec.name)
                           for v in self._variants.values()]
                self._seq += 1
                changed = True
            elif self._state == RAMP:
                if now - self._started_at >= self._config.ramp_s:
                    self._state = MEASURE
                    self._measure_started_at = now
                    self._seq += 1
                    changed = True
            elif self._state == MEASURE:
                ready = (now - self._measure_started_at
                         >= self._config.measure_s
                         and all(v.requests >= self._config.min_requests
                                 for v in live))
                if ready:
                    winner = max(live, key=self.online_score)
                    self._state = PROMOTED
                    self._decision = {
                        "winner": winner.spec.name, "at": now,
                        "reason": (f"online score "
                                   f"{self.online_score(winner):.4f}"),
                        "scores": {v.spec.name:
                                   round(self.online_score(v), 6)
                                   for v in self._variants.values()}}
                    actions = [("default", winner.spec.name)] + [
                        ("retire", v.spec.name)
                        for v in self._variants.values()
                        if v.spec.name != winner.spec.name]
                    self._seq += 1
                    changed = True
            if changed:
                state, name = self._state, self._config.name
        if changed:
            logger.info("experiment %s: %s%s", name, state,
                        f" — {self._decision}" if self._decision else "")
            self._apply_gateway(actions)
            self._changed()
        return changed

    def _apply_gateway(self, actions: list[tuple[str, str]]) -> None:
        """Promotion = default-engine switch + loser retire on the
        gateway. Idempotent under the sibling race: whoever decides
        first wins, a second application is a no-op (the retire of an
        already-retired engine raises KeyError, the default switch to
        the current default is harmless)."""
        if self._gateway is None:
            return
        for action, engine in actions:
            try:
                if action == "default":
                    self._gateway.set_default(engine)
                else:
                    self._gateway.retire(engine)
            except (KeyError, ValueError) as exc:
                logger.info("experiment gateway %s(%s) skipped: %s",
                            action, engine, exc)

    def _changed(self) -> None:
        cb = self.on_change
        if cb is not None:
            cb()

    # -- shared-admin-state round-trip (api/router_server.py) ----------------
    def state_doc(self) -> dict | None:
        """The experiment as a seq'd cumulative document for the worker
        admin spool; None when nothing was ever defined."""
        with self._lock:
            if self._config is None:
                return None
            return {
                "seq": self._seq,
                "state": self._state,
                "config": self._config.to_doc(),
                "startedAt": self._started_at,
                "measureStartedAt": self._measure_started_at,
                "decision": dict(self._decision) if self._decision else None,
                "variants": [
                    {**v.spec.to_doc(),
                     "aborted": v.aborted,
                     "conversions": v.conversions}
                    for v in self._variants.values()],
            }

    def adopt_state(self, doc: dict | None) -> bool:
        """Diff-apply a sibling's :meth:`state_doc`: only a NEWER seq
        mutates, local outcome windows survive (adopting a variant's
        abort latch goes through the canary's own diff-applying
        ``adopt_state``), and conversion counts merge by max.
        Malformed documents are ignored — a torn spool entry must
        never take the experiment plane down."""
        if not isinstance(doc, dict):
            return False
        try:
            seq = int(doc["seq"])
            config = ExperimentConfig.from_doc(doc["config"])
            state = str(doc["state"])
            variant_docs = list(doc["variants"])
        except (KeyError, TypeError, ValueError) as exc:
            logger.warning("ignoring malformed experiment doc: %s", exc)
            return False
        with self._lock:
            if seq <= self._seq:
                return False
            fresh = (self._config is None
                     or self._config.name != config.name
                     or set(self._variants)
                     != {str(d.get("name")) for d in variant_docs})
            if fresh:
                self._variants = {}
            self._config = config
            self._state = state
            self._started_at = float(doc.get("startedAt") or 0.0)
            self._measure_started_at = float(
                doc.get("measureStartedAt") or 0.0)
            decision = doc.get("decision")
            self._decision = dict(decision) \
                if isinstance(decision, dict) else None
            for vdoc in variant_docs:
                try:
                    spec = VariantSpec.from_doc(vdoc)
                except (KeyError, TypeError, ValueError):
                    continue
                v = self._variants.get(spec.name)
                if v is None:
                    v = _Variant(spec, config.guardrail, rng=self._rng)
                    self._variants[spec.name] = v
                else:
                    v.spec = spec
                if bool(vdoc.get("aborted")) and not v.aborted:
                    v.canary.abort("sibling abort (spool)")
                v.conversions = max(v.conversions,
                                    int(vdoc.get("conversions") or 0))
            self._seq = seq
        return True

    # -- observability -------------------------------------------------------
    def snapshot(self) -> dict | None:
        """The operator view (``pio status --router`` / GET
        /fleet/experiments): lifecycle + per-variant online evidence."""
        with self._lock:
            if self._config is None:
                return None
            return {
                "name": self._config.name,
                "state": self._state,
                "seq": self._seq,
                "decision": dict(self._decision) if self._decision else None,
                "variants": [
                    {"name": v.spec.name,
                     "weightPct": v.spec.weight_pct,
                     "gridIdx": v.spec.grid_idx,
                     "offlineScore": v.spec.offline_score,
                     "aborted": v.aborted,
                     "requests": v.requests,
                     "errors": v.errors,
                     "conversions": v.conversions,
                     "onlineScore": round(self.online_score(v), 6)}
                    for v in self._variants.values()],
            }

    def collector(self) -> list[Metric]:
        """``pio_experiment_state{experiment,variant}`` (0=aborted,
        1=serving, 2=promoted winner) + per-variant conversion/request
        counters + the online score gauge, for the router /metrics."""
        with self._lock:
            if self._config is None:
                return []
            name = self._config.name
            winner = (self._decision or {}).get("winner")
            state_samples, conv, reqs, scores = [], [], [], []
            for v in self._variants.values():
                labels = {"experiment": name, "variant": v.spec.name}
                code = 0.0 if v.aborted else \
                    (2.0 if v.spec.name == winner else 1.0)
                state_samples.append((labels, code))
                conv.append((labels, float(v.conversions)))
                reqs.append((labels, float(v.requests)))
                scores.append((labels, self.online_score(v)))
        return [
            Metric("pio_experiment_state", "gauge",
                   "Experiment variant state: 0 aborted, 1 serving, "
                   "2 promoted winner.", samples=state_samples),
            Metric("pio_experiment_conversions_total", "counter",
                   "Attributed conversions folded into each variant's "
                   "online score.", samples=conv),
            Metric("pio_experiment_requests_total", "counter",
                   "Routed outcomes recorded per experiment variant.",
                   samples=reqs),
            Metric("pio_experiment_online_score", "gauge",
                   "Current per-variant online score (success rate "
                   "folded with conversion rate).", samples=scores),
        ]
