"""e2 engine primitives: CategoricalNaiveBayes, MarkovChain,
BinaryVectorizer.

Parity: e2/src/main/scala/.../e2/engine/{CategoricalNaiveBayes.scala:24-171,
MarkovChain.scala:26-84, BinaryVectorizer.scala:27-66}. The reference
computed counts with RDD aggregations; here the host encodes strings to
dense indices (BiMap) and the counting/normalizing/top-N math runs as
jitted JAX — segment_sum onto static-shape count tables, lax.top_k for
transition pruning.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Mapping, Sequence

import numpy as np

from predictionio_tpu.utils.bimap import BiMap

# ---------------------------------------------------------------------------
# CategoricalNaiveBayes
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LabeledPoint:
    """A string label + string-categorical feature vector.
    Parity: LabeledPoint (CategoricalNaiveBayes.scala:152-162)."""

    label: str
    features: tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class CategoricalNaiveBayesModel:
    """Log priors + per-(feature-position, value) log likelihoods.

    Parity: CategoricalNaiveBayesModel (CategoricalNaiveBayes.scala:60-150):
    ``priors``: label -> log P(label); ``likelihoods``: label -> per feature
    position, value -> log P(value | label, position).

    Arrays: ``log_priors`` [L]; ``log_likelihoods`` [L, F, V] where V is
    the per-position vocab padded to the max; lookups go through the label
    and per-position value BiMaps.
    """

    labels: BiMap
    value_maps: tuple[BiMap, ...]      # one per feature position
    log_priors: np.ndarray             # [L]
    log_likelihoods: np.ndarray        # [L, F, V]

    def log_score(
        self,
        point: LabeledPoint,
        default_likelihood: Callable[[Sequence[float]], float] = lambda ls: -math.inf,
    ) -> float | None:
        """Log P(label, features) for the point's own label; None for an
        unseen label. ``default_likelihood`` maps the label's OTHER
        likelihoods at that position to a score for an unseen value
        (CategoricalNaiveBayes.scala:102-139)."""
        label_ix = self.labels.get(point.label)
        if label_ix is None:
            return None
        return self._score(label_ix, point.features, default_likelihood)

    def _score(self, label_ix, features, default_likelihood):
        total = float(self.log_priors[label_ix])
        for pos, value in enumerate(features):
            value_ix = self.value_maps[pos].get(value)
            row = self.log_likelihoods[label_ix, pos]
            if value_ix is None:
                # the reference's likelihood Map holds only values SEEN
                # with this label; pass those (finite entries), not the
                # padded vocab row
                vocab = len(self.value_maps[pos])
                seen = [float(v) for v in row[:vocab] if math.isfinite(v)]
                total += default_likelihood(seen)
            else:
                total += float(row[value_ix])
        return total

    def predict(self, features: Sequence[str]) -> str:
        """Argmax label (CategoricalNaiveBayes.scala:141-149); unseen
        values contribute -inf like the reference's default. When every
        label ties at -inf, the first label wins (argmax-of-ties), so a
        label string is always returned."""
        best_label, best = None, -math.inf
        for label, label_ix in self.labels.to_dict().items():
            s = self._score(label_ix, tuple(features), lambda ls: -math.inf)
            if best_label is None or s > best:
                best_label, best = label, s
        return best_label


class CategoricalNaiveBayes:
    """Parity: CategoricalNaiveBayes.train (CategoricalNaiveBayes.scala:30-58)."""

    @staticmethod
    def train(points: Sequence[LabeledPoint]) -> CategoricalNaiveBayesModel:
        if not points:
            raise ValueError("cannot train on zero points")
        n_features = len(points[0].features)
        labels = BiMap.string_int(p.label for p in points)
        value_maps = tuple(
            BiMap.string_int(p.features[pos] for p in points)
            for pos in range(n_features)
        )
        n_labels = len(labels)
        max_vocab = max((len(m) for m in value_maps), default=1)

        # encode to dense indices on host; count with one jitted segment_sum
        label_ix = np.asarray([labels[p.label] for p in points], dtype=np.int32)
        feat_ix = np.asarray(
            [[value_maps[pos][p.features[pos]] for pos in range(n_features)]
             for p in points],
            dtype=np.int32,
        ).reshape(len(points), n_features)

        label_counts, value_counts = _nb_count(
            label_ix, feat_ix, n_labels, n_features, max_vocab
        )
        # f32 end-to-end: counts are integers well under 2**24, so the
        # log-space priors/likelihoods lose nothing vs the old f64 copy
        label_counts = np.asarray(label_counts, dtype=np.float32)
        value_counts = np.asarray(value_counts, dtype=np.float32)

        log_priors = np.log(label_counts) - math.log(len(points))
        with np.errstate(divide="ignore"):
            log_likelihoods = np.log(value_counts) - np.log(
                label_counts[:, None, None]
            )
        # mask out-of-vocab padding per position
        for pos, m in enumerate(value_maps):
            log_likelihoods[:, pos, len(m):] = -np.inf
        return CategoricalNaiveBayesModel(
            labels=labels,
            value_maps=value_maps,
            log_priors=log_priors,
            log_likelihoods=log_likelihoods,
        )


def _nb_count(label_ix, feat_ix, n_labels, n_features, max_vocab):
    """Count tables via segment_sum — the RDD combineByKey of
    CategoricalNaiveBayes.scala:33-49 as one jitted reduction."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def count(label_ix, feat_ix):
        label_counts = jax.ops.segment_sum(
            jnp.ones_like(label_ix, dtype=jnp.float32), label_ix,
            num_segments=n_labels,
        )
        # flatten (label, position, value) to one segment id per cell
        pos_ix = jnp.arange(n_features, dtype=jnp.int32)[None, :]
        flat = (
            label_ix[:, None] * (n_features * max_vocab)
            + pos_ix * max_vocab
            + feat_ix
        ).reshape(-1)
        value_counts = jax.ops.segment_sum(
            jnp.ones_like(flat, dtype=jnp.float32), flat,
            num_segments=n_labels * n_features * max_vocab,
        ).reshape(n_labels, n_features, max_vocab)
        return label_counts, value_counts

    return count(jnp.asarray(label_ix), jnp.asarray(feat_ix))


# ---------------------------------------------------------------------------
# MarkovChain
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MarkovChainModel:
    """Top-N outgoing transitions per state.
    Parity: MarkovChainModel (MarkovChain.scala:56-69)."""

    n_states: int
    top_n: int
    #: [S, top_n] column indices and normalized probabilities, -1 padded
    transition_index: np.ndarray
    transition_prob: np.ndarray

    def predict(self, state: int) -> list[tuple[int, float]]:
        """Top transitions from ``state`` (MarkovChain.scala:71-79)."""
        out = []
        for j, p in zip(self.transition_index[state], self.transition_prob[state]):
            if j >= 0 and p > 0:
                out.append((int(j), float(p)))
        return out


class MarkovChain:
    """Parity: MarkovChain.train (MarkovChain.scala:33-54): row-normalize
    the transition-count matrix, keep the top-N per row. Dense [S, S]
    build + lax.top_k, jitted."""

    @staticmethod
    def train(
        n_states: int,
        transitions: Sequence[tuple[int, int, float]],
        top_n: int = 10,
    ) -> MarkovChainModel:
        import jax
        import jax.numpy as jnp

        rows = np.asarray([t[0] for t in transitions], dtype=np.int32)
        cols = np.asarray([t[1] for t in transitions], dtype=np.int32)
        vals = np.asarray([t[2] for t in transitions], dtype=np.float32)
        k = min(top_n, n_states)

        @jax.jit
        def build(rows, cols, vals):
            dense = jnp.zeros((n_states, n_states), dtype=jnp.float32)
            dense = dense.at[rows, cols].add(vals)
            row_sums = dense.sum(axis=1, keepdims=True)
            probs = jnp.where(row_sums > 0, dense / jnp.maximum(row_sums, 1e-30), 0.0)
            top_p, top_i = jax.lax.top_k(probs, k)
            top_i = jnp.where(top_p > 0, top_i, -1)
            return top_i, top_p

        top_i, top_p = build(jnp.asarray(rows), jnp.asarray(cols), jnp.asarray(vals))
        return MarkovChainModel(
            n_states=n_states,
            top_n=k,
            transition_index=np.asarray(top_i),
            transition_prob=np.asarray(top_p),
        )


# ---------------------------------------------------------------------------
# BinaryVectorizer
# ---------------------------------------------------------------------------


class BinaryVectorizer:
    """(property, value) -> one-hot index encoder.
    Parity: BinaryVectorizer (BinaryVectorizer.scala:27-66)."""

    def __init__(self, property_map: BiMap):
        self.property_map = property_map

    @staticmethod
    def fit(pairs) -> "BinaryVectorizer":
        """Build the index from observed (property, value) pairs
        (BinaryVectorizer.scala:31-41)."""
        return BinaryVectorizer(BiMap.string_int(tuple(p) for p in pairs))

    def __len__(self) -> int:
        return len(self.property_map)

    def to_binary(self, pairs: Sequence[tuple[str, str]]) -> np.ndarray:
        """One-hot encode; unknown pairs are ignored
        (BinaryVectorizer.scala:43-53)."""
        vec = np.zeros(len(self.property_map), dtype=np.float32)
        for pair in pairs:
            ix = self.property_map.get(tuple(pair))
            if ix is not None:
                vec[ix] = 1.0
        return vec

    def to_binary_batch(self, batch: Sequence[Sequence[tuple[str, str]]]) -> np.ndarray:
        """[B, D] one-hot matrix — the batched form algorithms feed to the
        mesh (rows become MXU matmul operands downstream)."""
        out = np.zeros((len(batch), len(self.property_map)), dtype=np.float32)
        for i, pairs in enumerate(batch):
            out[i] = self.to_binary(pairs)
        return out
