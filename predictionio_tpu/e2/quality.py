"""Quality-parity harness: independent NumPy ALS-WR + ranking metrics.

The north-star gate (BASELINE.md) is throughput *at matching MAP@10* —
speed claims are meaningless if the TPU factorizer converges to worse
factors than the reference's MLlib ALS
(reference: tests/pio_tests/engines/recommendation-engine/src/main/scala/
ALSAlgorithm.scala:79-93 and Evaluation.scala's Precision@K protocol).
Spark/MLlib cannot run in this environment (no JVM), so the comparison
point is an **independent NumPy implementation of the same ALS-WR
math** — the estimator MLlib's `ALS.train` computes — sharing *no code
or data layout* with the device path: it uses sort + ``np.add.reduceat``
segment reductions where the device path uses padded slab buckets
(ops/als.py), so it cross-checks the bucketing/masking machinery as well
as the solver.

Metrics follow the reference evaluation protocol: k-fold split over
rating rows, per-user top-k over items unseen in training,
Precision@K / MAP@K with a rating threshold defining relevance
(Evaluation.scala PrecisionAtK: tpCount / min(k, |positives|)). A
popularity baseline anchors the scale: a factorizer that fails to beat
most-popular recommendations has not learned personalization.
"""

from __future__ import annotations

import numpy as np

from predictionio_tpu.data.movielens import RatingsDataset


# ---------------------------------------------------------------------------
# Splits
# ---------------------------------------------------------------------------


def kfold_split(
    ds: RatingsDataset, k_fold: int = 5, fold: int = 0, seed: int = 3
) -> tuple[RatingsDataset, dict[int, list[tuple[int, float]]]]:
    """Reference protocol: assign each rating row to one of ``k_fold``
    folds (DataSource.scala:82-105 uses zipWithUniqueId % kFold; a seeded
    permutation gives the same exchangeable split deterministically).
    Returns (training fold, test ratings grouped per user)."""
    rng = np.random.default_rng(seed)
    fold_of = rng.permutation(ds.nnz) % k_fold
    test = fold_of == fold
    train = RatingsDataset(
        users=ds.users[~test],
        items=ds.items[~test],
        ratings=ds.ratings[~test],
        num_users=ds.num_users,
        num_items=ds.num_items,
    )
    test_by_user: dict[int, list[tuple[int, float]]] = {}
    for u, i, r in zip(ds.users[test], ds.items[test], ds.ratings[test]):
        test_by_user.setdefault(int(u), []).append((int(i), float(r)))
    return train, test_by_user


# ---------------------------------------------------------------------------
# Independent NumPy ALS-WR (the MLlib-equivalent estimator)
# ---------------------------------------------------------------------------


def _segment_half_solve(
    V: np.ndarray,
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    num_rows: int,
    lam: float,
) -> np.ndarray:
    """One ALS-WR half-step: for every row entity solve
    (F^T F + lam * n I) x = F^T r over its observed column factors.
    Segment layout: sort by row, reduce contiguous runs with
    ``np.add.reduceat`` — no padding, no bucketing."""
    rank = V.shape[1]
    order = np.argsort(rows, kind="stable")
    r_sorted = rows[order]
    F = V[cols[order]]                                  # (nnz, K)
    seg_rows, seg_starts = np.unique(r_sorted, return_index=True)
    counts = np.diff(np.append(seg_starts, len(r_sorted)))

    outer = F[:, :, None] * F[:, None, :]
    A = np.add.reduceat(outer.reshape(len(F), rank * rank), seg_starts, axis=0)
    A = A.reshape(-1, rank, rank)
    A += (lam * counts)[:, None, None] * np.eye(rank, dtype=V.dtype)
    b = np.add.reduceat(F * vals[order][:, None], seg_starts, axis=0)

    out = np.zeros((num_rows, rank), dtype=V.dtype)
    out[seg_rows] = np.linalg.solve(A, b[..., None])[..., 0]
    return out


def numpy_als_wr(
    ds: RatingsDataset,
    rank: int = 10,
    iterations: int = 10,
    lam: float = 0.01,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Reference-math ALS: alternating ALS-WR half-steps, item factors
    initialized N(0,1)/sqrt(rank), users solved first — the `ALS.train`
    estimator (ALSAlgorithm.scala:79-85) in plain NumPy."""
    rng = np.random.default_rng(seed)
    V = (rng.standard_normal((ds.num_items, rank)) / np.sqrt(rank)).astype(
        np.float32
    )
    U = np.zeros((ds.num_users, rank), dtype=np.float32)
    for _ in range(iterations):
        U = _segment_half_solve(V, ds.users, ds.items, ds.ratings,
                                ds.num_users, lam)
        V = _segment_half_solve(U, ds.items, ds.users, ds.ratings,
                                ds.num_items, lam)
    return U, V


def _rowloop_half_solve(
    V: np.ndarray,
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    num_rows: int,
    lam: float,
) -> np.ndarray:
    """One exact ALS-WR half-step via a per-row BLAS loop. Same
    estimator as :func:`_segment_half_solve` but memory-bounded at
    O(K^2) per row instead of materialising (nnz, K, K) outer products
    — the only way to run the oracle at BASELINE rank 200, where the
    segment formulation would allocate nnz * 160 KB."""
    rank = V.shape[1]
    order = np.argsort(rows, kind="stable")
    r_sorted = rows[order]
    c_sorted = cols[order]
    v_sorted = vals[order]
    seg_rows, seg_starts = np.unique(r_sorted, return_index=True)
    bounds = np.append(seg_starts, len(r_sorted))
    # pio: lint-ignore[dtype-discipline]: exact normal-equation oracle — f64 keeps the rank-200 solve conditioned; host-side, never ships to TPU
    out = np.zeros((num_rows, rank), dtype=np.float64)
    eye = np.eye(rank, dtype=np.float64)  # pio: lint-ignore[dtype-discipline]: same f64 oracle solve as above
    for j, row in enumerate(seg_rows):
        lo, hi = bounds[j], bounds[j + 1]
        F = V[c_sorted[lo:hi]].astype(np.float64)  # pio: lint-ignore[dtype-discipline]: same f64 oracle solve as above
        A = F.T @ F + lam * (hi - lo) * eye
        b = F.T @ v_sorted[lo:hi].astype(np.float64)  # pio: lint-ignore[dtype-discipline]: same f64 oracle solve as above
        out[row] = np.linalg.solve(A, b)
    return out.astype(np.float32)


def numpy_als_wr_rowloop(
    ds: RatingsDataset,
    rank: int,
    iterations: int = 5,
    lam: float = 0.1,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """High-rank variant of :func:`numpy_als_wr` (exact solves, per-row
    loop) — the rank-200 parity oracle for the bench."""
    rng = np.random.default_rng(seed)
    V = (rng.standard_normal((ds.num_items, rank)) / np.sqrt(rank)).astype(
        np.float32
    )
    U = np.zeros((ds.num_users, rank), dtype=np.float32)
    for _ in range(iterations):
        U = _rowloop_half_solve(V, ds.users, ds.items, ds.ratings,
                                ds.num_users, lam)
        V = _rowloop_half_solve(U, ds.items, ds.users, ds.ratings,
                                ds.num_items, lam)
    return U, V


# ---------------------------------------------------------------------------
# Ranking metrics (reference Evaluation.scala protocol)
# ---------------------------------------------------------------------------


def _topk_unseen(
    scores: np.ndarray, train: RatingsDataset, users: np.ndarray, k: int
) -> np.ndarray:
    """Top-k item indices per requested user, excluding training-seen
    items (the serving path's exclude_seen semantics). ``scores`` is
    already row-aligned with ``users``."""
    sub = scores.copy()
    pos_of = {int(u): j for j, u in enumerate(users)}
    for u, i in zip(train.users, train.items):
        j = pos_of.get(int(u))
        if j is not None:
            sub[j, i] = -np.inf
    part = np.argpartition(-sub, k, axis=1)[:, :k]
    part_scores = np.take_along_axis(sub, part, axis=1)
    order = np.argsort(-part_scores, axis=1)
    return np.take_along_axis(part, order, axis=1)


def ranking_eval(
    score_fn,
    train: RatingsDataset,
    test_by_user: dict[int, list[tuple[int, float]]],
    k: int = 10,
    threshold: float = 4.0,
) -> dict[str, float]:
    """MAP@k / Precision@k over held-out positives (rating >= threshold).

    ``score_fn(users) -> (len(users), num_items)`` scores; users whose
    held-out set has no positives are skipped (OptionAverageMetric
    contract, Evaluation.scala:40-45)."""
    users = np.asarray(sorted(test_by_user), dtype=np.int32)
    scores = score_fn(users)
    topk = _topk_unseen(scores, train, users, k)

    maps, precs = [], []
    for j, u in enumerate(users):
        positives = {i for i, r in test_by_user[int(u)] if r >= threshold}
        if not positives:
            continue
        denom = min(k, len(positives))
        hits, ap = 0, 0.0
        for rank_pos, item in enumerate(topk[j], start=1):
            if int(item) in positives:
                hits += 1
                ap += hits / rank_pos
        maps.append(ap / denom)
        precs.append(hits / denom)
    return {
        f"map@{k}": float(np.mean(maps)) if maps else 0.0,
        f"precision@{k}": float(np.mean(precs)) if precs else 0.0,
        "evaluated_users": len(maps),
    }


def factor_score_fn(U: np.ndarray, V: np.ndarray):
    return lambda users: np.asarray(U)[users] @ np.asarray(V).T


def test_rmse(
    U: np.ndarray,
    V: np.ndarray,
    test_by_user: dict[int, list[tuple[int, float]]],
) -> float:
    """Held-out RMSE of the rating predictions — the estimator's native
    objective and the *sharp* parity metric: two correct ALS-WR
    implementations at the same hyperparameters must land within
    seed-level noise of each other here."""
    U, V = np.asarray(U), np.asarray(V)
    users = np.asarray(
        [u for u, lst in test_by_user.items() for _ in lst], dtype=np.int64
    )
    items = np.asarray(
        [i for lst in test_by_user.values() for i, _ in lst], dtype=np.int64
    )
    vals = np.asarray(
        # pio: lint-ignore[dtype-discipline]: parity-oracle RMSE accumulates in f64 so the noise floor compares implementations, not summation error
        [r for lst in test_by_user.values() for _, r in lst], dtype=np.float64
    )
    pred = np.einsum("nk,nk->n", U[users], V[items])
    return float(np.sqrt(np.mean((pred - vals) ** 2)))


def popularity_score_fn(train: RatingsDataset):
    """Non-personalized anchor: score every item by its training rating
    count (same for all users)."""
    counts = np.bincount(train.items, minlength=train.num_items).astype(
        np.float32
    )
    return lambda users: np.broadcast_to(
        counts, (len(users), train.num_items)
    ).copy()


# ---------------------------------------------------------------------------
# The parity comparison
# ---------------------------------------------------------------------------


#: implicit-ALS config for :func:`compare_quality`'s ranking measurement
#: (selected by sweep on the preference-coupled ML-100k-statistics set:
#: rank 10 / alpha 5 / lam 0.1 gives MAP@10 ~2.1x popularity; larger
#: alpha or rank over-weights the sparse positives and decays toward or
#: below the popularity anchor)
IMPLICIT_RANK = 10
IMPLICIT_ALPHA = 5.0
IMPLICIT_LAM = 0.1


def implicit_ranking_eval(
    train: RatingsDataset,
    test_by_user: dict[int, list[tuple[int, float]]],
    k: int = 10,
    threshold: float = 4.0,
    seed: int = 3,
    mesh=None,
) -> dict[str, float]:
    """MAP@k of the implicit-feedback ALS path — the framework's
    production ranking story (the ecommerce template's `trainImplicit`
    analogue, reference: examples/scala-parallel-ecommercerecommendation/
    ecomm/src/main/scala/ALSAlgorithm.scala). Ratings >= ``threshold``
    binarize to unit-confidence interactions; ranking scores are the
    factor dot products."""
    from predictionio_tpu.ops.als import RatingsCOO, als_train

    keep = train.ratings >= threshold
    coo = RatingsCOO(
        train.users[keep], train.items[keep],
        np.ones(int(keep.sum()), dtype=np.float32),
        train.num_users, train.num_items,
    )
    f = als_train(coo, rank=IMPLICIT_RANK, iterations=10, lam=IMPLICIT_LAM,
                  implicit=True, alpha=IMPLICIT_ALPHA, seed=seed, mesh=mesh)
    return ranking_eval(factor_score_fn(f.user, f.item), train,
                        test_by_user, k=k, threshold=threshold)


def implicit_vs_popularity_kfold(
    ds: RatingsDataset,
    k_fold: int = 5,
    k: int = 10,
    threshold: float = 4.0,
    seed: int = 3,
) -> dict[str, float]:
    """Mean MAP@k of the implicit path vs the popularity baseline over
    ALL folds — the protocol shared by the bench's real-data keys
    (``map10_*_real``) and the off-generator gating test, hoisted here
    so the two cannot drift (ADVICE-style round-4 review finding)."""
    imps, pops = [], []
    for fold in range(k_fold):
        train, test = kfold_split(ds, k_fold=k_fold, fold=fold, seed=seed)
        pops.append(ranking_eval(
            popularity_score_fn(train), train, test, k=k,
            threshold=threshold)[f"map@{k}"])
        imps.append(implicit_ranking_eval(
            train, test, k=k, threshold=threshold, seed=seed)[f"map@{k}"])
    return {
        f"map{k}_implicit": float(np.mean(imps)),
        f"map{k}_popularity": float(np.mean(pops)),
    }


def compare_quality(
    ds: RatingsDataset,
    rank: int = 10,
    iterations: int = 10,
    lam: float = 0.01,
    k: int = 10,
    threshold: float = 4.0,
    k_fold: int = 5,
    seed: int = 3,
    mesh=None,
) -> dict[str, float]:
    """Train the device-path ALS (ops/als.als_train) and the independent
    NumPy ALS-WR on the same fold; evaluate both plus the popularity
    baseline AND the implicit-feedback ranking path under the identical
    protocol. Returns a flat metric dict (the bench harness embeds it in
    the BENCH JSON line).

    Two quality axes, stated plainly: ``rmse_*``/``map{k}_tpu`` vs
    ``map{k}_ref`` are *parity* metrics (same estimator, two
    implementations — they must agree); ``map{k}_implicit`` vs
    ``map{k}_popularity`` is the *ranking-wins* metric — explicit ALS
    models rating values, not interaction propensity, and loses to the
    popularity baseline on top-N (MLlib's does too); the implicit path
    is the production ranking story and must beat popularity."""
    from predictionio_tpu.ops.als import RatingsCOO, als_train

    train, test_by_user = kfold_split(ds, k_fold=k_fold, seed=seed)

    factors = als_train(
        RatingsCOO(train.users, train.items, train.ratings,
                   train.num_users, train.num_items),
        rank=rank, iterations=iterations, lam=lam, seed=seed, mesh=mesh,
    )
    tpu = ranking_eval(
        factor_score_fn(factors.user, factors.item), train, test_by_user,
        k=k, threshold=threshold,
    )
    rmse_tpu = test_rmse(factors.user, factors.item, test_by_user)

    U, V = numpy_als_wr(train, rank=rank, iterations=iterations, lam=lam,
                        seed=seed + 1)
    ref = ranking_eval(factor_score_fn(U, V), train, test_by_user,
                       k=k, threshold=threshold)
    rmse_ref = test_rmse(U, V, test_by_user)

    pop = ranking_eval(popularity_score_fn(train), train, test_by_user,
                       k=k, threshold=threshold)
    imp = implicit_ranking_eval(train, test_by_user, k=k,
                                threshold=threshold, seed=seed, mesh=mesh)

    return {
        f"map{k}_tpu": round(tpu[f"map@{k}"], 4),
        f"map{k}_ref": round(ref[f"map@{k}"], 4),
        f"map{k}_popularity": round(pop[f"map@{k}"], 4),
        f"map{k}_implicit": round(imp[f"map@{k}"], 4),
        f"precision{k}_tpu": round(tpu[f"precision@{k}"], 4),
        f"precision{k}_ref": round(ref[f"precision@{k}"], 4),
        "rmse_tpu": round(rmse_tpu, 4),
        "rmse_ref": round(rmse_ref, 4),
        "evaluated_users": tpu["evaluated_users"],
    }
