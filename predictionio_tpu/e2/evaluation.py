"""k-fold cross-validation splitting.

Parity: e2/src/main/scala/.../e2/evaluation/CrossValidation.scala:24-76 —
``splitData`` assigns each record a fold by ``zipWithUniqueId % k`` and
yields, per fold, (training records, eval-info, (query, actual) pairs).
"""

from __future__ import annotations

from typing import Callable, Sequence, TypeVar

D = TypeVar("D")
TD = TypeVar("TD")
EI = TypeVar("EI")
Q = TypeVar("Q")
A = TypeVar("A")


def cross_validation_split(
    data: Sequence[D],
    k: int,
    make_training: Callable[[list[D]], TD],
    make_query_actual: Callable[[D], tuple[Q, A]],
    eval_info: EI = None,
) -> list[tuple[TD, EI, list[tuple[Q, A]]]]:
    """Split ``data`` into k folds: fold i evaluates on records whose
    index % k == i and trains on the rest (CrossValidation.scala:36-63).

    Index-based assignment keeps the split deterministic, like the
    reference's zipWithUniqueId — shuffle upstream if randomization is
    wanted.
    """
    if k <= 0:
        raise ValueError("k must be positive")
    folds = []
    for fold in range(k):
        train = [d for i, d in enumerate(data) if i % k != fold]
        held_out = [d for i, d in enumerate(data) if i % k == fold]
        qa = [make_query_actual(d) for d in held_out]
        folds.append((make_training(train), eval_info, qa))
    return folds
