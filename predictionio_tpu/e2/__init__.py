"""e2 — reusable engine-building library.

Parity: the reference's `e2` module (e2/src/main/scala/.../e2/): small,
engine-agnostic building blocks (categorical Naive Bayes, Markov chain,
binary vectorizer, cross-validation splitter) re-designed for JAX — count
aggregation with segment_sum, top-N with lax.top_k, static shapes
throughout.
"""

from predictionio_tpu.e2.engine import (
    BinaryVectorizer,
    CategoricalNaiveBayes,
    CategoricalNaiveBayesModel,
    LabeledPoint,
    MarkovChain,
    MarkovChainModel,
)
from predictionio_tpu.e2.evaluation import cross_validation_split

__all__ = [
    "BinaryVectorizer",
    "CategoricalNaiveBayes",
    "CategoricalNaiveBayesModel",
    "LabeledPoint",
    "MarkovChain",
    "MarkovChainModel",
    "cross_validation_split",
]
