"""FastEvalEngine — grid evaluation with pipeline-prefix memoization.

Parity: core/src/main/scala/.../controller/FastEvalEngine.scala:46-346.
A hyperparameter grid usually varies only one pipeline stage between
neighbouring points; re-running the full read→prepare→train→predict
pipeline per point wastes the shared prefix. This engine memoizes:

- DataSourcePrefix(ds_params)                  → eval splits
- PreparatorPrefix(+ prep_params)              → prepared data per fold
- AlgorithmsPrefix(+ algo_params_list)         → trained models per fold
- ServingPrefix(+ serving_params)              → served (Q, P, A) results

(FastEvalEngine.scala:88-268). Training is the expensive stage on the
mesh (repeated jitted solves); sharing models across grid points that
differ only in serving params is the big win. Cache keys are the
canonical JSON of the slot params, so logically-equal params hit.

Divergence from the reference: the reference also memoized batchPredict
output inside AlgorithmsPrefix, which silently assumed every serving's
``supplement`` is identity. Here prediction runs at the ServingPrefix
stage (after the real ``supplement``), trading a cheap re-predict for
exact Engine.eval semantics.
"""

from __future__ import annotations

import json
import logging
from typing import TYPE_CHECKING, Any, Sequence

from predictionio_tpu.controller.engine import Engine, _sanity_check
from predictionio_tpu.controller.params import EngineParams, params_to_json

if TYPE_CHECKING:
    from predictionio_tpu.workflow.context import EngineContext

logger = logging.getLogger(__name__)


def _slot_key(name_params: tuple[str, Any]) -> str:
    name, params = name_params
    return json.dumps({"name": name, "params": params_to_json(params)}, sort_keys=True)


def _algos_key(algorithm_params_list: Sequence[tuple[str, Any]]) -> str:
    return json.dumps(
        [{"name": n, "params": params_to_json(p)} for n, p in algorithm_params_list],
        sort_keys=True,
    )


class FastEvalEngineWorkflow:
    """The memo table for one batch_eval run
    (FastEvalEngineWorkflow, FastEvalEngine.scala:46-286)."""

    def __init__(self, engine: Engine, ctx: "EngineContext"):
        self.engine = engine
        self.ctx = ctx
        self.data_source_cache: dict[str, list] = {}
        self.preparator_cache: dict[tuple[str, str], list] = {}
        self.algorithms_cache: dict[tuple[str, str, str], list] = {}
        self.serving_cache: dict[tuple[str, str, str, str], list] = {}

    # -- prefix stages (getDataSourceResult:88, getPreparatorResult:113,
    #    computeAlgorithmsResult:133, getServingResult:226) ------------------
    def get_data_source_result(self, ep: EngineParams) -> list:
        key = _slot_key(ep.data_source_params)
        if key not in self.data_source_cache:
            data_source = self.engine._component(
                self.engine.data_source_class_map, "datasource", ep.data_source_params
            )
            splits = list(data_source.read_eval(self.ctx))
            for fold, (td, _, _) in enumerate(splits):
                _sanity_check(td, f"fold[{fold}] training data",
                              not self.ctx.workflow_params.skip_sanity_check)
            self.data_source_cache[key] = splits
        return self.data_source_cache[key]

    def get_preparator_result(self, ep: EngineParams) -> list:
        key = (_slot_key(ep.data_source_params), _slot_key(ep.preparator_params))
        if key not in self.preparator_cache:
            preparator = self.engine._component(
                self.engine.preparator_class_map, "preparator", ep.preparator_params
            )
            splits = self.get_data_source_result(ep)
            self.preparator_cache[key] = [
                preparator.prepare(self.ctx, td) for td, _, _ in splits
            ]
        return self.preparator_cache[key]

    def get_algorithms_result(self, ep: EngineParams) -> list:
        """Trained models: one list of per-algo models per fold."""
        key = (
            _slot_key(ep.data_source_params),
            _slot_key(ep.preparator_params),
            _algos_key(ep.algorithm_params_list),
        )
        if key not in self.algorithms_cache:
            algo_list = list(ep.algorithm_params_list) or [("", None)]
            algorithms = [
                self.engine._component(self.engine.algorithm_class_map, "algorithms", ap)
                for ap in algo_list
            ]
            prepared = self.get_preparator_result(ep)
            self.algorithms_cache[key] = [
                (algorithms, [algo.train(self.ctx, pd) for algo in algorithms])
                for pd in prepared
            ]
        return self.algorithms_cache[key]

    def get_serving_result(self, ep: EngineParams) -> list:
        key = (
            _slot_key(ep.data_source_params),
            _slot_key(ep.preparator_params),
            _algos_key(ep.algorithm_params_list),
            _slot_key(ep.serving_params),
        )
        if key not in self.serving_cache:
            serving = self.engine._component(
                self.engine.serving_class_map, "serving", ep.serving_params
            )
            splits = self.get_data_source_result(ep)
            per_fold_models = self.get_algorithms_result(ep)
            results = []
            for (td, ei, qa_pairs), (algorithms, models) in zip(splits, per_fold_models):
                supplemented = [
                    (i, serving.supplement(q)) for i, (q, _) in enumerate(qa_pairs)
                ]
                per_algo = [
                    dict(algo.batch_predict(model, supplemented))
                    for algo, model in zip(algorithms, models)
                ]
                fold_results = []
                for i, (q, a) in enumerate(qa_pairs):
                    predictions = [preds[i] for preds in per_algo if i in preds]
                    fold_results.append((q, serving.serve(q, predictions), a))
                results.append((ei, fold_results))
            self.serving_cache[key] = results
        return self.serving_cache[key]


class FastEvalEngine(Engine):
    """Drop-in Engine whose batch_eval shares pipeline prefixes across the
    grid (FastEvalEngine, FastEvalEngine.scala:313-346)."""

    def batch_eval(
        self,
        ctx: "EngineContext",
        engine_params_list: Sequence[EngineParams],
    ) -> list[tuple[EngineParams, list]]:
        workflow = FastEvalEngineWorkflow(self, ctx)
        return [
            (ep, workflow.get_serving_result(ep)) for ep in engine_params_list
        ]
