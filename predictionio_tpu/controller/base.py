"""DASE component contracts: DataSource / Preparator / Algorithm / Serving
/ Evaluator, plus Doer construction.

Parity: core/src/main/scala/.../core/{BaseDataSource.scala:34-54,
BasePreparator.scala:33-44, BaseAlgorithm.scala:58-126,
BaseServing.scala:31-53, BaseEvaluator.scala:39-75, AbstractDoer.scala:35-69}
and controller/{PDataSource,LServing,...}.scala.

Type vocabulary (Engine.scala:83-89): TD training data, EI evaluation
info, PD prepared data, Q query, P predicted result, A actual result,
M model. Components are Generic over these so engines stay typed.

TPU-first difference: every hook that received a SparkContext receives an
``EngineContext`` (predictionio_tpu.workflow.context) carrying the JAX
device mesh, RNG key, and workflow params — SURVEY.md §7's translation
table row 1.
"""

from __future__ import annotations

import abc
import dataclasses
import inspect
from typing import TYPE_CHECKING, Any, Generic, Sequence, TypeVar

from predictionio_tpu.controller.params import EmptyParams, Params

if TYPE_CHECKING:
    from predictionio_tpu.workflow.context import EngineContext

TD = TypeVar("TD")
EI = TypeVar("EI")
PD = TypeVar("PD")
Q = TypeVar("Q")
P = TypeVar("P")
A = TypeVar("A")
M = TypeVar("M")


class Doer:
    """Reflective component construction from params.

    Parity: AbstractDoer/Doer (AbstractDoer.scala:35-69): construct with
    (params) when the __init__ accepts it, else no-arg. Components keep
    their params on ``self.params``.
    """

    @staticmethod
    def create(cls: type, params: Any = None):
        sig = inspect.signature(cls.__init__)
        # count non-self positional params without defaults
        accepts_params = len(sig.parameters) > 1
        if accepts_params:
            instance = cls(params if params is not None else EmptyParams())
        else:
            instance = cls()
            instance.params = params if params is not None else EmptyParams()
        return instance


class BaseComponent:
    """Common base: stores params, exposes the params class for JSON binding."""

    #: dataclass bound to this component's engine.json "params" object
    params_class: type = EmptyParams

    #: dataclass the /queries.json body binds to (algorithms/servings).
    #: Parity: BaseAlgorithm.queryClass via TypeResolver
    #: (BaseAlgorithm.scala:91-109); declared explicitly here since Python
    #: generics don't survive to runtime.
    query_class: type | None = None

    def __init__(self, params: Any = None):
        self.params = params if params is not None else EmptyParams()


class DataSource(BaseComponent, Generic[TD, EI, Q, A], abc.ABC):
    """Reads training and evaluation data from the Event Store.

    Parity: BaseDataSource (BaseDataSource.scala:34-54) + PDataSource
    (PDataSource.scala:36-72). The L/P split collapses: a single
    DataSource returns host data structures; sharding onto the mesh is the
    Preparator/Algorithm's job.
    """

    @abc.abstractmethod
    def read_training(self, ctx: "EngineContext") -> TD:
        """Parity: readTrainingBase/readTraining."""

    def read_eval(self, ctx: "EngineContext") -> Sequence[tuple[TD, EI, Sequence[tuple[Q, A]]]]:
        """k folds of (training data, eval info, (query, actual) pairs).
        Parity: readEvalBase/readEval (BaseDataSource.scala:40-49)."""
        return []


class Preparator(BaseComponent, Generic[TD, PD], abc.ABC):
    """Transforms training data into prepared (model-ready) data.

    Parity: BasePreparator (BasePreparator.scala:33-44). In the TPU design
    this is the ragged->static boundary: the natural place to pad/bucket
    events into fixed-shape arrays and device_put them onto the mesh
    (SURVEY.md §7 hard-parts note on recompilation control).
    """

    @abc.abstractmethod
    def prepare(self, ctx: "EngineContext", td: TD) -> PD:
        """Parity: prepareBase/prepare."""


class IdentityPreparator(Preparator[TD, TD]):
    """Passes training data through. Parity: IdentityPreparator
    (IdentityPreparator.scala:34-92)."""

    def prepare(self, ctx: "EngineContext", td: TD) -> TD:
        return td


class Algorithm(BaseComponent, Generic[PD, M, Q, P], abc.ABC):
    """Trains a model and answers queries.

    Parity: BaseAlgorithm (BaseAlgorithm.scala:58-126). The reference's
    P/P2L/L locality taxonomy (SURVEY.md §2.6) is re-expressed in
    controller/algorithm.py as Local/HostModel/Sharded mesh placements;
    this base carries the shared contract.
    """

    @abc.abstractmethod
    def train(self, ctx: "EngineContext", pd: PD) -> M:
        """Parity: trainBase/train."""

    @abc.abstractmethod
    def predict(self, model: M, query: Q) -> P:
        """Serving-time single query. Parity: predictBase/predict."""

    def batch_predict(self, model: M, queries: Sequence[tuple[int, Q]]) -> Sequence[tuple[int, P]]:
        """Evaluation-time bulk predict over (index, query) pairs.

        Parity: batchPredictBase (BaseAlgorithm.scala:73-90). Default maps
        ``predict``; mesh-sharded algorithms override with a vectorized
        jitted path (the RDD-join analogue).
        """
        return [(i, self.predict(model, q)) for i, q in queries]

    # -- persistence hooks (BaseAlgorithm.makePersistentModel:111-126) ------
    def make_persistent_model(self, ctx: "EngineContext", model: M) -> Any:
        """Return what the train workflow should persist for ``model``:

        - the model itself (default) -> pickled into the MODELDATA repo;
        - a ``PersistentModelManifest`` -> the algorithm saved it via its
          own ``save`` hook (orbax sharded checkpoint etc.);
        - ``None`` -> nothing persisted; retrain on deploy (the reference's
          "Unit model" semantics, PAlgorithm.scala:89-101).
        """
        return model

    def load_model(self, ctx: "EngineContext", manifest: "PersistentModelManifest") -> M:
        """Inverse of a manifest-producing make_persistent_model."""
        raise NotImplementedError(
            f"{type(self).__name__} stored a manifest but does not implement load_model"
        )


@dataclasses.dataclass(frozen=True)
class PersistentModelManifest:
    """Stored in place of a model blob when the algorithm persists the
    model itself. Parity: PersistentModelManifest
    (workflow/PersistentModelManifest.scala)."""

    class_name: str
    location: str = ""


class Serving(BaseComponent, Generic[Q, P], abc.ABC):
    """Combines per-algorithm predictions into one response.

    Parity: BaseServing (BaseServing.scala:31-53) / LServing
    (LServing.scala:30-54).
    """

    def supplement(self, query: Q) -> Q:
        """Pre-process query before algorithms see it (supplementBase)."""
        return query

    @abc.abstractmethod
    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        """Parity: serveBase/serve; receives the ORIGINAL query
        (Engine.scala:810-812)."""


class FirstServing(Serving[Q, P]):
    """Serves the first algorithm's prediction. Parity: LFirstServing
    (LFirstServing.scala:28-41)."""

    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        return predictions[0]


class AverageServing(Serving[Q, float]):
    """Averages numeric predictions. Parity: LAverageServing
    (LAverageServing.scala:28-43)."""

    def serve(self, query: Q, predictions: Sequence[float]) -> float:
        return sum(predictions) / len(predictions)


class SanityCheck(abc.ABC):
    """Data classes may implement this to be checked between pipeline
    stages. Parity: SanityCheck (controller/SanityCheck.scala)."""

    @abc.abstractmethod
    def sanity_check(self) -> None:
        """Raise on inconsistent data."""


class Evaluator(BaseComponent, Generic[EI, Q, P, A], abc.ABC):
    """Folds evaluation results into a final score.

    Parity: BaseEvaluator (BaseEvaluator.scala:39-75).
    """

    @abc.abstractmethod
    def evaluate(
        self,
        ctx: "EngineContext",
        engine_eval_data_set: Sequence[
            tuple[Any, Sequence[tuple[EI, Sequence[tuple[Q, P, A]]]]]
        ],
        params: Any,
    ) -> Any:
        """engine_eval_data_set: per EngineParams, the per-fold
        (EI, [(Q, P, A)]) results. Returns a BaseEvaluatorResult-like."""
