"""The Metric family — per-query scoring folded into scalar results.

Parity: core/src/main/scala/.../controller/Metric.scala:39-269. A Metric
scores an evaluation data set (the output of ``Engine.eval``: per-fold
``(EI, [(Q, P, A)])``) into a comparable result, usually a float.

The reference reduced RDD[score] with Spark's StatCounter
(Metric.scala:60-67); here the per-query scores for one metric are
gathered into a NumPy vector and reduced on host. The expensive part of
evaluation — batch prediction — already ran on the mesh inside
``Engine.eval``; metric reduction is a scalar fold over a few thousand
floats, which belongs on host (a device round-trip per metric would cost
more than the reduction).
"""

from __future__ import annotations

import abc
import math
from typing import Any, Generic, Sequence, TypeVar

import numpy as np

from predictionio_tpu.controller.base import A, EI, P, Q

R = TypeVar("R")

#: An evaluation data set: per-fold evaluation info + (query, prediction,
#: actual) triples — what Engine.eval returns for one EngineParams.
EvalDataSet = Sequence[tuple[EI, Sequence[tuple[Q, P, A]]]]


class Metric(Generic[EI, Q, P, A, R], abc.ABC):
    """Parity: Metric (Metric.scala:39-57)."""

    @abc.abstractmethod
    def calculate(self, eval_data_set: EvalDataSet) -> R:
        """Score the whole evaluation data set."""

    def compare(self, r0: R, r1: R) -> int:
        """Default ordering: larger is better (Metric.scala:48-56).
        NaN (an empty grid point's Average/Stdev score) always loses, so
        it can never be selected as best."""
        r0_nan = isinstance(r0, float) and math.isnan(r0)
        r1_nan = isinstance(r1, float) and math.isnan(r1)
        if r0_nan or r1_nan:
            return 0 if r0_nan == r1_nan else (-1 if r0_nan else 1)
        if r0 == r1:
            return 0
        return -1 if r0 < r1 else 1

    @property
    def header(self) -> str:
        """Column label in evaluator reports (Metric.scala:44)."""
        return type(self).__name__


def _scores(metric: "QPAMetric", eval_data_set: EvalDataSet) -> np.ndarray:
    """All per-query scores across folds as one float vector — the
    host-side analogue of the reference's RDD union (Metric.scala:62-67)."""
    vals = [
        metric.calculate_qpa(q, p, a)
        for _, qpa in eval_data_set
        for q, p, a in qpa
    ]
    return np.asarray(vals, dtype=np.float64)


def _option_scores(metric: "QPAMetric", eval_data_set: EvalDataSet) -> np.ndarray:
    """Scores with None dropped (Option semantics, Metric.scala:124-149)."""
    vals = [
        s
        for _, qpa in eval_data_set
        for q, p, a in qpa
        if (s := metric.calculate_qpa(q, p, a)) is not None
    ]
    return np.asarray(vals, dtype=np.float64)


class QPAMetric(Metric[EI, Q, P, A, float], abc.ABC):
    """A metric defined per (query, prediction, actual) triple.
    Parity: QPAMetric (Metric.scala:259-269)."""

    @abc.abstractmethod
    def calculate_qpa(self, q: Q, p: P, a: A) -> float | None:
        """Score one query. May return None for Option* subclasses."""

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        raise NotImplementedError


class AverageMetric(QPAMetric[EI, Q, P, A]):
    """Mean of per-query scores. Parity: AverageMetric (Metric.scala:99-122)."""

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        s = _scores(self, eval_data_set)
        return float(s.mean()) if s.size else math.nan


class OptionAverageMetric(QPAMetric[EI, Q, P, A]):
    """Mean of non-None scores. Parity: OptionAverageMetric
    (Metric.scala:124-149)."""

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        s = _option_scores(self, eval_data_set)
        return float(s.mean()) if s.size else math.nan


class StdevMetric(QPAMetric[EI, Q, P, A]):
    """Population stdev of scores. Parity: StdevMetric (Metric.scala:151-177);
    Spark StatCounter.stdev is the population form."""

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        s = _scores(self, eval_data_set)
        return float(s.std()) if s.size else math.nan


class OptionStdevMetric(QPAMetric[EI, Q, P, A]):
    """Population stdev of non-None scores. Parity: Metric.scala:179-203."""

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        s = _option_scores(self, eval_data_set)
        return float(s.std()) if s.size else math.nan


class SumMetric(QPAMetric[EI, Q, P, A]):
    """Sum of scores. Parity: SumMetric (Metric.scala:205-232)."""

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        s = _scores(self, eval_data_set)
        return float(s.sum())


class ZeroMetric(Metric[EI, Q, P, A, float]):
    """Always 0 — placeholder for required metric slots.
    Parity: ZeroMetric (Metric.scala:234-246)."""

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        return 0.0
