"""Parameter types for DASE components.

Parity: core/src/main/scala/.../controller/{Params.scala:26-37,
EngineParams.scala:33-148}. Params classes are plain dataclasses; the
JSON in engine.json binds to them by field name (the single-codec
replacement for the reference's json4s/Gson JsonExtractor duality).
"""

from __future__ import annotations

import dataclasses
import keyword
import re
from typing import Any, Sequence, Type, TypeVar

P = TypeVar("P")


@dataclasses.dataclass(frozen=True)
class Params:
    """Marker base for component parameter classes (Params.scala:26-32).
    Subclasses are frozen dataclasses."""


@dataclasses.dataclass(frozen=True)
class EmptyParams(Params):
    """Parity: EmptyParams (Params.scala:35-37)."""


def _snake(name: str) -> str:
    """camelCase -> snake_case; appends "_" when the result is a Python
    keyword ("lambda" -> "lambda_", matching the reference templates'
    ALS params)."""
    out = re.sub(r"(?<=[a-z0-9])([A-Z])", r"_\1", name).lower()
    return out + "_" if keyword.iskeyword(out) else out


def params_from_json(params_class: Type[P], obj: dict[str, Any] | None) -> P:
    """Bind a JSON object to a Params dataclass by field name.

    Reference engine.json files use camelCase keys ("numIterations",
    "appName", "lambda"); fields here are snake_case — camelCase keys
    bind through a snake_case conversion so existing variant files work
    unchanged. Genuinely unknown keys are rejected (catching typos in
    engine.json — the reference got this from json4s strict extraction);
    missing keys fall back to dataclass defaults.
    """
    obj = obj or {}
    if not dataclasses.is_dataclass(params_class):
        raise TypeError(f"{params_class} must be a dataclass")
    field_names = {f.name for f in dataclasses.fields(params_class)}
    renamed = {}
    for k, v in obj.items():
        key = k if k in field_names else _snake(k)
        if key in renamed:
            raise ValueError(
                f"Duplicate parameter {key!r} for {params_class.__name__} "
                f"(camelCase and snake_case forms both present)"
            )
        renamed[key] = v
    obj = renamed
    unknown = set(obj) - field_names
    if unknown:
        raise ValueError(
            f"Unknown parameter(s) {sorted(unknown)} for {params_class.__name__} "
            f"(accepted: {sorted(field_names)})"
        )
    kwargs = {}
    for f in dataclasses.fields(params_class):
        if f.name in obj:
            v = obj[f.name]
            # JSON arrays bind to tuple-typed fields as tuples
            if isinstance(v, list):
                ann = str(f.type)
                if ann.startswith(("tuple", "Tuple", "typing.Tuple")) or "Sequence" in ann:
                    v = tuple(v)
            kwargs[f.name] = v
    return params_class(**kwargs)


def params_to_json(params: Any) -> dict[str, Any]:
    if params is None:
        return {}
    if dataclasses.is_dataclass(params):
        return dataclasses.asdict(params)
    if isinstance(params, dict):
        return dict(params)
    raise TypeError(f"cannot serialize params of type {type(params)}")


@dataclasses.dataclass(frozen=True)
class EngineParams:
    """The full parameter set of one engine variant: (name, params) per
    component slot, algorithm list ordered.

    Parity: EngineParams (EngineParams.scala:33-108).
    """

    data_source_params: tuple[str, Any] = ("", EmptyParams())
    preparator_params: tuple[str, Any] = ("", EmptyParams())
    algorithm_params_list: Sequence[tuple[str, Any]] = ()
    serving_params: tuple[str, Any] = ("", EmptyParams())

    def __post_init__(self):
        object.__setattr__(
            self, "algorithm_params_list", tuple(self.algorithm_params_list)
        )

    @staticmethod
    def of(
        data_source: Any = None,
        preparator: Any = None,
        algorithms: Sequence[tuple[str, Any]] = (),
        serving: Any = None,
    ) -> "EngineParams":
        """Convenience constructor for single-name engines."""
        return EngineParams(
            data_source_params=("", data_source if data_source is not None else EmptyParams()),
            preparator_params=("", preparator if preparator is not None else EmptyParams()),
            algorithm_params_list=tuple(algorithms),
            serving_params=("", serving if serving is not None else EmptyParams()),
        )
