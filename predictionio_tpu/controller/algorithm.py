"""Algorithm mesh-placement taxonomy — the TPU re-expression of the
reference's P / P2L / L algorithm classes.

Parity mapping (SURVEY.md §2.6 "load-bearing abstraction"):

- ``LocalAlgorithm``    ≙ LAlgorithm (LAlgorithm.scala:45-133): trains and
  predicts entirely on host (NumPy); model is host memory.
- ``HostModelAlgorithm`` ≙ P2LAlgorithm (P2LAlgorithm.scala:46-124):
  training runs jitted over the device mesh, the finished model is pulled
  to host (replicated) — serving needs no mesh.
- ``ShardedAlgorithm``  ≙ PAlgorithm (PAlgorithm.scala:47-129): the model
  *stays* as mesh-sharded jax.Arrays in HBM (e.g. ALS factor tables under
  NamedSharding). Batch predict must be implemented sharded, and models
  are persisted via sharded checkpoints or retrained on deploy — the same
  constraint the reference had for RDD models, solved better here
  (SURVEY.md §7 hard-parts: orbax sharded checkpoints avoid the forced
  retrain).
"""

from __future__ import annotations

import abc
from typing import TYPE_CHECKING, Sequence

from predictionio_tpu.controller.base import M, P, PD, Q, Algorithm

if TYPE_CHECKING:
    from predictionio_tpu.workflow.context import EngineContext


class LocalAlgorithm(Algorithm[PD, M, Q, P], abc.ABC):
    """Host-only algorithm; never touches the mesh."""

    placement = "local"


class HostModelAlgorithm(Algorithm[PD, M, Q, P], abc.ABC):
    """Mesh-trained, host-held model.

    ``train`` may use ``ctx.mesh`` freely; the returned model must be
    host-transferable (the workflow calls ``gather_model`` after training,
    mirroring P2LAlgorithm's implicit collect at P2LAlgorithm.scala:56-69).
    """

    placement = "host_model"

    def gather_model(self, ctx: "EngineContext", model: M) -> M:
        """Pull device arrays to host numpy, including inside plain
        dataclass models (which jax treats as opaque pytree leaves)."""
        from predictionio_tpu.workflow.persistence import _to_host

        return _to_host(model)


class ShardedAlgorithm(Algorithm[PD, M, Q, P], abc.ABC):
    """Model lives sharded on the mesh between training and serving.

    Contract differences, mirroring PAlgorithm:
    - ``batch_predict`` MUST be overridden with a sharded implementation
      (PAlgorithm.batchPredict "must be implemented", PAlgorithm.scala:72).
    - Models are not auto-pickled; implement ``make_persistent_model`` /
      ``load_model`` (sharded checkpoint) or return None to retrain on
      deploy (PAlgorithm.scala:89-125).
    """

    placement = "sharded"

    def batch_predict(self, model: M, queries: Sequence[tuple[int, Q]]) -> Sequence[tuple[int, P]]:
        raise NotImplementedError(
            f"{type(self).__name__} is a ShardedAlgorithm and must override "
            "batch_predict with a mesh-sharded implementation"
        )

    def make_persistent_model(self, ctx: "EngineContext", model: M):
        """Default for sharded models: do not persist; retrain on deploy
        (reference parity). Algorithms with orbax checkpoints override."""
        return None
