"""The Engine: binds DASE component classes, runs train and eval pipelines.

Parity: core/src/main/scala/.../controller/Engine.scala:83-833 and
core/.../core/BaseEngine.scala:38-101. An ``Engine`` holds name->class
maps for DataSource/Preparator/Algorithm(s)/Serving; ``train`` runs
read -> sanity -> prepare -> sanity -> per-algorithm train -> sanity
(honoring stop-after-read/prepare, Engine.scala:643-692); ``eval`` trains
per evaluation split and aligns per-query predictions from all algorithms
before serving (Engine.scala:730-833).

The Spark driver/executor split disappears: the pipeline is one process
orchestrating host data prep and jitted mesh computation through the
EngineContext.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import TYPE_CHECKING, Any, Callable, Generic, Mapping, Sequence

from predictionio_tpu.controller.base import (
    A,
    EI,
    P,
    PD,
    Q,
    TD,
    Algorithm,
    DataSource,
    Doer,
    PersistentModelManifest,
    Preparator,
    SanityCheck,
    Serving,
)
from predictionio_tpu.controller.params import EngineParams, params_from_json

if TYPE_CHECKING:
    from predictionio_tpu.workflow.context import EngineContext

logger = logging.getLogger(__name__)


class StopAfterReadInterruption(Exception):
    """Parity: WorkflowUtils.StopAfterReadInterruption (WorkflowUtils.scala:390)."""


class StopAfterPrepareInterruption(Exception):
    """Parity: StopAfterPrepareInterruption (WorkflowUtils.scala:392)."""


def _sanity_check(obj: Any, name: str, enabled: bool) -> None:
    """Parity: Engine.scala:653-664 — run sanityCheck() on data classes
    that opt in."""
    if enabled and isinstance(obj, SanityCheck):
        logger.info("%s: running sanity check", name)
        obj.sanity_check()


@dataclasses.dataclass
class TrainResult:
    """Models plus what the workflow should persist for each."""

    models: list[Any]
    persisted: list[Any]  # per algo: model | PersistentModelManifest | None


class Engine(Generic[TD, EI, PD, Q, P, A]):
    """Parity: Engine (Engine.scala:83-151). Component maps are
    name -> class; EngineParams name selects the class per slot."""

    def __init__(
        self,
        data_source_class_map: Mapping[str, type] | type,
        preparator_class_map: Mapping[str, type] | type,
        algorithm_class_map: Mapping[str, type] | type,
        serving_class_map: Mapping[str, type] | type,
    ):
        self.data_source_class_map = self._as_map(data_source_class_map)
        self.preparator_class_map = self._as_map(preparator_class_map)
        self.algorithm_class_map = self._as_map(algorithm_class_map)
        self.serving_class_map = self._as_map(serving_class_map)

    @staticmethod
    def _as_map(m: Mapping[str, type] | type) -> dict[str, type]:
        """Single-class sugar: Engine(MyDS, MyPrep, MyAlgo, MyServing)
        (Engine.scala:120-151 single-class constructors)."""
        if isinstance(m, Mapping):
            return dict(m)
        return {"": m}

    # -- component instantiation -------------------------------------------
    def _component(self, class_map: Mapping[str, type], slot: str, name_params: tuple[str, Any]):
        name, params = name_params
        if name not in class_map:
            raise ValueError(
                f"{slot} has no component named {name!r} "
                f"(available: {sorted(class_map)})"
            )
        return Doer.create(class_map[name], params)

    def make_components(self, engine_params: EngineParams) -> tuple[
        DataSource, Preparator, list[Algorithm], Serving
    ]:
        data_source = self._component(
            self.data_source_class_map, "datasource", engine_params.data_source_params
        )
        preparator = self._component(
            self.preparator_class_map, "preparator", engine_params.preparator_params
        )
        algo_list = list(engine_params.algorithm_params_list) or [("", None)]
        algorithms = [
            self._component(self.algorithm_class_map, "algorithms", ap)
            for ap in algo_list
        ]
        serving = self._component(
            self.serving_class_map, "serving", engine_params.serving_params
        )
        return data_source, preparator, algorithms, serving

    # -- training pipeline (object Engine.train, Engine.scala:625-728) ------
    def train(
        self,
        ctx: "EngineContext",
        engine_params: EngineParams,
        algorithms: Sequence[Any] | None = None,
    ) -> TrainResult:
        """``algorithms`` lets deploy-time retrain train the SAME
        instances that will serve (see prepare_deploy) — train hooks
        stash serve-time state on the instance just like load_model
        hooks do."""
        # per-DASE-stage spans (obs/trace.py): when the driver bound an
        # ambient trace (workflow/train.run_train always does), read /
        # prepare / train land as spans and `pio train` prints the
        # stage breakdown; with no trace active, span() is a shared
        # no-op — direct Engine.train callers pay one contextvar read
        from predictionio_tpu.obs.trace import span

        params = ctx.workflow_params
        data_source, preparator, made_algorithms, _ = \
            self.make_components(engine_params)
        if algorithms is None:
            algorithms = made_algorithms

        with span("read"):
            td = data_source.read_training(ctx)
        _sanity_check(td, "training data", not params.skip_sanity_check)
        if params.stop_after_read:
            raise StopAfterReadInterruption("stopping after read per workflow params")

        with span("prepare"):
            pd = preparator.prepare(ctx, td)
        _sanity_check(pd, "prepared data", not params.skip_sanity_check)
        if params.stop_after_prepare:
            raise StopAfterPrepareInterruption("stopping after prepare per workflow params")

        models: list[Any] = []
        for i, algo in enumerate(algorithms):
            logger.info("training algorithm %d: %s", i, type(algo).__name__)
            with span("train"):
                model = algo.train(ctx, pd)
                _sanity_check(model, f"model[{i}]",
                              not params.skip_sanity_check)
                if hasattr(algo, "gather_model"):
                    model = algo.gather_model(ctx, model)
            models.append(model)

        persisted = [
            algo.make_persistent_model(ctx.with_workflow_params(algorithm_slot=i), model)
            if params.save_model else None
            for i, (algo, model) in enumerate(zip(algorithms, models))
        ]
        return TrainResult(models=models, persisted=persisted)

    # -- deploy-time model restoration (Engine.prepareDeploy, :199-257) -----
    def prepare_deploy(
        self,
        ctx: "EngineContext",
        engine_params: EngineParams,
        persisted: Sequence[Any],
        algorithms: Sequence[Any] | None = None,
    ) -> list[Any]:
        """Restore deployable models. ``algorithms`` MUST be the same
        instances that will later serve the models when an algorithm
        keeps deploy-time state — ``load_model`` hooks commonly stash
        the context for serve-time live reads (e.g. the ecommerce
        template's unavailableItems/weight constraints), and loading on
        one instance while serving with another silently drops that
        state (caught by the round-3 CLI end-to-end drive)."""
        if algorithms is None:
            _, _, algorithms, _ = self.make_components(engine_params)
        models: list[Any] = []
        retrain_needed = any(p is None for p in persisted)
        retrained: TrainResult | None = None
        if retrain_needed:
            # "Unit model -> retrain on deploy" (Engine.scala:211-229).
            # save_model=False: deploy-time retrain must not redo (or
            # overwrite) persistence work.
            logger.info("some models were not persisted; retraining for deploy")
            # retrain on the SERVING instances, not throwaway ones —
            # train hooks stash serve-time state exactly like
            # load_model hooks (same bug class as the docstring above)
            retrained = self.train(
                ctx.with_workflow_params(save_model=False), engine_params,
                algorithms=algorithms,
            )
        for i, (algo, blob) in enumerate(zip(algorithms, persisted)):
            if blob is None:
                models.append(retrained.models[i])
            elif isinstance(blob, PersistentModelManifest):
                # custom-persistence reload (Engine.scala:242-251)
                models.append(algo.load_model(ctx, blob))
            else:
                models.append(blob)
        return models

    # -- evaluation pipeline (object Engine.eval, Engine.scala:730-833) -----
    def eval(
        self,
        ctx: "EngineContext",
        engine_params: EngineParams,
    ) -> list[tuple[EI, list[tuple[Q, P, A]]]]:
        data_source, preparator, algorithms, serving = self.make_components(engine_params)
        eval_splits = data_source.read_eval(ctx)
        results: list[tuple[EI, list[tuple[Q, P, A]]]] = []
        for fold, (td, ei, qa_pairs) in enumerate(eval_splits):
            logger.info("evaluating fold %d (%d queries)", fold, len(qa_pairs))
            _sanity_check(td, f"fold[{fold}] training data",
                          not ctx.workflow_params.skip_sanity_check)
            pd = preparator.prepare(ctx, td)
            models = [algo.train(ctx, pd) for algo in algorithms]

            supplemented = [
                (i, serving.supplement(q)) for i, (q, _) in enumerate(qa_pairs)
            ]
            # per-algo batch predict, aligned by dense query index — the
            # union+groupByKey of Engine.scala:783-799 becomes list indexing
            per_algo: list[dict[int, P]] = []
            for algo, model in zip(algorithms, models):
                preds = dict(algo.batch_predict(model, supplemented))
                per_algo.append(preds)
            fold_results: list[tuple[Q, P, A]] = []
            for i, (q, a) in enumerate(qa_pairs):
                predictions = [preds[i] for preds in per_algo if i in preds]
                served = serving.serve(q, predictions)
                fold_results.append((q, served, a))
            results.append((ei, fold_results))
        return results

    def batch_eval(
        self,
        ctx: "EngineContext",
        engine_params_list: Sequence[EngineParams],
    ) -> list[tuple[EngineParams, list[tuple[EI, list[tuple[Q, P, A]]]]]]:
        """Parity: BaseEngine.batchEval default (BaseEngine.scala:82-94)."""
        return [(ep, self.eval(ctx, ep)) for ep in engine_params_list]

    # -- engine.json binding (Engine.jValueToEngineParams, :357-420) --------
    def params_from_variant_json(self, variant: Mapping[str, Any]) -> EngineParams:
        def slot(key: str, class_map: Mapping[str, type]) -> tuple[str, Any]:
            spec = variant.get(key)
            if spec is None:
                # omitted slot: unambiguous only for single-component maps
                if "" in class_map:
                    name = ""
                elif len(class_map) == 1:
                    name = next(iter(class_map))
                else:
                    raise ValueError(
                        f"engine.json omits {key!r} but the engine has multiple "
                        f"{key} components {sorted(class_map)}; specify one by name"
                    )
                cls = class_map.get(name)
                default = params_from_json(cls.params_class, None) if cls else None
                return (name, default)
            name = spec.get("name", "")
            if name not in class_map:
                raise ValueError(
                    f"engine.json {key} names unknown component {name!r} "
                    f"(available: {sorted(class_map)})"
                )
            cls = class_map[name]
            return (name, params_from_json(cls.params_class, spec.get("params")))

        algorithms = []
        for spec in variant.get("algorithms", []):
            name = spec.get("name", "")
            if name not in self.algorithm_class_map:
                raise ValueError(
                    f"engine.json algorithms names unknown component {name!r} "
                    f"(available: {sorted(self.algorithm_class_map)})"
                )
            cls = self.algorithm_class_map[name]
            algorithms.append((name, params_from_json(cls.params_class, spec.get("params"))))
        if not algorithms:
            if "" in self.algorithm_class_map:
                name = ""
            elif len(self.algorithm_class_map) == 1:
                name = next(iter(self.algorithm_class_map))
            else:
                raise ValueError(
                    "engine.json omits 'algorithms' but the engine has multiple "
                    f"algorithm components {sorted(self.algorithm_class_map)}; "
                    "specify at least one by name"
                )
            cls = self.algorithm_class_map[name]
            algorithms = [(name, params_from_json(cls.params_class, None))]

        return EngineParams(
            data_source_params=slot("datasource", self.data_source_class_map),
            preparator_params=slot("preparator", self.preparator_class_map),
            algorithm_params_list=tuple(algorithms),
            serving_params=slot("serving", self.serving_class_map),
        )


    def params_from_instance_json(
        self,
        data_source_params: str,
        preparator_params: str,
        algorithms_params: str,
        serving_params: str,
    ) -> EngineParams:
        """Rebuild typed EngineParams from the JSON blobs stored on an
        EngineInstance row. Parity: Engine.engineInstanceToEngineParams
        (Engine.scala:422-514)."""
        import json

        def slot(raw: str, class_map: Mapping[str, type]) -> tuple[str, Any]:
            spec = json.loads(raw) if raw else {"name": "", "params": {}}
            name = spec.get("name", "")
            cls = class_map.get(name)
            if cls is None:
                raise ValueError(f"stored params name {name!r} not in {sorted(class_map)}")
            return (name, params_from_json(cls.params_class, spec.get("params")))

        algo_specs = json.loads(algorithms_params) if algorithms_params else []
        algorithms = []
        for spec in algo_specs:
            name = spec.get("name", "")
            cls = self.algorithm_class_map.get(name)
            if cls is None:
                raise ValueError(
                    f"stored algorithm name {name!r} not in {sorted(self.algorithm_class_map)}"
                )
            algorithms.append((name, params_from_json(cls.params_class, spec.get("params"))))
        return EngineParams(
            data_source_params=slot(data_source_params, self.data_source_class_map),
            preparator_params=slot(preparator_params, self.preparator_class_map),
            algorithm_params_list=tuple(algorithms),
            serving_params=slot(serving_params, self.serving_class_map),
        )


class EngineFactory:
    """Parity: EngineFactory (controller/EngineFactory.scala:31-40).
    Subclass and implement ``apply``; or pass any zero-arg callable
    returning an Engine."""

    def apply(self) -> Engine:
        raise NotImplementedError


def resolve_engine_factory(spec: str) -> Callable[[], Engine]:
    """Resolve an engineFactory string "pkg.module.obj" / "pkg.module:obj"
    to a zero-arg callable returning an Engine.

    Parity: WorkflowUtils.getEngine (WorkflowUtils.scala:53-90), which
    tried object-then-class reflection; here importlib + attribute lookup.
    """
    from predictionio_tpu.utils.reflection import resolve_attr

    obj = resolve_attr(spec)
    if isinstance(obj, Engine):
        return lambda: obj
    if isinstance(obj, type) and issubclass(obj, EngineFactory):
        return lambda: obj().apply()
    if callable(obj):
        return obj
    raise TypeError(f"engineFactory {spec!r} is not callable or an Engine")
