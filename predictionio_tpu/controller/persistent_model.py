"""Custom model-persistence contract + local-filesystem helper.

Parity: core/src/main/scala/.../controller/{PersistentModel.scala:68-115,
LocalFileSystemPersistentModel.scala:43-77}. A model implementing
``PersistentModel`` owns its persistence: ``save`` stores the real
artifact and the workflow records only a ``PersistentModelManifest``;
at deploy the companion ``load`` restores it. Algorithms get this
behavior automatically via ``PersistentModelAlgorithmMixin``.

TPU note: this is the escape hatch for models that should NOT go through
the pickle blob path — e.g. large sharded factor tables checkpointed
per-shard (the templates' ALSModel.save directory checkpoints follow the
same pattern).
"""

from __future__ import annotations

import abc
import logging
import os
import pickle
from typing import Any, TYPE_CHECKING

from predictionio_tpu.controller.base import PersistentModelManifest

if TYPE_CHECKING:
    from predictionio_tpu.workflow.context import EngineContext

logger = logging.getLogger(__name__)


def model_base_dir() -> str:
    """Where local model artifacts live: $PIO_MODEL_DIR or
    $PIO_FS_BASEDIR/models or ~/.pio_store/models."""
    if os.environ.get("PIO_MODEL_DIR"):
        return os.environ["PIO_MODEL_DIR"]
    base = os.environ.get(
        "PIO_FS_BASEDIR", os.path.join(os.path.expanduser("~"), ".pio_store")
    )
    return os.path.join(base, "models")


def checkpoint_location(ctx: "EngineContext", prefix: str) -> str:
    """The canonical directory for a template's model checkpoint:
    ``<model_base_dir>/<prefix>_<run>_a<slot>`` — keyed by training run
    and algorithm slot so multi-algorithm engines and successive runs
    never collide."""
    import uuid

    run_id = ctx.workflow_params.engine_instance_id or uuid.uuid4().hex
    return os.path.join(
        model_base_dir(),
        f"{prefix}_{run_id}_a{ctx.workflow_params.algorithm_slot}",
    )


class PersistentModel(abc.ABC):
    """Parity: PersistentModel trait (PersistentModel.scala:68-96).
    ``save`` returns True when it stored the model itself (the workflow
    then persists only a manifest); False falls back to the automatic
    pickle path."""

    @abc.abstractmethod
    def save(self, instance_id: str, params: Any) -> bool: ...

    @classmethod
    @abc.abstractmethod
    def load(cls, instance_id: str, params: Any) -> "PersistentModel":
        """Parity: PersistentModelLoader.apply (PersistentModel.scala:98-115)."""


class LocalFileSystemPersistentModel(PersistentModel):
    """Pickles the model to ``<model_base_dir>/<instance_id>``.
    Parity: LocalFileSystemPersistentModel(+Loader)
    (LocalFileSystemPersistentModel.scala:43-77)."""

    def save(self, instance_id: str, params: Any) -> bool:
        path = os.path.join(model_base_dir(), instance_id)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            pickle.dump(self, f, protocol=pickle.HIGHEST_PROTOCOL)
        logger.info("saved persistent model to %s", path)
        return True

    @classmethod
    def load(cls, instance_id: str, params: Any) -> "LocalFileSystemPersistentModel":
        path = os.path.join(model_base_dir(), instance_id)
        with open(path, "rb") as f:
            return pickle.load(f)


class PersistentModelAlgorithmMixin:
    """Mixin for Algorithms whose models implement PersistentModel: wires
    make_persistent_model/load_model to the model's own save/load
    (the reference did this via makePersistentModel reflection,
    BaseAlgorithm.scala:111-126 + WorkflowUtils.getPersistentModel)."""

    def make_persistent_model(self, ctx: "EngineContext", model: Any) -> Any:
        if isinstance(model, PersistentModel):
            import uuid

            run_id = ctx.workflow_params.engine_instance_id or uuid.uuid4().hex
            # slot suffix: multi-algorithm engines must not share locations
            location = f"{run_id}_a{ctx.workflow_params.algorithm_slot}"
            if model.save(location, getattr(self, "params", None)):
                return PersistentModelManifest(
                    class_name=(
                        f"{type(model).__module__}.{type(model).__qualname__}"
                    ),
                    location=location,
                )
        return model

    def load_model(self, ctx: "EngineContext", manifest: PersistentModelManifest) -> Any:
        from predictionio_tpu.utils.reflection import resolve_attr

        model_cls = resolve_attr(manifest.class_name)
        return model_cls.load(manifest.location, getattr(self, "params", None))
