"""DASE controller API.

Reference: core/src/main/scala/.../controller/ and core/.../core/.
"""

from predictionio_tpu.controller.algorithm import (
    HostModelAlgorithm,
    LocalAlgorithm,
    ShardedAlgorithm,
)
from predictionio_tpu.controller.base import (
    Algorithm,
    AverageServing,
    DataSource,
    Doer,
    Evaluator,
    FirstServing,
    IdentityPreparator,
    PersistentModelManifest,
    Preparator,
    SanityCheck,
    Serving,
)
from predictionio_tpu.controller.engine import (
    Engine,
    EngineFactory,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
    TrainResult,
    resolve_engine_factory,
)
from predictionio_tpu.controller.evaluation import (
    BaseEvaluator,
    BaseEvaluatorResult,
    EngineParamsGenerator,
    Evaluation,
    MetricEvaluator,
    MetricEvaluatorResult,
    MetricScores,
)
from predictionio_tpu.controller.fast_eval import FastEvalEngine
from predictionio_tpu.controller.metrics import (
    AverageMetric,
    Metric,
    OptionAverageMetric,
    OptionStdevMetric,
    QPAMetric,
    StdevMetric,
    SumMetric,
    ZeroMetric,
)
from predictionio_tpu.controller.params import (
    EmptyParams,
    EngineParams,
    Params,
    params_from_json,
    params_to_json,
)

__all__ = [
    "Algorithm", "AverageServing", "DataSource", "Doer", "Evaluator",
    "FirstServing", "IdentityPreparator", "PersistentModelManifest",
    "Preparator", "SanityCheck", "Serving",
    "HostModelAlgorithm", "LocalAlgorithm", "ShardedAlgorithm",
    "Engine", "EngineFactory", "StopAfterPrepareInterruption",
    "StopAfterReadInterruption", "TrainResult", "resolve_engine_factory",
    "EmptyParams", "EngineParams", "Params", "params_from_json", "params_to_json",
    "Metric", "QPAMetric", "AverageMetric", "OptionAverageMetric",
    "StdevMetric", "OptionStdevMetric", "SumMetric", "ZeroMetric",
    "BaseEvaluator", "BaseEvaluatorResult", "Evaluation",
    "EngineParamsGenerator", "MetricEvaluator", "MetricEvaluatorResult",
    "MetricScores", "FastEvalEngine",
]
