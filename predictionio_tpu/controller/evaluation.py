"""Evaluation & hyperparameter tuning: Evaluation, EngineParamsGenerator,
MetricEvaluator.

Parity: core/src/main/scala/.../controller/{Evaluation.scala:32-125,
EngineParamsGenerator.scala:30-46, MetricEvaluator.scala:41-263}. An
``Evaluation`` binds an Engine to an evaluator (usually a
``MetricEvaluator`` over one primary + N secondary metrics); an
``EngineParamsGenerator`` supplies the grid of EngineParams to search;
the evaluator scores every grid point and tracks the best.
"""

from __future__ import annotations

import abc
import dataclasses
import json
import logging
import os
from typing import Any, Generic, Sequence, TYPE_CHECKING

from predictionio_tpu.controller.base import A, EI, P, Q
from predictionio_tpu.controller.metrics import EvalDataSet, Metric
from predictionio_tpu.controller.params import EngineParams, params_to_json

if TYPE_CHECKING:
    from predictionio_tpu.controller.engine import Engine
    from predictionio_tpu.workflow.context import EngineContext

logger = logging.getLogger(__name__)


class BaseEvaluatorResult(abc.ABC):
    """Parity: BaseEvaluatorResult (core/BaseEvaluator.scala:52-75)."""

    #: When True the workflow skips persisting renders (noSave mode).
    no_save: bool = False

    def to_one_liner(self) -> str:
        return ""

    def to_json(self) -> str:
        return ""

    def to_html(self) -> str:
        return ""


class BaseEvaluator(abc.ABC, Generic[EI, Q, P, A]):
    """Parity: BaseEvaluator (core/BaseEvaluator.scala:39-50)."""

    @abc.abstractmethod
    def evaluate(
        self,
        ctx: "EngineContext",
        evaluation: "Evaluation",
        engine_eval_data_set: Sequence[tuple[EngineParams, EvalDataSet]],
    ) -> BaseEvaluatorResult:
        ...


@dataclasses.dataclass
class MetricScores:
    """Scores for one grid point. Parity: MetricScores
    (MetricEvaluator.scala:47-53)."""

    score: Any
    other_scores: list[Any]


@dataclasses.dataclass
class MetricEvaluatorResult(BaseEvaluatorResult):
    """Parity: MetricEvaluatorResult (MetricEvaluator.scala:55-110)."""

    best_score: MetricScores
    best_engine_params: EngineParams
    best_idx: int
    metric_header: str
    other_metric_headers: list[str]
    engine_params_scores: list[tuple[EngineParams, MetricScores]]
    output_path: str | None = None

    def to_one_liner(self) -> str:
        best = self.engine_params_scores[self.best_idx][1]
        return f"[{best.score}] {_engine_params_oneline(self.best_engine_params)}"

    def to_json(self) -> str:
        return json.dumps(
            {
                "metricHeader": self.metric_header,
                "otherMetricHeaders": self.other_metric_headers,
                "bestIdx": self.best_idx,
                "bestScore": self.best_score.score,
                "bestEngineParams": _engine_params_json(self.best_engine_params),
                "engineParamsScores": [
                    {
                        "engineParams": _engine_params_json(ep),
                        "score": ms.score,
                        "otherScores": ms.other_scores,
                    }
                    for ep, ms in self.engine_params_scores
                ],
            },
            indent=2,
        )

    def to_html(self) -> str:
        # the metric_evaluator.scala.html twirl render, minimally
        rows = "\n".join(
            "<tr><td>{}</td><td>{}</td><td><pre>{}</pre></td></tr>".format(
                ms.score,
                " ".join(str(s) for s in ms.other_scores),
                json.dumps(_engine_params_json(ep), indent=2),
            )
            for ep, ms in self.engine_params_scores
        )
        return (
            "<h2>Metric: {}</h2><p>Best score: {} (grid point {})</p>"
            "<table border=1><tr><th>{}</th><th>{}</th><th>EngineParams</th></tr>{}</table>"
        ).format(
            self.metric_header,
            self.best_score.score,
            self.best_idx,
            self.metric_header,
            " ".join(self.other_metric_headers),
            rows,
        )


def _engine_params_json(ep: EngineParams) -> dict[str, Any]:
    return {
        "dataSourceParams": {
            "name": ep.data_source_params[0],
            "params": params_to_json(ep.data_source_params[1]),
        },
        "preparatorParams": {
            "name": ep.preparator_params[0],
            "params": params_to_json(ep.preparator_params[1]),
        },
        "algorithmParamsList": [
            {"name": n, "params": params_to_json(p)}
            for n, p in ep.algorithm_params_list
        ],
        "servingParams": {
            "name": ep.serving_params[0],
            "params": params_to_json(ep.serving_params[1]),
        },
    }


def _engine_params_oneline(ep: EngineParams) -> str:
    return json.dumps(_engine_params_json(ep), separators=(",", ":"))


class MetricEvaluator(BaseEvaluator[EI, Q, P, A]):
    """Scores every grid point with a primary metric (+ optional secondary
    metrics), tracks the best by ``metric.compare``, and optionally writes
    ``best.json`` to ``output_path``.

    Parity: MetricEvaluator (MetricEvaluator.scala:112-263; best tracking
    :185-191, saveEngineJson/best.json :193-216).
    """

    def __init__(
        self,
        metric: Metric,
        other_metrics: Sequence[Metric] = (),
        output_path: str | None = None,
    ):
        self.metric = metric
        self.other_metrics = list(other_metrics)
        self.output_path = output_path

    def evaluate(
        self,
        ctx: "EngineContext",
        evaluation: "Evaluation",
        engine_eval_data_set: Sequence[tuple[EngineParams, EvalDataSet]],
    ) -> MetricEvaluatorResult:
        scores: list[tuple[EngineParams, MetricScores]] = []
        best_idx = -1
        for idx, (engine_params, eval_data) in enumerate(engine_eval_data_set):
            ms = MetricScores(
                score=self.metric.calculate(eval_data),
                other_scores=[m.calculate(eval_data) for m in self.other_metrics],
            )
            scores.append((engine_params, ms))
            logger.info("grid point %d: %s = %s", idx, self.metric.header, ms.score)
            if best_idx < 0 or self.metric.compare(ms.score, scores[best_idx][1].score) > 0:
                best_idx = idx
        if best_idx < 0:
            raise ValueError("MetricEvaluator.evaluate got an empty grid")

        best_params, best_score = scores[best_idx]
        result = MetricEvaluatorResult(
            best_score=best_score,
            best_engine_params=best_params,
            best_idx=best_idx,
            metric_header=self.metric.header,
            other_metric_headers=[m.header for m in self.other_metrics],
            engine_params_scores=scores,
            output_path=self.output_path,
        )
        if self.output_path:
            self._save_best_json(evaluation, best_params)
        return result

    def _save_best_json(self, evaluation: "Evaluation", ep: EngineParams) -> None:
        """Write best.json usable as an engine.json variant
        (MetricEvaluator.saveEngineJson, :193-216)."""
        payload = _engine_params_json(ep)
        payload["evaluation"] = type(evaluation).__name__
        os.makedirs(os.path.dirname(self.output_path) or ".", exist_ok=True)
        with open(self.output_path, "w") as f:
            json.dump(payload, f, indent=2)
        logger.info("wrote best engine params to %s", self.output_path)


class Evaluation:
    """Binds an engine to its evaluator. Set either ``engine_metric``
    (primary only), ``engine_metrics`` (primary + others), or
    ``engine_evaluator`` (custom BaseEvaluator).

    Parity: Evaluation (Evaluation.scala:32-125; engineMetric_= wraps the
    metric into a MetricEvaluator :88-99).
    """

    def __init__(self):
        self._engine: "Engine" | None = None
        self._evaluator: BaseEvaluator | None = None

    # -- binding styles ------------------------------------------------------
    @property
    def engine_metric(self) -> tuple["Engine", Metric]:
        raise NotImplementedError

    @engine_metric.setter
    def engine_metric(self, value: tuple["Engine", Metric]) -> None:
        engine, metric = value
        self._engine = engine
        self._evaluator = MetricEvaluator(metric, output_path="best.json")

    @property
    def engine_metrics(self) -> tuple["Engine", Metric, Sequence[Metric]]:
        raise NotImplementedError

    @engine_metrics.setter
    def engine_metrics(self, value: tuple["Engine", Metric, Sequence[Metric]]) -> None:
        engine, metric, others = value
        self._engine = engine
        self._evaluator = MetricEvaluator(metric, others, output_path="best.json")

    @property
    def engine_evaluator(self) -> tuple["Engine", BaseEvaluator]:
        if self._engine is None or self._evaluator is None:
            raise ValueError(
                f"{type(self).__name__} must set engine_metric, engine_metrics, "
                "or engine_evaluator in __init__"
            )
        return (self._engine, self._evaluator)

    @engine_evaluator.setter
    def engine_evaluator(self, value: tuple["Engine", BaseEvaluator]) -> None:
        self._engine, self._evaluator = value

    @property
    def engine(self) -> "Engine":
        return self.engine_evaluator[0]

    @property
    def evaluator(self) -> BaseEvaluator:
        return self.engine_evaluator[1]


class EngineParamsGenerator:
    """The grid of EngineParams an evaluation searches.
    Parity: EngineParamsGenerator (EngineParamsGenerator.scala:30-46)."""

    def __init__(self, engine_params_list: Sequence[EngineParams] = ()):
        self._engine_params_list: list[EngineParams] | None = (
            list(engine_params_list) if engine_params_list else None
        )

    @property
    def engine_params_list(self) -> list[EngineParams]:
        if self._engine_params_list is None:
            raise ValueError("engine_params_list is not set")
        return self._engine_params_list

    @engine_params_list.setter
    def engine_params_list(self, value: Sequence[EngineParams]) -> None:
        self._engine_params_list = list(value)
