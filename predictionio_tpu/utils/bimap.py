"""Immutable bidirectional maps and dense id indexing.

Parity with the reference BiMap / EntityIdIxMap
(reference: data/src/main/scala/.../data/storage/BiMap.scala:24-167,
EntityMap.scala:28-99) — the string-id → contiguous-dense-index primitive
every ALS template uses to turn entity ids into embedding-table rows.

TPU relevance: dense contiguous indices are what make factor tables plain
``jax.Array`` rows that can be sharded across a mesh with NamedSharding;
this is the host-side boundary where ragged external ids become static
tensor coordinates.
"""

from __future__ import annotations

from typing import Generic, Hashable, Iterable, Iterator, Mapping, TypeVar

import numpy as np

K = TypeVar("K", bound=Hashable)
V = TypeVar("V", bound=Hashable)


class BiMap(Generic[K, V]):
    """Immutable bidirectional map; values must be unique.

    Parity: BiMap.scala:24-110 (apply/get/getOrElse/contains/inverse/take/toMap).
    """

    __slots__ = ("_forward", "_inverse_cache")

    def __init__(self, forward: Mapping[K, V]):
        self._forward: dict[K, V] = dict(forward)
        if len(set(self._forward.values())) != len(self._forward):
            raise ValueError("BiMap values must be unique")
        self._inverse_cache: "BiMap[V, K] | None" = None

    def __getitem__(self, key: K) -> V:
        return self._forward[key]

    def get(self, key: K) -> V | None:
        return self._forward.get(key)

    def get_or_else(self, key: K, default: V) -> V:
        return self._forward.get(key, default)

    def __contains__(self, key: object) -> bool:
        return key in self._forward

    def __len__(self) -> int:
        return len(self._forward)

    def __iter__(self) -> Iterator[K]:
        return iter(self._forward)

    @property
    def inverse(self) -> "BiMap[V, K]":
        """Swapped-direction view (BiMap.scala:45-50); cached like the
        reference's lazy ``inverse``."""
        if self._inverse_cache is None:
            inv = BiMap({v: k for k, v in self._forward.items()})
            inv._inverse_cache = self
            self._inverse_cache = inv
        return self._inverse_cache

    def take(self, n: int) -> "BiMap[K, V]":
        return BiMap(dict(list(self._forward.items())[:n]))

    def to_dict(self) -> dict[K, V]:
        return dict(self._forward)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, BiMap):
            return self._forward == other._forward
        return NotImplemented

    def __hash__(self) -> int:
        return hash(frozenset(self._forward.items()))

    def __repr__(self) -> str:
        return f"BiMap({self._forward!r})"

    # -- constructors (BiMap.scala:112-167) --------------------------------
    @staticmethod
    def string_int(keys: Iterable[str]) -> "BiMap[str, int]":
        """Distinct keys -> contiguous [0, n) indices. Parity:
        BiMap.stringInt (BiMap.scala:125-133)."""
        return BiMap({k: i for i, k in enumerate(dict.fromkeys(keys))})

    # stringLong in the reference exists only because Scala distinguishes
    # Int/Long; Python ints are unbounded so string_long ≡ string_int.
    string_long = string_int


class EntityIdIxMap:
    """entityId <-> dense index with numpy-vectorized batch lookup.

    Parity: EntityIdIxMap (EntityMap.scala:28-58). ``to_index`` maps an
    array of string ids to int32 indices in one vectorized pass — the hot
    path when converting an event log into (user_ix, item_ix, rating)
    triples for the TPU.
    """

    def __init__(self, id_to_ix: BiMap[str, int]):
        self.id_to_ix = id_to_ix
        self._dict = id_to_ix.to_dict()  # cached once: to_index is a hot path

    @staticmethod
    def from_ids(ids: Iterable[str]) -> "EntityIdIxMap":
        return EntityIdIxMap(BiMap.string_int(ids))

    def __getitem__(self, entity_id: str) -> int:
        return self.id_to_ix[entity_id]

    def get(self, entity_id: str) -> int | None:
        return self.id_to_ix.get(entity_id)

    def __contains__(self, entity_id: str) -> bool:
        return entity_id in self.id_to_ix

    def __len__(self) -> int:
        return len(self.id_to_ix)

    @property
    def inverse(self) -> BiMap[int, str]:
        return self.id_to_ix.inverse

    def to_index(self, entity_ids: Iterable[str], missing: int = -1) -> np.ndarray:
        """Vectorized batch id -> index; unknown ids map to ``missing``."""
        d = self._dict
        return np.fromiter(
            (d.get(e, missing) for e in entity_ids), dtype=np.int32
        )

    def to_ids(self, indices: np.ndarray) -> list[str]:
        inv = self.inverse
        return [inv[int(i)] for i in indices]
