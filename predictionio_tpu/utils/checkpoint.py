"""Sharded model checkpointing via orbax — crash-safe.

The reference cannot auto-persist distributed models — a PAlgorithm's
RDD model forces either a custom PersistentModel or a full retrain at
deploy (reference: core/.../controller/PAlgorithm.scala:89-125,
Engine.scala:211-229). Here mesh-sharded ``jax.Array`` models save as
orbax checkpoints: each host writes only its own shards (OCDBT), and
restore places shards straight back onto the target mesh — no
gather-to-host, no retrain-on-deploy, which is the SURVEY.md §7
"better than the reference" contract for sharded model persistence.

A plain-numpy fallback (the ``npz`` backend) keeps the same directory
API working when orbax is unavailable.

Crash safety (docs/fleet.md "trustworthy generations"): a canary-vs-
stable rollout is only meaningful when each replica group really runs
the generation it claims, so a torn or bit-flipped checkpoint must
fail LOUDLY at load, never deploy garbage:

- the npz payload is written to a temp path, fsync'd, and atomically
  renamed to a CONTENT-ADDRESSED name (``arrays-<digest>.npz``); the
  atomically replaced ``checkpoint_meta.json`` then names that payload
  — the meta replace is the commit point, so a crash anywhere mid-save
  leaves the previous meta pointing at the previous (still present)
  payload, never a new payload under an old manifest;
- :func:`save_sharded` writes a manifest (inside the meta) naming
  every array with its shape, dtype and — on the npz path, where the
  bytes are host-local — a SHA-256 content checksum;
- :func:`load_sharded` verifies the manifest: missing/extra arrays,
  shape/dtype drift, or a checksum mismatch raise
  :class:`CheckpointCorruptError`. (Orbax arrays may be device-sharded
  across hosts, so their manifest carries shape/dtype only — hashing
  would force the gather-to-host this module exists to avoid; orbax's
  own OCDBT format detects truncation.)

Pre-manifest checkpoints (version 1) load without verification, so
existing artifacts keep working.

Memory-mapped loading (``pio deploy --workers N``; docs/
serving-performance.md "Multi-process serving"): ``load_sharded(...,
mmap_mode="r")`` maps each npz member's raw ``.npy`` bytes straight out
of the page cache instead of copying them onto the heap. N prefork
worker processes that load the same checkpoint then *share* one
physical copy of the factor tables — the kernel backs every worker's
mapping with the same pages — so model memory is O(1) in workers
instead of O(N). ``PIO_CHECKPOINT_MMAP=r`` turns it on fleet-wide
without a code change (read per load call, never frozen at import).

Checksum-verification story under mmap: the sha256 content check reads
every byte, which would fault the whole file in and erase the laziness
the mapping exists for. The policy is **verify-once at save, verify
eagerly on integrity-suspect paths**: a mmap load verifies the
manifest's *shape/dtype* per array (header-only, O(arrays)) but skips
the content hash — the save path already fsync'd + atomically renamed
the content-addressed payload, so a torn write cannot be named by a
committed meta. Deployments that want the full content check (e.g.
after a disk scare) load eagerly (the default), which verifies every
checksum as before. Any mmap failure — compressed member, legacy
layout, filesystem without mmap — logs a warning and falls back to the
eager verified load; the knob can degrade, never brick a deploy.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Any, Mapping

import numpy as np

logger = logging.getLogger(__name__)

_ORBAX_SUBDIR = "orbax"
_META_FILE = "checkpoint_meta.json"
_NPZ_FILE = "arrays.npz"
_META_VERSION = 2


class CheckpointCorruptError(RuntimeError):
    """The persisted checkpoint fails integrity verification (torn
    write, bit flip, missing file). Callers must treat the checkpoint
    as unusable — the deploy path surfaces this instead of serving a
    silently wrong model."""


def _ocp():
    try:
        import orbax.checkpoint as ocp

        return ocp
    except Exception:  # pragma: no cover - orbax is baked into the image
        return None


def _array_meta(name: str, value: Any, checksum: bool) -> dict:
    meta: dict[str, Any] = {
        "shape": list(getattr(value, "shape", ())),
        "dtype": str(getattr(value, "dtype", "")),
    }
    if checksum:
        host = np.ascontiguousarray(np.asarray(value))
        meta["sha256"] = hashlib.sha256(host.tobytes()).hexdigest()
    return meta


def save_sharded(directory: str, arrays: Mapping[str, Any]) -> str:
    """Persist a flat {name: jax.Array|np.ndarray} mapping. Sharded
    arrays are written shard-locally by orbax; returns the backend used
    ("orbax" or "npz"). Crash-safe: see the module docstring."""
    os.makedirs(directory, exist_ok=True)
    ocp = _ocp()
    if ocp is not None:
        try:
            path = os.path.join(os.path.abspath(directory), _ORBAX_SUBDIR)
            with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
                ckptr.save(path, dict(arrays), force=True)
            # shape/dtype manifest only: hashing a sharded array would
            # gather it to host (module docstring)
            _write_meta(directory, "orbax", {
                name: _array_meta(name, v, checksum=False)
                for name, v in arrays.items()
            })
            return "orbax"
        except Exception as exc:
            logger.warning("orbax save failed (%s); falling back to npz", exc)
    manifest = {
        name: _array_meta(name, v, checksum=True)
        for name, v in arrays.items()
    }
    # content-addressed payload name: the meta (written LAST, replaced
    # atomically) is the commit point. A crash between payload and meta
    # leaves the previous meta naming the previous payload — which is
    # still on disk, because a new generation never overwrites it.
    digest = hashlib.sha256(json.dumps(manifest, sort_keys=True)
                            .encode()).hexdigest()[:16]
    payload_name = f"arrays-{digest}.npz"
    final = os.path.join(directory, payload_name)
    tmp = f"{final}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **{k: np.asarray(v) for k, v in arrays.items()})
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    _write_meta(directory, "npz", manifest, payload=payload_name)
    # the commit landed: previous generations' payloads are garbage now
    for stale in os.listdir(directory):
        if (stale.startswith("arrays-") and stale.endswith(".npz")
                and stale != payload_name) or stale == _NPZ_FILE:
            try:
                os.unlink(os.path.join(directory, stale))
            except OSError:
                pass
    return "npz"


def default_mmap_mode() -> str | None:
    """The fleet-wide mmap default: ``PIO_CHECKPOINT_MMAP`` set to
    ``r``/``1``/``true`` means read-only mapping, anything else (or
    unset) means eager copy-and-verify. Read at call time — the
    ServerConfig env discipline, never frozen at import."""
    raw = os.environ.get("PIO_CHECKPOINT_MMAP", "").strip().lower()
    if raw in ("r", "1", "true", "yes", "on"):
        return "r"
    return None


def _mmap_npz(path: str) -> dict[str, Any]:
    """Map every member of an uncompressed npz as a read-only
    ``np.memmap`` view into the archive file (module docstring). Raises
    on anything unexpected (compressed member, pickled object array,
    short file) — the caller falls back to the eager load."""
    import zipfile

    from numpy.lib import format as npy_format

    out: dict[str, Any] = {}
    with zipfile.ZipFile(path) as zf, open(path, "rb") as f:
        for info in zf.infolist():
            if info.compress_type != zipfile.ZIP_STORED:
                raise ValueError(
                    f"member {info.filename!r} is compressed; "
                    "mmap needs raw stored bytes")
            name = info.filename
            if name.endswith(".npy"):
                name = name[:-4]
            # the zip local file header is variable length: seek to
            # it, read the name/extra lengths, land on the .npy data
            f.seek(info.header_offset)
            local = f.read(30)
            if len(local) != 30 or local[:4] != b"PK\x03\x04":
                raise ValueError("torn local header")
            name_len = int.from_bytes(local[26:28], "little")
            extra_len = int.from_bytes(local[28:30], "little")
            f.seek(info.header_offset + 30 + name_len + extra_len)
            version = npy_format.read_magic(f)
            shape, fortran, dtype = npy_format._read_array_header(
                f, version)
            if dtype.hasobject:
                raise ValueError(
                    f"member {name!r} holds objects; not mappable")
            out[name] = np.memmap(
                path, dtype=dtype, mode="r", shape=shape,
                offset=f.tell(), order="F" if fortran else "C")
    return out


def load_sharded(
    directory: str,
    shardings: Mapping[str, Any] | None = None,
    mmap_mode: str | None = None,
) -> dict[str, Any]:
    """Restore a mapping saved by :func:`save_sharded`, verifying the
    integrity manifest when one exists (raises
    :class:`CheckpointCorruptError` on any mismatch).

    ``shardings`` optionally maps names to ``jax.sharding.Sharding``
    targets — orbax then materialises each array directly with that
    placement (shard-by-shard on multi-host meshes). Without it, arrays
    restore host-local.

    ``mmap_mode="r"`` (npz backend only) maps the arrays instead of
    copying them — the prefork-worker page-sharing path; shape/dtype
    still verify against the manifest but content checksums are skipped
    (module docstring has the verification trade-off). ``None`` defers
    to :func:`default_mmap_mode` (the ``PIO_CHECKPOINT_MMAP`` env);
    orbax checkpoints and device-sharded restores ignore it."""
    meta = _read_meta(directory)
    backend = meta.get("backend", "npz")
    manifest: Mapping[str, Any] | None = meta.get("arrays")
    if backend == "orbax":
        ocp = _ocp()
        if ocp is None:
            raise RuntimeError(
                f"checkpoint at {directory} was written by orbax, which is "
                "not importable here"
            )
        import jax

        path = os.path.join(os.path.abspath(directory), _ORBAX_SUBDIR)
        with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
            if shardings:
                ckpt_meta = ckptr.metadata(path)
                # orbax API drift: metadata() returns an object with
                # .item_metadata on older releases, a plain dict of
                # per-array metadata on newer ones
                items = getattr(ckpt_meta, "item_metadata", ckpt_meta)
                targets = {}
                for name, m in items.items():
                    sh = shardings.get(name)
                    if sh is not None:
                        targets[name] = jax.ShapeDtypeStruct(
                            m.shape, m.dtype, sharding=sh
                        )
                    else:
                        targets[name] = jax.ShapeDtypeStruct(m.shape, m.dtype)
                out = dict(ckptr.restore(path, targets))
            else:
                out = dict(ckptr.restore(path))
        _verify(directory, out, manifest, check_sums=False)
        return out
    payload_name = meta.get("payload", _NPZ_FILE)
    npz_path = os.path.join(directory, payload_name)
    if mmap_mode is None:
        mmap_mode = default_mmap_mode()
    if mmap_mode is not None:
        try:
            out = _mmap_npz(npz_path)
        except FileNotFoundError:
            raise CheckpointCorruptError(
                f"checkpoint at {directory} is missing {payload_name} — "
                "incomplete or deleted save") from None
        except Exception as exc:  # degrade to the eager verified load
            logger.warning(
                "mmap load of %s failed (%s); falling back to the "
                "eager copy-and-verify load", npz_path, exc)
        else:
            # header-only verification: the content hash would fault
            # the whole mapping in (module docstring)
            _verify(directory, out, manifest, check_sums=False)
            if shardings:
                import jax

                for name, sh in shardings.items():
                    if name in out:
                        out[name] = jax.device_put(out[name], sh)
            return out
    try:
        data = np.load(npz_path)
        out = {k: data[k] for k in data.files}
    except FileNotFoundError:
        raise CheckpointCorruptError(
            f"checkpoint at {directory} is missing {payload_name} — "
            "incomplete or deleted save") from None
    except Exception as exc:  # truncated/garbled zip payload
        raise CheckpointCorruptError(
            f"checkpoint at {directory} is unreadable ({exc}) — "
            "torn write or corruption") from exc
    _verify(directory, out, manifest, check_sums=True)
    if shardings:
        import jax

        for name, sh in shardings.items():
            if name in out:
                out[name] = jax.device_put(out[name], sh)
    return out


def _verify(directory: str, arrays: Mapping[str, Any],
            manifest: Mapping[str, Any] | None, check_sums: bool) -> None:
    """Arrays-vs-manifest integrity check; no-op for pre-manifest
    (version 1) checkpoints."""
    if manifest is None:
        return
    have, want = set(arrays), set(manifest)
    if have != want:
        raise CheckpointCorruptError(
            f"checkpoint at {directory} does not match its manifest: "
            f"missing {sorted(want - have)}, unexpected {sorted(have - want)}")
    for name, meta in manifest.items():
        value = arrays[name]
        if list(getattr(value, "shape", ())) != list(meta.get("shape", ())):
            raise CheckpointCorruptError(
                f"checkpoint array {name!r} at {directory} has shape "
                f"{list(value.shape)}, manifest says {meta.get('shape')}")
        if str(getattr(value, "dtype", "")) != meta.get("dtype", ""):
            raise CheckpointCorruptError(
                f"checkpoint array {name!r} at {directory} has dtype "
                f"{value.dtype}, manifest says {meta.get('dtype')}")
        expected = meta.get("sha256")
        if check_sums and expected:
            host = np.ascontiguousarray(np.asarray(value))
            actual = hashlib.sha256(host.tobytes()).hexdigest()
            if actual != expected:
                raise CheckpointCorruptError(
                    f"checkpoint array {name!r} at {directory} fails its "
                    f"content checksum — bit flip or torn write; refusing "
                    f"to load a corrupted model")


def _write_meta(directory: str, backend: str,
                arrays: Mapping[str, Any] | None = None,
                payload: str | None = None) -> None:
    # atomic + durable: a crash between the checkpoint write and the
    # meta landing must never leave a readable-but-stale meta; fsync
    # then os.replace so readers see either the old complete meta or
    # the new one
    path = os.path.join(directory, _META_FILE)
    tmp = f"{path}.tmp.{os.getpid()}"
    doc: dict[str, Any] = {"backend": backend, "version": _META_VERSION}
    if arrays is not None:
        doc["arrays"] = dict(arrays)
    if payload is not None:
        doc["payload"] = payload
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_meta(directory: str) -> dict:
    meta_path = os.path.join(directory, _META_FILE)
    if not os.path.exists(meta_path):
        # no meta: prefer a complete orbax checkpoint over legacy npz (a
        # crash after the orbax write but before the meta landed must not
        # silently resurrect a stale npz from an earlier save)
        if os.path.isdir(os.path.join(directory, _ORBAX_SUBDIR)):
            return {"backend": "orbax"}
        return {"backend": "npz"}
    try:
        with open(meta_path) as f:
            doc = json.load(f)
    except (json.JSONDecodeError, OSError) as exc:
        raise CheckpointCorruptError(
            f"checkpoint meta at {meta_path} is unreadable ({exc})") from exc
    if not isinstance(doc, dict):
        raise CheckpointCorruptError(
            f"checkpoint meta at {meta_path} is not a JSON object")
    return doc
