"""Sharded model checkpointing via orbax.

The reference cannot auto-persist distributed models — a PAlgorithm's
RDD model forces either a custom PersistentModel or a full retrain at
deploy (reference: core/.../controller/PAlgorithm.scala:89-125,
Engine.scala:211-229). Here mesh-sharded ``jax.Array`` models save as
orbax checkpoints: each host writes only its own shards (OCDBT), and
restore places shards straight back onto the target mesh — no
gather-to-host, no retrain-on-deploy, which is the SURVEY.md §7
"better than the reference" contract for sharded model persistence.

A plain-numpy fallback (`save_arrays`/`load_arrays`) keeps the same
directory API working when orbax is unavailable.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Mapping

import numpy as np

logger = logging.getLogger(__name__)

_ORBAX_SUBDIR = "orbax"
_META_FILE = "checkpoint_meta.json"


def _ocp():
    try:
        import orbax.checkpoint as ocp

        return ocp
    except Exception:  # pragma: no cover - orbax is baked into the image
        return None


def save_sharded(directory: str, arrays: Mapping[str, Any]) -> str:
    """Persist a flat {name: jax.Array|np.ndarray} mapping. Sharded
    arrays are written shard-locally by orbax; returns the backend used
    ("orbax" or "npz")."""
    os.makedirs(directory, exist_ok=True)
    ocp = _ocp()
    if ocp is not None:
        try:
            path = os.path.join(os.path.abspath(directory), _ORBAX_SUBDIR)
            with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
                ckptr.save(path, dict(arrays), force=True)
            _write_meta(directory, "orbax")
            return "orbax"
        except Exception as exc:
            logger.warning("orbax save failed (%s); falling back to npz", exc)
    np.savez(
        os.path.join(directory, "arrays.npz"),
        **{k: np.asarray(v) for k, v in arrays.items()},
    )
    _write_meta(directory, "npz")
    return "npz"


def load_sharded(
    directory: str,
    shardings: Mapping[str, Any] | None = None,
) -> dict[str, Any]:
    """Restore a mapping saved by :func:`save_sharded`.

    ``shardings`` optionally maps names to ``jax.sharding.Sharding``
    targets — orbax then materialises each array directly with that
    placement (shard-by-shard on multi-host meshes). Without it, arrays
    restore host-local."""
    backend = _read_meta(directory)
    if backend == "orbax":
        ocp = _ocp()
        if ocp is None:
            raise RuntimeError(
                f"checkpoint at {directory} was written by orbax, which is "
                "not importable here"
            )
        import jax

        path = os.path.join(os.path.abspath(directory), _ORBAX_SUBDIR)
        with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
            if shardings:
                meta = ckptr.metadata(path)
                targets = {}
                for name, m in meta.item_metadata.items():
                    sh = shardings.get(name)
                    if sh is not None:
                        targets[name] = jax.ShapeDtypeStruct(
                            m.shape, m.dtype, sharding=sh
                        )
                    else:
                        targets[name] = jax.ShapeDtypeStruct(m.shape, m.dtype)
                return dict(ckptr.restore(path, targets))
            return dict(ckptr.restore(path))
    data = np.load(os.path.join(directory, "arrays.npz"))
    out: dict[str, Any] = {k: data[k] for k in data.files}
    if shardings:
        import jax

        for name, sh in shardings.items():
            if name in out:
                out[name] = jax.device_put(out[name], sh)
    return out


def _write_meta(directory: str, backend: str) -> None:
    # atomic: a crash between the checkpoint write and the meta landing
    # must never leave a readable-but-stale meta; os.replace is atomic so
    # readers see either the old complete meta or the new one
    path = os.path.join(directory, _META_FILE)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"backend": backend, "version": 1}, f)
    os.replace(tmp, path)


def _read_meta(directory: str) -> str:
    meta_path = os.path.join(directory, _META_FILE)
    if not os.path.exists(meta_path):
        # no meta: prefer a complete orbax checkpoint over legacy npz (a
        # crash after the orbax write but before the meta landed must not
        # silently resurrect a stale npz from an earlier save)
        if os.path.isdir(os.path.join(directory, _ORBAX_SUBDIR)):
            return "orbax"
        return "npz"
    with open(meta_path) as f:
        return json.load(f).get("backend", "npz")
