"""Utility primitives: BiMap id indexing, JSON codecs, time helpers."""
