"""Host-platform helpers for virtual-mesh testing.

This box's sitecustomize registers a TPU backend and programmatically
sets jax_platforms, which beats JAX_PLATFORMS env config; tests and
dry-runs that need an n-device virtual CPU mesh must force the platform
back after import.
"""

from __future__ import annotations

import os


def force_cpu_devices(n: int) -> None:
    """Make jax see ``n`` virtual CPU devices, even if a TPU platform was
    pre-registered. Must run before any jax computation in this process
    (safe to call after `import jax`)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        from jax.extend.backend import clear_backends

        clear_backends()


def sqlite_supports_returning() -> bool:
    """Whether this interpreter's bundled SQLite understands the
    ``RETURNING`` clause (3.35.0+, 2021). The channels DAO — and the PG
    wire emulator, which is backed by the same library — issue
    ``INSERT ... RETURNING id``; containers shipping an older libsqlite
    cannot run those paths at all, so their tests capability-skip with
    this check instead of failing on a syntax error (a container
    artifact, not a regression)."""
    import sqlite3

    return sqlite3.sqlite_version_info >= (3, 35, 0)


def memory_storage():
    """A fresh all-in-memory Storage (the three repositories on the MEM
    source) — the standard test storage, analogous to the reference's
    `Storage.getLEvents(test=true)` test wiring."""
    from predictionio_tpu.storage.registry import Storage

    return Storage({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
